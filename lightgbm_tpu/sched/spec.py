"""Job-spec files for the scheduler (conf-flavored, like train.conf).

Top-level ``key = value`` lines before the first ``job =`` line are
scheduler knobs (``sched_quantum_chunks=``, ``sched_policy=``,
``compile_cache=``, ...) AND defaults inherited by every job.  Each
``job = NAME`` line opens a job section whose lines override the
defaults for that job only.  ``weight =`` inside a section sets the
job's fair-share weight (scheduler-level key, never a training param).

    sched_policy = fair
    sched_quantum_chunks = 2
    compile_cache = /tmp/shared_cache
    num_iterations = 30          # inherited default

    job = churn
    data = churn.csv
    objective = binary
    output_model = churn.txt
    weight = 2

    job = intent
    data = intent.csv
    objective = multiclass
    num_class = 3
    output_model = intent.txt

Driven by ``tools/submit_jobs.py`` and the CLI ``sched=`` entry point
(``python -m lightgbm_tpu sched=jobs.spec``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from ..config import Config, kv2map
from ..utils.log import LightGBMError
from .job import JobSpec

# keys the spec grammar consumes at the scheduler layer (everything
# else flows into job params / scheduler-knob params untouched)
_JOB_KEY = "job"
_WEIGHT_KEY = "weight"
# scheduler/global-only keys that must not leak into per-job configs
_SCHED_ONLY = frozenset([
    "sched", "sched_quantum_chunks", "sched_policy", "sched_max_jobs",
    "sched_health_out", "compile_cache", "fault_injection", "task",
    "config", "config_file",
])


def parse_spec_file(path: str) -> Tuple[Dict[str, str], List[JobSpec]]:
    """Parse one spec file into (scheduler params, job specs)."""
    if not os.path.exists(path):
        raise LightGBMError(f"sched spec file {path} doesn't exist")
    sched_params: Dict[str, str] = {}
    defaults: Dict[str, str] = {}
    jobs: List[JobSpec] = []
    current: Optional[Dict[str, str]] = None
    current_name = ""
    rel_dir = os.path.dirname(os.path.abspath(path))

    def _close_section() -> None:
        if current is None:
            return
        weight = float(current.pop(_WEIGHT_KEY, 1.0))
        params = {k: v for k, v in {**defaults, **current}.items()
                  if k not in _SCHED_ONLY}
        for key in ("data", "valid", "output_model", "input_model"):
            # paths resolve relative to the spec file, not the cwd
            val = params.get(key)
            if val and not os.path.isabs(str(val).split(",")[0]):
                params[key] = ",".join(
                    os.path.join(rel_dir, p) if p else p
                    for p in str(val).split(","))
        jobs.append(JobSpec(current_name, params, weight=weight))

    with open(path) as fh:
        for line in fh:
            kv: Dict[str, str] = {}
            kv2map(kv, line)
            if not kv:
                continue
            (key, value), = kv.items()
            if key == _JOB_KEY:
                _close_section()
                current, current_name = {}, value
                if not value:
                    raise LightGBMError(
                        f"{path}: 'job =' needs a name")
            elif current is not None:
                current[key] = value
            else:
                (sched_params if key in _SCHED_ONLY
                 else defaults)[key] = value
    _close_section()
    if not jobs:
        raise LightGBMError(f"{path}: no 'job =' sections found")
    seen = set()
    for spec in jobs:
        if spec.name in seen:
            raise LightGBMError(
                f"{path}: duplicate job name {spec.name!r}")
        seen.add(spec.name)
    return sched_params, jobs


def run_spec_file(path: str, overrides: Optional[Dict[str, Any]] = None,
                  **scheduler_kwargs) -> Dict[str, Any]:
    """Parse a spec file, build the scheduler, submit every job and
    run to completion; returns the ``sched_summary`` dict.  A job the
    admission check rejects outright is recorded (and its entry kept,
    state ``failed``) without aborting the siblings.  ``overrides``
    are CLI-level params that win over the spec's scheduler knobs."""
    from .scheduler import SchedAdmissionError, Scheduler

    sched_params, specs = parse_spec_file(path)
    merged = dict(sched_params)
    for k, v in (overrides or {}).items():
        if v not in (None, ""):
            merged[k] = v
    merged.pop("task", None)
    merged.pop("sched", None)
    config = Config.from_params(merged)
    sched = Scheduler.from_config(config, **scheduler_kwargs)
    rejected = []
    for spec in specs:
        try:
            sched.submit(spec)
        except SchedAdmissionError as e:
            rejected.append((spec.name, str(e)))
    out = sched.run()
    if rejected:
        out["rejected"] = {name: err for name, err in rejected}
    return out


__all__ = ["parse_spec_file", "run_spec_file"]
