"""Multi-tenant training-as-a-service scheduler (docs/SCHEDULING.md).

N independent training jobs cooperatively time-sliced on one device
set: chunk-boundary preemption, byte-exact snapshot/restore of
descheduled tenants, working-set admission control against the HBM
budget, a shared persistent compile cache across tenants, and a
per-scheduler JSONL health stream with fairness and queue-latency
accounting (``tools/sched_monitor.py`` renders it,
``tools/submit_jobs.py`` drives it from a spec file).
"""

from .job import Job, JobSpec, peek_data_shape
from .scheduler import POLICIES, SchedAdmissionError, Scheduler
from .spec import parse_spec_file, run_spec_file

__all__ = ["Job", "JobSpec", "Scheduler", "SchedAdmissionError",
           "POLICIES", "parse_spec_file", "run_spec_file",
           "peek_data_shape"]
