"""scikit-learn compatible API.

Reference: python-package/lightgbm/sklearn.py:168-879 — LGBMModel base with
get/set_params, fit with eval sets / early stopping / sample weights, and
the Classifier/Regressor/Ranker specializations (label encoding, predict /
predict_proba, query groups).  Works with or without scikit-learn installed
(duck-typed mixins like the reference's compat shims).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .engine import train as train_fn
from .utils.log import LightGBMError, log_warning

try:  # pragma: no cover - sklearn is optional
    from sklearn.base import BaseEstimator as _SKBase

    class _Base(_SKBase):
        pass
except Exception:  # pragma: no cover
    class _Base:
        def get_params(self, deep=True):
            params = {}
            for k, v in self.__dict__.items():
                if not k.endswith("_") and not k.startswith("_"):
                    params[k] = v
            return params

        def set_params(self, **params):
            for k, v in params.items():
                setattr(self, k, v)
            return self


class LGBMModel(_Base):
    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self._evals_result = None
        self._best_iteration = -1
        self._objective = objective

    def get_params(self, deep=True):
        params = super().get_params(deep=deep) if hasattr(
            super(), "get_params") else {}
        if not params:
            params = {k: getattr(self, k) for k in (
                "boosting_type num_leaves max_depth learning_rate "
                "n_estimators subsample_for_bin objective class_weight "
                "min_split_gain min_child_weight min_child_samples subsample "
                "subsample_freq colsample_bytree reg_alpha reg_lambda "
                "random_state n_jobs silent importance_type").split()}
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if not hasattr(type(self), key):
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _train_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self._objective or self._default_objective(),
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            p["seed"] = int(self.random_state)
        p.update(self._other_params)
        return p

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None):
        X = np.asarray(X, dtype=np.float64) if not hasattr(X, "columns") else X
        self._n_features = (X.shape[1] if hasattr(X, "shape")
                            else len(X.columns))
        y_arr = self._process_label(np.asarray(y).ravel())
        # params resolved AFTER label processing so n_classes is known
        params = self._train_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        if sample_weight is not None:
            sample_weight = np.asarray(sample_weight, dtype=np.float64)
        sample_weight = self._apply_class_weight(y_arr, sample_weight)
        train_set = Dataset(X, y_arr, weight=sample_weight, group=group,
                            init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vX, vy) in enumerate(eval_set):
                if vX is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                valid_sets.append(Dataset(
                    vX, self._process_label(np.asarray(vy).ravel(),
                                            fit=False),
                    reference=train_set, weight=vw, group=vg, init_score=vi))
        self._evals_result = {}
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            callbacks=callbacks, init_model=init_model)
        self._best_iteration = self._Booster.best_iteration
        return self

    def _process_label(self, y, fit=True):
        return y.astype(np.float64)

    def _apply_class_weight(self, y, sample_weight):
        return sample_weight

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, "
                                "call fit before exploiting the model.")
        return self._Booster.predict(
            X, raw_score=raw_score,
            num_iteration=num_iteration if num_iteration is not None else -1,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. "
                                "Need to call fit beforehand.")
        return self._Booster

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def n_features_(self):
        return self._n_features

    @property
    def feature_importances_(self):
        return self.booster_.feature_importance(self.importance_type)

    @property
    def best_score_(self):
        return self.booster_.best_score


class LGBMRegressor(LGBMModel):
    def _default_objective(self):
        return "regression"


class LGBMClassifier(LGBMModel):
    def _default_objective(self):
        if self._n_classes is not None and self._n_classes > 2:
            return "multiclass"
        return "binary"

    def _process_label(self, y, fit=True):
        if fit:
            self._classes = np.unique(y)
            self._n_classes = len(self._classes)
            if self._n_classes > 2:
                if self._objective is None:
                    self._other_params.setdefault("num_class",
                                                  self._n_classes)
        self._label_map = {c: i for i, c in enumerate(self._classes)}
        return np.asarray([self._label_map[v] for v in y], dtype=np.float64)

    def _apply_class_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        if self.class_weight == "balanced":
            counts = np.bincount(y.astype(int))
            weights_per_class = len(y) / (len(counts) * np.maximum(counts, 1))
            cw = weights_per_class[y.astype(int)]
        else:
            cw = np.asarray([self.class_weight.get(self._classes[int(v)], 1.0)
                             for v in y])
        if sample_weight is None:
            return cw
        return sample_weight * cw

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration, pred_leaf,
                                    pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim > 1:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(int)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = super().predict(X, raw_score, num_iteration, pred_leaf,
                                 pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        eval_group = kwargs.get("eval_group")
        if kwargs.get("eval_set") is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        return super().fit(X, y, sample_weight=sample_weight,
                           init_score=init_score, group=group, **kwargs)
