"""Serve-side health stream: the serving half of the run-health layer.

A long-lived :class:`~lightgbm_tpu.serve.ServeSession` opened with
``serve_health_out=`` (env ``LIGHTGBM_TPU_SERVE_HEALTH_JSONL`` wins)
appends ``lightgbm_tpu.health/v1`` records through the SAME never-torn
``O_APPEND`` writer training uses (utils/telemetry.HealthStream) — but
into its OWN stream instance, so serving can never interleave with (or
truncate) a training run's health file.  Record kinds:

  * ``serve_start`` — stream opened: pid, knobs (max_batch,
    max_delay_ms, window period).
  * ``serve_window`` — one per ``serve_health_window_s`` seconds while
    the session lives: request/batch/row counts and QPS for the window,
    per-stage latency p50/p99 (``t_queue``/``t_coalesce``/
    ``t_dispatch``/``t_reply``) and end-to-end p50/p99, the coalesce
    fill ratio (rows per batch / max_batch), pad ratio, current queue
    depth, per-model row counts, and the HBM gauge when the backend
    reports one.  Idle windows are still written (qps 0) so a wedged
    server is distinguishable from an idle one.
  * ``serve_admit`` — mirror of every registry admission decision
    (admitted / rejected / evicted, full detail string).
  * ``serve_drift`` — one per model with new traffic at each window
    close, when the session runs with ``drift_detect=true``
    (obs/drift.py): cumulative rows observed, per-feature PSI of the
    served bin occupancy vs the model's training baseline (top-K
    drifting features by name), the raw-score Jensen–Shannon shift,
    the gate threshold and the ``drifted`` verdict.
  * ``serve_fault`` — a dispatch error, injected fault or predictor
    exception that failed request futures (including the OOM-ladder
    retries, queued requests failed by an evict, and a worker found
    wedged at close).
  * ``swap_begin`` / ``swap_rejected`` / ``swap_flip`` / ``swap_done``
    — the hot-swap lifecycle (serve/registry.py): candidate built off
    to the side, quality-gate verdict, the atomic flip with its
    measured pause, completion (``rollback: true`` variants for
    ``ModelRegistry.rollback``).
  * ``serve_refit`` — one refit-loop attempt (serve/refit_loop.py):
    status swapped / rejected / fault.
  * ``serve_summary`` — terminal record from ``close()``: lifetime
    totals, shed submits, pending futures failed at close.  Its
    presence is what separates an aborted-but-orderly server from a
    wedged one.

``serve_window`` records additionally carry ``shed_requests`` /
``shed_rows`` for any window in which the bounded queue
(``serve_max_queue_rows``) shed load.

Consume live with ``tools/serve_monitor.py`` (mirrors run_monitor).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from ..utils.telemetry import TELEMETRY, HealthStream

SERVE_HEALTH_ENV = "LIGHTGBM_TPU_SERVE_HEALTH_JSONL"
# bound on the per-window stage sample lists: a window at extreme QPS
# keeps exact counts but quantiles come from the newest samples
WINDOW_SAMPLE_CAPACITY = 8192

# lifecycle stage keys, in request order (also the record_dispatch
# label suffixes used by serve/queue.py)
STAGES = ("t_queue", "t_coalesce", "t_dispatch", "t_reply")


def resolve_serve_health_path(config=None, override: str = "") -> str:
    """Serve stream destination: env wins over the ``serve_health_out``
    config parameter / keyword override; "" = no stream."""
    env = os.environ.get(SERVE_HEALTH_ENV, "")
    if env:
        return env
    if override:
        return str(override)
    if config is not None:
        return str(getattr(config, "serve_health_out", "") or "")
    return ""


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])


class _Window:
    """Accumulators for one serve_window period (reset each emit)."""

    def __init__(self) -> None:
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.padded = 0
        self.dispatch_rows = 0      # rows through the compiled path
        self.shed_requests = 0      # submits rejected by load shedding
        self.shed_rows = 0
        self.e2e: List[float] = []
        self.stages: Dict[str, List[float]] = {s: [] for s in STAGES}
        self.model_rows: Dict[str, int] = defaultdict(int)

    def _keep(self, samples: List[float], vals) -> None:
        samples.extend(vals)
        if len(samples) > WINDOW_SAMPLE_CAPACITY:
            del samples[: len(samples) - WINDOW_SAMPLE_CAPACITY]


class ServeHealth:
    """One serve session's health stream + periodic window emitter.

    The window emitter is a daemon thread bounded by ``close()`` — it
    can never outlive the session, and ``close()`` flushes one final
    (partial) window before the ``serve_summary`` so short-lived
    sessions still report their traffic."""

    def __init__(self, path: str, window_s: float = 5.0,
                 meta: Optional[Dict[str, Any]] = None):
        self.path = path
        self.window_s = max(float(window_s), 0.05)
        self._lock = threading.Lock()
        self._win = _Window()
        self._win_t0 = time.perf_counter()
        # lifetime totals for the serve_summary record
        self._total = defaultdict(int)
        self._closed = False
        self.drift = None       # obs/drift.DriftAccumulator, session-wired
        self._stream = HealthStream()
        rec: Dict[str, Any] = {"stream": "serve",
                               "window_s": round(self.window_s, 3)}
        if meta:
            rec.update(meta)
        self._stream.open(path, meta=rec, start_kind="serve_start")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="serve-health",
                                        daemon=True)
        self._thread.start()

    @property
    def active(self) -> bool:
        return self._stream.active

    # ------------------------------------------------------------ feeds
    def note_request(self, model_id: str, rows: int,
                     stages: Dict[str, float], e2e_s: float) -> None:
        """One replied request: its per-stage walls and end-to-end
        latency (serve/queue.py calls this as it resolves futures)."""
        with self._lock:
            w = self._win
            w.requests += 1
            w.rows += int(rows)
            w.model_rows[model_id] += int(rows)
            w._keep(w.e2e, (float(e2e_s),))
            for k, v in stages.items():
                if k in w.stages:
                    w._keep(w.stages[k], (float(v),))
            self._total["requests"] += 1
            self._total["rows"] += int(rows)

    def note_dispatch(self, model_id: str, rows: int, padded: int,
                      bucket: int) -> None:
        """One compiled dispatch (serve/predictor.py): real rows, pad
        rows and the bucket it compiled/ran under."""
        with self._lock:
            w = self._win
            w.batches += 1
            w.dispatch_rows += int(rows)
            w.padded += int(padded)
            self._total["batches"] += 1

    def note_shed(self, rows: int) -> None:
        """One submit shed by the bounded queue (overload or an armed
        ``serve/shed`` fault); counted into the current window and the
        lifetime totals."""
        with self._lock:
            self._win.shed_requests += 1
            self._win.shed_rows += int(rows)
            self._total["shed_requests"] += 1
            self._total["shed_rows"] += int(rows)

    def event(self, kind: str, fields: Optional[Dict[str, Any]] = None,
              ) -> None:
        """A serve_admit / serve_fault record, written immediately."""
        self._stream.record(kind, fields)
        if kind == "serve_fault":
            with self._lock:
                self._total["faults"] += 1

    # ---------------------------------------------------------- windows
    def _snapshot_window(self):
        """Swap the live window for a fresh one; returns the finished
        window and its wall span."""
        with self._lock:
            w, self._win = self._win, _Window()
            t0, self._win_t0 = self._win_t0, time.perf_counter()
        return w, max(self._win_t0 - t0, 1e-9)

    def _window_record(self, w: _Window, span_s: float,
                       max_batch: Optional[int] = None,
                       ) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "span_s": round(span_s, 3),
            "requests": w.requests,
            "rows": w.rows,
            "batches": w.batches,
            "qps": round(w.requests / span_s, 3),
            "rows_per_s": round(w.rows / span_s, 1),
        }
        if w.batches:
            rec["rows_per_batch"] = round(w.dispatch_rows / w.batches, 3)
            denom = w.dispatch_rows + w.padded
            rec["pad_ratio"] = round(w.padded / max(denom, 1), 6)
            cap = max_batch or TELEMETRY.gauge_get("serve/max_batch")
            if cap:
                # the coalescing knob's measured effect: how full the
                # window's average dispatch ran vs the coalescing cap
                rec["fill_ratio"] = round(
                    w.dispatch_rows / w.batches / float(cap), 6)
        if w.shed_requests:
            rec["shed_requests"] = w.shed_requests
            rec["shed_rows"] = w.shed_rows
        if w.e2e:
            lat = sorted(w.e2e)
            rec["p50_s"] = round(_quantile(lat, 0.50), 9)
            rec["p99_s"] = round(_quantile(lat, 0.99), 9)
        stages = {}
        for name, vals in w.stages.items():
            if vals:
                sv = sorted(vals)
                stages[name] = {"p50_s": round(_quantile(sv, 0.50), 9),
                                "p99_s": round(_quantile(sv, 0.99), 9)}
        if stages:
            rec["stages"] = stages
        if w.model_rows:
            rec["models"] = dict(w.model_rows)
        depth = TELEMETRY.gauge_get("serve/queue_depth")
        if depth is not None:
            rec["queue_depth"] = int(depth)
        slack = TELEMETRY.gauge_get("serve/coalesce_slack_ms")
        if slack is not None:
            rec["coalesce_slack_ms"] = round(float(slack), 3)
        hbm = TELEMETRY.memory_gauges()
        if hbm:
            rec["hbm"] = hbm
        return rec

    def emit_window(self, max_batch: Optional[int] = None) -> None:
        w, span = self._snapshot_window()
        self._stream.record("serve_window",
                            self._window_record(w, span, max_batch))
        if self.drift is not None:
            # drift rides the window cadence: one serve_drift record
            # per model with new rows since the last emission, plus
            # the serve/drift_psi_max and serve/score_js gauges
            for rec in self.drift.window_records():
                self._stream.record("serve_drift", rec)

    def _run(self) -> None:
        while not self._stop.wait(self.window_s):
            try:
                self.emit_window()
            except Exception:       # a reporting bug must not kill serve
                return

    # ---------------------------------------------------------- closing
    def close(self, pending_failed: int = 0,
              extra: Optional[Dict[str, Any]] = None) -> None:
        """Flush the final partial window, write ``serve_summary`` and
        release the descriptor.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self.emit_window()
        except Exception:
            pass
        with self._lock:
            rec: Dict[str, Any] = {
                "requests": self._total["requests"],
                "rows": self._total["rows"],
                "batches": self._total["batches"],
                "faults": self._total["faults"],
                "shed_requests": self._total["shed_requests"],
                "pending_failed": int(pending_failed),
            }
        if extra:
            rec.update(extra)
        self._stream.record("serve_summary", rec)
        self._stream.close(summary=False)
