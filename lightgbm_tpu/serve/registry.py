"""Multi-model hosting under an HBM budget.

Every resident model's tree stack and binning tables are packed into
ONE set of shared device buffers (``[M, T, nodes]`` / ``[M, F, len]``,
models padded to the pack maxima) so residency is a single accountable
allocation.  Admission mirrors the training-side out-of-core check
(``GBDT._resolve_data_tier``): the hypothetical packed working set —
pack bytes + the largest compiled-executable working set on record +
the request activation for one max-size batch — is compared against the
device allocator's reported capacity (``TELEMETRY.device_memory_budget``)
BEFORE anything is uploaded.  Every decision lands in the telemetry
faults section as a ``serve_admit`` event; a rejection raises
:class:`ServeAdmissionError` naming the budget, the shortfall and the
current residents so the operator knows exactly what to evict.

Backends without allocator stats (CPU) admit everything, same as the
training check.

Hot swap (``swap()``) replaces one resident model with ZERO downtime:
the replacement stack and tables are built off to the side while the
old pack keeps serving, an optional quality gate shadow-scores the
candidate, and the flip is one pointer exchange under the lock.  Two
versioning planes make that cheap:

  * ``pack_version`` — global; bumped on load/evict (and on a swap
    whose candidate does not fit the current pack padding), which
    rebuilds the pack and invalidates EVERY compiled serve executable.
  * per-model ``epoch`` — bumped only for the swapped id; when the
    candidate fits the current pack maxima the swapped row is updated
    functionally (same shapes, new arrays) so untouched residents'
    executables stay valid and are never retraced.

In-flight requests are version-pinned: ``snapshot()`` hands the
predictor one consistent ``(entry, row, epoch, pack)`` view, and the
old device arrays stay alive (functional update) until the last
dispatched batch against them completes — there is no reject window.
The previous generation is retained for a one-call ``rollback()``,
and the whole lifecycle lands as ``swap_begin``/``swap_rejected``/
``swap_flip``/``swap_done`` health records with the measured pause.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..models.device_predict import stack_trees_host
from ..utils.faults import FAULTS, InjectedFault
from ..utils.log import LightGBMError
from ..utils.telemetry import TELEMETRY
from .binning import _CAT_PAD, build_tables, tables_nbytes

# same headroom fraction as the training admission check (models/gbdt.py)
SERVE_ADMIT_FRACTION = 0.9
# bounded deterministic reservoir of recently served request rows per
# model — the default shadow-scoring holdout for the swap quality gate
REPLAY_RESERVOIR = 512


class ServeError(LightGBMError):
    """Base error for the prediction service."""


class ServeAdmissionError(ServeError):
    """A model load would not fit under the device HBM budget."""


class ServeOverloadError(ServeError):
    """A submit was shed because the queue is at serve_max_queue_rows."""


class SwapRejectedError(ServeError):
    """A hot swap was rejected (quality gate, admission or injected
    fault at the flip); the previous model keeps serving."""


class ResidentModel:
    """Host-side state of one admitted model (device state lives in the
    shared pack)."""

    __slots__ = ("model_id", "trees", "num_tree_per_iteration",
                 "init_scores", "objective", "max_feature_idx",
                 "average_output", "tables", "stack", "max_depth",
                 "nbytes", "baseline", "leaf_values")

    def __init__(self, model_id, trees, num_tree_per_iteration, init_scores,
                 objective, max_feature_idx, average_output, tables, stack,
                 max_depth, nbytes):
        self.baseline = None          # obs/drift.ModelBaseline when the
                                      # session runs with drift_detect
        self.model_id = model_id
        self.trees = trees
        self.num_tree_per_iteration = num_tree_per_iteration
        self.init_scores = init_scores
        self.objective = objective
        self.max_feature_idx = max_feature_idx
        self.average_output = average_output
        self.tables = tables          # host numpy binning tables
        self.stack = stack            # host numpy tree-stack fields
        self.max_depth = max_depth
        self.nbytes = nbytes          # unpadded host bytes (reporting)
        # per-tree float64 leaf values SNAPSHOT at load/swap time: the
        # predictor gathers from these, never from the live tree
        # objects, so an in-place ``Booster.refit`` of the source
        # booster cannot perturb serving mid-flight — the refitted
        # values only go live through the atomic swap
        self.leaf_values = [np.array(t.leaf_value, dtype=np.float64)
                            for t in trees]

    def dims(self):
        """(T, maxnodes, F, bounds_len, cat_len) this entry needs in
        the shared pack."""
        return (self.stack[0].shape[0], self.stack[0].shape[1],
                self.tables["src_col"].shape[0],
                self.tables["bounds"].shape[1],
                self.tables["cat_vals"].shape[1])


class PackSnapshot:
    """One consistent view of a model for the whole lifetime of a
    dispatched request: the entry, its pack row, its epoch and the
    device pack it was built against.  A swap that flips mid-request
    cannot mix generations — the old arrays stay alive until the last
    snapshot holding them is dropped."""

    __slots__ = ("model_id", "entry", "row", "epoch", "pack",
                 "pack_version")

    def __init__(self, model_id, entry, row, epoch, pack, pack_version):
        self.model_id = model_id
        self.entry = entry
        self.row = row
        self.epoch = epoch
        self.pack = pack
        self.pack_version = pack_version


class _ReplayReservoir:
    """Deterministic bounded reservoir of served request rows."""

    __slots__ = ("rows", "seen", "rng", "cap")

    def __init__(self, cap: int, seed: int):
        self.rows: List[np.ndarray] = []
        self.seen = 0
        self.rng = random.Random(seed)
        self.cap = int(cap)

    def note(self, X: np.ndarray) -> None:
        for i in range(X.shape[0]):
            self.seen += 1
            if len(self.rows) < self.cap:
                self.rows.append(np.array(X[i]))
            else:
                j = self.rng.randrange(self.seen)
                if j < self.cap:
                    self.rows[j] = np.array(X[i])

    def sample(self) -> Optional[np.ndarray]:
        if not self.rows:
            return None
        return np.stack(self.rows)


def _extract(booster, num_iteration: int = -1) -> tuple:
    """(trees, mappers, used_indices, C, init_scores, objective,
    max_feature_idx, average_output) of a Booster, validated for binned
    serving."""
    gbdt = booster.gbdt
    if hasattr(gbdt, "_flush_pending"):
        gbdt._flush_pending()
    C = gbdt.num_tree_per_iteration
    n_iter = len(gbdt.models) // max(C, 1)
    if num_iteration is None or num_iteration < 0:
        num_iteration = (booster.best_iteration
                         if booster.best_iteration > 0 else n_iter)
    n_iter = min(max(num_iteration, 0), n_iter) or n_iter
    trees = list(gbdt.models[: n_iter * C])
    if not trees:
        raise ServeError("cannot serve a model with no trees")
    for i, t in enumerate(trees):
        if not getattr(t, "bins_aligned", True):
            raise ServeError(
                f"tree {i} was loaded from a model file and its bin "
                f"thresholds are not aligned with any dataset; load the "
                f"model into a training-capable booster "
                f"(serialization.load_trees_into) before serving")
    ds = getattr(gbdt, "train_set", None)
    if ds is None or not getattr(ds, "bin_mappers", None):
        raise ServeError(
            "serving needs the model's BinMappers for on-device binning; "
            "this booster carries no training dataset (file-loaded "
            "models must be re-bound to a dataset first)")
    return (trees, ds.bin_mappers, ds.used_feature_indices, C,
            list(gbdt.init_scores), booster.objective,
            gbdt.max_feature_idx, bool(getattr(gbdt, "average_output",
                                               False)))


# (field, dtype, pad value) of the packed tree stack; leaf values stay on
# the host (the predictor gathers them in float64 for bit-parity with the
# host walk), so they are deliberately NOT part of the device pack
_STACK_FIELDS = (
    ("split_feature", np.int32, 0),
    ("threshold_bin", np.int32, 0),
    ("decision_type", np.int32, 0),
    ("left_child", np.int32, -1),
    ("right_child", np.int32, -1),
    ("cat_bitset", np.uint32, 0),
    ("num_leaves", np.int32, 1),
)

_STACK_SLOT = {"split_feature": 0, "threshold_bin": 1, "decision_type": 2,
               "left_child": 3, "right_child": 4, "cat_bitset": 5,
               "num_leaves": 7}

_TABLE_PADS = {"src_col": 0, "bounds": np.inf, "num_bin": 1,
               "default_bin": 0, "missing_type": 0, "is_cat": False,
               "cat_vals": _CAT_PAD, "cat_bins": 0}


def _build_entry(booster, model_id: str, num_iteration: int
                 ) -> ResidentModel:
    """Host-side ResidentModel for one booster — the expensive part of
    load/swap, deliberately lock-free."""
    (trees, mappers, used, C, init_scores, objective, max_fi,
     avg_out) = _extract(booster, num_iteration)
    tables = build_tables(mappers, used)
    stack = stack_trees_host(trees, len(used))
    max_depth = stack[-1]
    nbytes = (sum(int(np.asarray(a).nbytes) for a in stack[:-1])
              + tables_nbytes(tables))
    return ResidentModel(model_id, trees, C, init_scores, objective,
                         max_fi, avg_out, tables, stack[:-1], max_depth,
                         nbytes)


class ModelRegistry:
    """Admission-checked residency of N models in shared device buffers.

    ``pack()`` returns the current device arrays; ``pack_version``
    changes whenever they are rebuilt (load/evict), which invalidates
    every compiled serve executable that closed over the previous
    shapes.  ``epoch_of()`` changes only for a hot-swapped id — the
    predictor re-keys on (version, epoch), so a swap invalidates the
    swapped model's executables and nobody else's.
    """

    def __init__(self, max_batch: int = 256,
                 admit_fraction: float = SERVE_ADMIT_FRACTION):
        self._lock = threading.RLock()
        self._models: Dict[str, ResidentModel] = {}
        self._order: List[str] = []          # pack row per model_id
        self._pack = None                    # device arrays, lazily built
        self.pack_version = 0
        self._epochs: Dict[str, int] = {}    # per-model swap generation
        self._retained: Dict[str, ResidentModel] = {}   # rollback target
        self._replay: Dict[str, _ReplayReservoir] = {}
        self.swap_pauses: List[float] = []   # flip lock-hold seconds
        self.max_batch = int(max_batch)
        self.admit_fraction = float(admit_fraction)
        self.health = None      # serve/health.ServeHealth, session-wired
        self.drift = None       # obs/drift.DriftAccumulator, session-wired

    def _admit_record(self, detail: str) -> None:
        """Every admission decision lands in the telemetry faults section
        AND (when the session streams health) as a serve_admit record."""
        TELEMETRY.fault_event("serve_admit", site="serve/admit",
                              detail=detail)
        if self.health is not None:
            self.health.event("serve_admit", {"detail": detail})

    def _swap_event(self, kind: str, model_id: str, fields: dict) -> None:
        """Swap lifecycle records ride the same two channels as
        admission decisions: the telemetry faults section and the serve
        health stream."""
        detail = ", ".join(f"{k}={v}" for k, v in fields.items())
        TELEMETRY.fault_event(kind, site="serve/swap",
                              detail=f"{model_id}: {detail}")
        if self.health is not None:
            self.health.event(kind, {"model": model_id, **fields})

    # ------------------------------------------------------------ loading
    def load(self, booster, model_id: Optional[str] = None,
             num_iteration: int = -1) -> str:
        """Admit one Booster; returns its model_id.  Raises
        :class:`ServeAdmissionError` when the packed working set would
        exceed the HBM budget."""
        with self._lock:
            if model_id is None:
                model_id = f"model{len(self._order)}"
            if model_id in self._models:
                raise ServeError(f"model_id {model_id!r} is already "
                                 f"resident; evict it first")
        entry = _build_entry(booster, model_id, num_iteration)
        with self._lock:
            if model_id in self._models:
                raise ServeError(f"model_id {model_id!r} is already "
                                 f"resident; evict it first")
            self._admit_or_raise(entry)
            if self.drift is not None:
                # training baseline rides next to the pack: fine bin
                # occupancy of the Dataset's binned matrix + the
                # raw-score quantile digest the drift windows compare
                # against (host numpy; nothing extra uploaded)
                from ..obs.drift import extract_baseline
                entry.baseline = extract_baseline(booster)
                self.drift.register(model_id, entry.baseline)
            self._models[model_id] = entry
            self._order.append(model_id)
            self._epochs.setdefault(model_id, 0)
            self._replay.setdefault(
                model_id, _ReplayReservoir(
                    REPLAY_RESERVOIR, seed=hash(model_id) & 0x7FFFFFFF))
            self._pack = None
            self.pack_version += 1
            return model_id

    def evict(self, model_id: str) -> None:
        with self._lock:
            if model_id not in self._models:
                raise ServeError(f"model_id {model_id!r} is not resident")
            del self._models[model_id]
            self._order.remove(model_id)
            self._retained.pop(model_id, None)
            self._replay.pop(model_id, None)
            self._epochs.pop(model_id, None)
            if self.drift is not None:
                self.drift.forget(model_id)
            self._pack = None
            self.pack_version += 1
            self._admit_record(
                f"evicted {model_id}; residents="
                f"{','.join(self._order) or '<none>'}")

    # ----------------------------------------------------------- hot swap
    def swap(self, model_id: str, booster, num_iteration: int = -1,
             gate=None) -> float:
        """Atomically replace a resident model with ``booster``.

        The replacement pack row and binning tables are built while the
        old model keeps serving; ``gate(candidate_entry)`` (optional)
        then shadow-scores the candidate and returns ``(ok, detail)``
        — a failing gate, a failing admission check or an armed
        ``serve/swap`` fault raises :class:`SwapRejectedError` with the
        old model untouched.  On success the previous generation is
        retained for :meth:`rollback` and the flip pause (lock-hold
        seconds) is returned.  When the candidate fits the current pack
        padding only the swapped id's epoch changes, so untouched
        residents' compiled executables survive.
        """
        with self._lock:
            if model_id not in self._models:
                raise ServeError(
                    f"model_id {model_id!r} is not resident; loaded: "
                    f"{', '.join(self._order) or '<none>'}")
        entry = _build_entry(booster, model_id, num_iteration)
        self._swap_event("swap_begin", model_id, {
            "trees": len(entry.trees), "nbytes": entry.nbytes})
        with self._lock:
            others = [self._models[m] for m in self._order
                      if m != model_id]
        try:
            self._admit_or_raise(entry, others=others, verb="swap")
        except ServeAdmissionError as exc:
            self._reject_swap(model_id, f"admission failed: {exc}")
        if gate is not None:
            ok, detail = gate(entry)
            if not ok:
                self._reject_swap(model_id, detail)
        try:
            FAULTS.maybe_raise(
                "serve/swap",
                lambda site: InjectedFault(
                    site, f"injected fault at {site}: hot-swap flip "
                          f"for {model_id} aborted"))
        except InjectedFault as exc:
            self._reject_swap(model_id, str(exc))
        baseline = None
        if self.drift is not None:
            from ..obs.drift import extract_baseline
            baseline = extract_baseline(booster)
        pause, rebuilt, epoch = self._flip(model_id, entry, baseline)
        self._swap_event("swap_flip", model_id, {
            "pause_ms": round(pause * 1e3, 3), "epoch": epoch,
            "pack_rebuild": rebuilt})
        TELEMETRY.counter_add("serve/swaps")
        self._swap_event("swap_done", model_id, {
            "epoch": epoch, "trees": len(entry.trees),
            "pause_ms": round(pause * 1e3, 3)})
        return pause

    def rollback(self, model_id: str) -> float:
        """Restore the generation retained by the last successful
        ``swap()`` — the same atomic flip, in reverse.  Returns the
        flip pause; raises :class:`ServeError` when there is nothing
        retained to roll back to."""
        with self._lock:
            if model_id not in self._models:
                raise ServeError(f"model_id {model_id!r} is not resident")
            prev = self._retained.get(model_id)
        if prev is None:
            raise ServeError(
                f"no retained previous generation for {model_id!r}; "
                f"rollback is available after a successful swap")
        pause, rebuilt, epoch = self._flip(model_id, prev, prev.baseline)
        self._swap_event("swap_flip", model_id, {
            "pause_ms": round(pause * 1e3, 3), "epoch": epoch,
            "pack_rebuild": rebuilt, "rollback": True})
        TELEMETRY.counter_add("serve/rollbacks")
        self._swap_event("swap_done", model_id, {
            "epoch": epoch, "rollback": True,
            "pause_ms": round(pause * 1e3, 3)})
        return pause

    def _reject_swap(self, model_id: str, reason: str) -> None:
        TELEMETRY.counter_add("serve/swap_rejected")
        self._swap_event("swap_rejected", model_id, {"reason": reason})
        raise SwapRejectedError(
            f"hot swap of {model_id!r} rejected: {reason}; the previous "
            f"model keeps serving")

    def _flip(self, model_id: str, entry: ResidentModel,
              baseline) -> tuple:
        """The one-step pointer exchange: swap ``entry`` in for the
        current generation of ``model_id``.  Returns (pause_seconds,
        pack_rebuilt, new_epoch)."""
        row_update = None
        with self._lock:
            pack_ref = self._pack
            if pack_ref is not None:
                dims = (pack_ref["split_feature"].shape[1],
                        pack_ref["split_feature"].shape[2],
                        pack_ref["tab_bounds"].shape[1],
                        pack_ref["tab_bounds"].shape[2],
                        pack_ref["tab_cat_vals"].shape[2])
        if pack_ref is not None and \
                all(n <= d for n, d in zip(entry.dims(), dims)):
            # candidate fits the live padding: build the padded host
            # row off-lock, update functionally under the lock — same
            # shapes, so untouched executables are never invalidated
            row_update = self._pack_row(entry, dims)
        t0 = time.perf_counter()
        with self._lock:
            old = self._models[model_id]
            m = self._order.index(model_id)
            if row_update is not None and self._pack is pack_ref:
                import jax.numpy as jnp
                new_pack = dict(pack_ref)
                for name, buf in row_update.items():
                    new_pack[name] = new_pack[name].at[m].set(
                        jnp.asarray(buf))
                self._pack = new_pack
                rebuilt = False
            else:
                # shapes change (or the pack raced a rebuild): fall
                # back to the global invalidation plane
                self._pack = None
                self.pack_version += 1
                rebuilt = True
            self._models[model_id] = entry
            self._retained[model_id] = old
            self._epochs[model_id] = epoch = \
                self._epochs.get(model_id, 0) + 1
            if self.drift is not None and baseline is not None:
                entry.baseline = baseline
                self.drift.register(model_id, baseline, generation=epoch)
        pause = time.perf_counter() - t0
        self.swap_pauses.append(pause)
        TELEMETRY.record_dispatch("serve/swap_pause", t0, t0 + pause)
        return pause, rebuilt, epoch

    def _pack_row(self, entry: ResidentModel, dims) -> Dict[str, np.ndarray]:
        """One model's padded host buffers shaped like a single row of
        each pack field (pure numpy; nothing uploaded)."""
        T, Mn, F, B, Cc = dims
        out = {}
        for name, dtype, fill in _STACK_FIELDS:
            if name == "cat_bitset":
                shape = (T, Mn, 8)
            elif name == "num_leaves":
                shape = (T,)
            else:
                shape = (T, Mn)
            buf = np.full(shape, fill, dtype=dtype)
            a = entry.stack[_STACK_SLOT[name]]
            buf[tuple(slice(0, s) for s in a.shape)] = a
            out[name] = buf
        for key in entry.tables:
            shape = {"bounds": (F, B), "cat_vals": (F, Cc),
                     "cat_bins": (F, Cc)}.get(key, (F,))
            buf = np.full(shape, _TABLE_PADS[key],
                          dtype=entry.tables[key].dtype)
            a = entry.tables[key]
            buf[tuple(slice(0, s) for s in a.shape)] = a
            out["tab_" + key] = buf
        return out

    # ------------------------------------------------ replay reservoir
    def note_rows(self, model_id: str, X: np.ndarray) -> None:
        """Reservoir-sample served request rows (the predictor feeds
        every request through here) — the deterministic holdout the
        swap quality gate shadow-scores candidates on."""
        with self._lock:
            res = self._replay.get(model_id)
            if res is not None:
                res.note(X)

    def replay_rows(self, model_id: str) -> Optional[np.ndarray]:
        """The current holdout sample of recently served rows, or None
        before any traffic."""
        with self._lock:
            res = self._replay.get(model_id)
            return res.sample() if res is not None else None

    # ---------------------------------------------------------- admission
    def _packed_nbytes(self, entries) -> int:
        """Bytes of the shared device pack holding ``entries`` (padded
        to the common maxima) — pure host arithmetic, nothing uploaded."""
        if not entries:
            return 0
        M = len(entries)
        T = max(e.stack[0].shape[0] for e in entries)
        Mn = max(e.stack[0].shape[1] for e in entries)
        total = M * T * Mn * 4 * 5      # sf/tb/dt/lc/rc i32
        total += M * T * Mn * 8 * 4     # cat_bitset u32 words
        total += M * T * 4              # num_leaves
        F = max(e.tables["src_col"].shape[0] for e in entries)
        B = max(e.tables["bounds"].shape[1] for e in entries)
        Cc = max(e.tables["cat_vals"].shape[1] for e in entries)
        total += M * F * B * 4          # bounds f32
        total += M * F * Cc * 4 * 2     # cat_vals + cat_bins i32
        total += M * F * (4 * 4 + 1)    # src_col/num_bin/default_bin/
        return total                    # missing_type i32 + is_cat bool

    def _admit_or_raise(self, entry: ResidentModel, others=None,
                        verb: str = "load") -> None:
        if others is None:
            others = list(self._models.values())
        hypothetical = others + [entry]
        pack_bytes = self._packed_nbytes(hypothetical)
        budget = TELEMETRY.device_memory_budget()
        if budget is None:
            self._admit_record(
                f"admitted {entry.model_id} ({verb}, ~{entry.nbytes} B, "
                f"pack ~{pack_bytes} B); no allocator stats on "
                f"this backend — budget check skipped")
            return
        # request activation for one max-size batch of the widest model:
        # raw floats in, per-tree leaves out, bins in between
        F_raw = max(e.max_feature_idx + 1 for e in hypothetical)
        F_used = max(e.tables["src_col"].shape[0] for e in hypothetical)
        T = max(len(e.trees) for e in hypothetical)
        act = self.max_batch * (4 * F_raw + 4 * F_used + 4 * T)
        need = pack_bytes + act + TELEMETRY.cost_working_set()
        limit = int(self.admit_fraction * budget)
        if need <= limit:
            self._admit_record(
                f"admitted {entry.model_id} ({verb}): working set "
                f"~{need} B within {limit} B "
                f"({self.admit_fraction:.0%} of {budget} B HBM)")
            return
        residents = ", ".join(
            f"{m.model_id}(~{m.nbytes}B)" for m in others) or "<none>"
        detail = (f"rejected {entry.model_id} ({verb}): estimated working "
                  f"set ~{need} B exceeds {limit} B "
                  f"({self.admit_fraction:.0%} of the {budget} B reported "
                  f"HBM budget); residents: {residents}")
        self._admit_record(detail)
        raise ServeAdmissionError(
            f"serve admission: {detail}; evict a resident model "
            f"(ModelRegistry.evict) or raise the budget")

    # --------------------------------------------------------------- pack
    def entry(self, model_id: str) -> ResidentModel:
        with self._lock:
            e = self._models.get(model_id)
            if e is None:
                raise ServeError(
                    f"model_id {model_id!r} is not resident; loaded: "
                    f"{', '.join(self._order) or '<none>'}")
            return e

    def row_of(self, model_id: str) -> int:
        with self._lock:
            return self._order.index(model_id)

    def epoch_of(self, model_id: str) -> int:
        with self._lock:
            return self._epochs.get(model_id, 0)

    def residents(self) -> Dict[str, int]:
        with self._lock:
            return {mid: self._models[mid].nbytes for mid in self._order}

    def snapshot(self, model_id: str) -> PackSnapshot:
        """The version-pinned view one request dispatches against:
        entry, pack row, epoch and the pack arrays, taken atomically so
        a concurrent swap cannot mix generations mid-request."""
        with self._lock:
            entry = self.entry(model_id)
            return PackSnapshot(model_id, entry,
                               self._order.index(model_id),
                               self._epochs.get(model_id, 0),
                               self.pack(), self.pack_version)

    def pack(self) -> Dict[str, "object"]:
        """The shared device buffers, (re)built on demand after a
        load/evict.  One upload per rebuild; every serve executable
        takes these arrays as runtime arguments, so N models share one
        residency."""
        import jax.numpy as jnp
        with self._lock:
            if self._pack is not None:
                return self._pack
            entries = [self._models[mid] for mid in self._order]
            if not entries:
                raise ServeError("no models resident; load one first")
            M = len(entries)
            T = max(e.stack[0].shape[0] for e in entries)
            Mn = max(e.stack[0].shape[1] for e in entries)
            out = {}
            for name, dtype, fill in _STACK_FIELDS:
                if name == "cat_bitset":
                    shape = (M, T, Mn, 8)
                elif name == "num_leaves":
                    shape = (M, T)
                else:
                    shape = (M, T, Mn)
                buf = np.full(shape, fill, dtype=dtype)
                for m, e in enumerate(entries):
                    a = e.stack[_STACK_SLOT[name]]
                    buf[m][tuple(slice(0, s) for s in a.shape)] = a
                out[name] = jnp.asarray(buf)
            F = max(e.tables["src_col"].shape[0] for e in entries)
            B = max(e.tables["bounds"].shape[1] for e in entries)
            Cc = max(e.tables["cat_vals"].shape[1] for e in entries)
            for key in entries[0].tables:
                shape = {"bounds": (M, F, B), "cat_vals": (M, F, Cc),
                         "cat_bins": (M, F, Cc)}.get(key, (M, F))
                buf = np.full(shape, _TABLE_PADS[key],
                              dtype=entries[0].tables[key].dtype)
                for m, e in enumerate(entries):
                    a = e.tables[key]
                    buf[m][tuple(slice(0, s) for s in a.shape)] = a
                out["tab_" + key] = jnp.asarray(buf)
            self._pack = out
            TELEMETRY.gauge_set("serve/pack_bytes",
                                sum(int(v.nbytes) for v in out.values()))
            TELEMETRY.gauge_set("serve/resident_models", M)
            return self._pack
