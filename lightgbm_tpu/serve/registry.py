"""Multi-model hosting under an HBM budget.

Every resident model's tree stack and binning tables are packed into
ONE set of shared device buffers (``[M, T, nodes]`` / ``[M, F, len]``,
models padded to the pack maxima) so residency is a single accountable
allocation.  Admission mirrors the training-side out-of-core check
(``GBDT._resolve_data_tier``): the hypothetical packed working set —
pack bytes + the largest compiled-executable working set on record +
the request activation for one max-size batch — is compared against the
device allocator's reported capacity (``TELEMETRY.device_memory_budget``)
BEFORE anything is uploaded.  Every decision lands in the telemetry
faults section as a ``serve_admit`` event; a rejection raises
:class:`ServeAdmissionError` naming the budget, the shortfall and the
current residents so the operator knows exactly what to evict.

Backends without allocator stats (CPU) admit everything, same as the
training check.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..models.device_predict import stack_trees_host
from ..utils.log import LightGBMError
from ..utils.telemetry import TELEMETRY
from .binning import _CAT_PAD, build_tables, tables_nbytes

# same headroom fraction as the training admission check (models/gbdt.py)
SERVE_ADMIT_FRACTION = 0.9


class ServeError(LightGBMError):
    """Base error for the prediction service."""


class ServeAdmissionError(ServeError):
    """A model load would not fit under the device HBM budget."""


class ResidentModel:
    """Host-side state of one admitted model (device state lives in the
    shared pack)."""

    __slots__ = ("model_id", "trees", "num_tree_per_iteration",
                 "init_scores", "objective", "max_feature_idx",
                 "average_output", "tables", "stack", "max_depth",
                 "nbytes", "baseline")

    def __init__(self, model_id, trees, num_tree_per_iteration, init_scores,
                 objective, max_feature_idx, average_output, tables, stack,
                 max_depth, nbytes):
        self.baseline = None          # obs/drift.ModelBaseline when the
                                      # session runs with drift_detect
        self.model_id = model_id
        self.trees = trees
        self.num_tree_per_iteration = num_tree_per_iteration
        self.init_scores = init_scores
        self.objective = objective
        self.max_feature_idx = max_feature_idx
        self.average_output = average_output
        self.tables = tables          # host numpy binning tables
        self.stack = stack            # host numpy tree-stack fields
        self.max_depth = max_depth
        self.nbytes = nbytes          # unpadded host bytes (reporting)


def _extract(booster, num_iteration: int = -1) -> tuple:
    """(trees, mappers, used_indices, C, init_scores, objective,
    max_feature_idx, average_output) of a Booster, validated for binned
    serving."""
    gbdt = booster.gbdt
    if hasattr(gbdt, "_flush_pending"):
        gbdt._flush_pending()
    C = gbdt.num_tree_per_iteration
    n_iter = len(gbdt.models) // max(C, 1)
    if num_iteration is None or num_iteration < 0:
        num_iteration = (booster.best_iteration
                         if booster.best_iteration > 0 else n_iter)
    n_iter = min(max(num_iteration, 0), n_iter) or n_iter
    trees = list(gbdt.models[: n_iter * C])
    if not trees:
        raise ServeError("cannot serve a model with no trees")
    for i, t in enumerate(trees):
        if not getattr(t, "bins_aligned", True):
            raise ServeError(
                f"tree {i} was loaded from a model file and its bin "
                f"thresholds are not aligned with any dataset; load the "
                f"model into a training-capable booster "
                f"(serialization.load_trees_into) before serving")
    ds = getattr(gbdt, "train_set", None)
    if ds is None or not getattr(ds, "bin_mappers", None):
        raise ServeError(
            "serving needs the model's BinMappers for on-device binning; "
            "this booster carries no training dataset (file-loaded "
            "models must be re-bound to a dataset first)")
    return (trees, ds.bin_mappers, ds.used_feature_indices, C,
            list(gbdt.init_scores), booster.objective,
            gbdt.max_feature_idx, bool(getattr(gbdt, "average_output",
                                               False)))


# (field, dtype, pad value) of the packed tree stack; leaf values stay on
# the host (the predictor gathers them in float64 for bit-parity with the
# host walk), so they are deliberately NOT part of the device pack
_STACK_FIELDS = (
    ("split_feature", np.int32, 0),
    ("threshold_bin", np.int32, 0),
    ("decision_type", np.int32, 0),
    ("left_child", np.int32, -1),
    ("right_child", np.int32, -1),
    ("cat_bitset", np.uint32, 0),
    ("num_leaves", np.int32, 1),
)

_TABLE_PADS = {"src_col": 0, "bounds": np.inf, "num_bin": 1,
               "default_bin": 0, "missing_type": 0, "is_cat": False,
               "cat_vals": _CAT_PAD, "cat_bins": 0}


class ModelRegistry:
    """Admission-checked residency of N models in shared device buffers.

    ``pack()`` returns the current device arrays; ``pack_version``
    changes whenever they are rebuilt (load/evict), which invalidates
    every compiled serve executable that closed over the previous
    shapes (serve/predictor.py re-keys on the version).
    """

    def __init__(self, max_batch: int = 256,
                 admit_fraction: float = SERVE_ADMIT_FRACTION):
        self._lock = threading.RLock()
        self._models: Dict[str, ResidentModel] = {}
        self._order: List[str] = []          # pack row per model_id
        self._pack = None                    # device arrays, lazily built
        self.pack_version = 0
        self.max_batch = int(max_batch)
        self.admit_fraction = float(admit_fraction)
        self.health = None      # serve/health.ServeHealth, session-wired
        self.drift = None       # obs/drift.DriftAccumulator, session-wired

    def _admit_record(self, detail: str) -> None:
        """Every admission decision lands in the telemetry faults section
        AND (when the session streams health) as a serve_admit record."""
        TELEMETRY.fault_event("serve_admit", site="serve/admit",
                              detail=detail)
        if self.health is not None:
            self.health.event("serve_admit", {"detail": detail})

    # ------------------------------------------------------------ loading
    def load(self, booster, model_id: Optional[str] = None,
             num_iteration: int = -1) -> str:
        """Admit one Booster; returns its model_id.  Raises
        :class:`ServeAdmissionError` when the packed working set would
        exceed the HBM budget."""
        (trees, mappers, used, C, init_scores, objective, max_fi,
         avg_out) = _extract(booster, num_iteration)
        with self._lock:
            if model_id is None:
                model_id = f"model{len(self._order)}"
            if model_id in self._models:
                raise ServeError(f"model_id {model_id!r} is already "
                                 f"resident; evict it first")
            tables = build_tables(mappers, used)
            stack = stack_trees_host(trees, len(used))
            max_depth = stack[-1]
            nbytes = (sum(int(np.asarray(a).nbytes) for a in stack[:-1])
                      + tables_nbytes(tables))
            entry = ResidentModel(model_id, trees, C, init_scores,
                                  objective, max_fi, avg_out, tables,
                                  stack[:-1], max_depth, nbytes)
            self._admit_or_raise(entry)
            if self.drift is not None:
                # training baseline rides next to the pack: fine bin
                # occupancy of the Dataset's binned matrix + the
                # raw-score quantile digest the drift windows compare
                # against (host numpy; nothing extra uploaded)
                from ..obs.drift import extract_baseline
                entry.baseline = extract_baseline(booster)
                self.drift.register(model_id, entry.baseline)
            self._models[model_id] = entry
            self._order.append(model_id)
            self._pack = None
            self.pack_version += 1
            return model_id

    def evict(self, model_id: str) -> None:
        with self._lock:
            if model_id not in self._models:
                raise ServeError(f"model_id {model_id!r} is not resident")
            del self._models[model_id]
            self._order.remove(model_id)
            if self.drift is not None:
                self.drift.forget(model_id)
            self._pack = None
            self.pack_version += 1
            self._admit_record(
                f"evicted {model_id}; residents="
                f"{','.join(self._order) or '<none>'}")

    # ---------------------------------------------------------- admission
    def _packed_nbytes(self, entries) -> int:
        """Bytes of the shared device pack holding ``entries`` (padded
        to the common maxima) — pure host arithmetic, nothing uploaded."""
        if not entries:
            return 0
        M = len(entries)
        T = max(e.stack[0].shape[0] for e in entries)
        Mn = max(e.stack[0].shape[1] for e in entries)
        total = M * T * Mn * 4 * 5      # sf/tb/dt/lc/rc i32
        total += M * T * Mn * 8 * 4     # cat_bitset u32 words
        total += M * T * 4              # num_leaves
        F = max(e.tables["src_col"].shape[0] for e in entries)
        B = max(e.tables["bounds"].shape[1] for e in entries)
        Cc = max(e.tables["cat_vals"].shape[1] for e in entries)
        total += M * F * B * 4          # bounds f32
        total += M * F * Cc * 4 * 2     # cat_vals + cat_bins i32
        total += M * F * (4 * 4 + 1)    # src_col/num_bin/default_bin/
        return total                    # missing_type i32 + is_cat bool

    def _admit_or_raise(self, entry: ResidentModel) -> None:
        hypothetical = list(self._models.values()) + [entry]
        pack_bytes = self._packed_nbytes(hypothetical)
        budget = TELEMETRY.device_memory_budget()
        if budget is None:
            self._admit_record(
                f"admitted {entry.model_id} (~{entry.nbytes} B, "
                f"pack ~{pack_bytes} B); no allocator stats on "
                f"this backend — budget check skipped")
            return
        # request activation for one max-size batch of the widest model:
        # raw floats in, per-tree leaves out, bins in between
        F_raw = max(e.max_feature_idx + 1 for e in hypothetical)
        F_used = max(e.tables["src_col"].shape[0] for e in hypothetical)
        T = max(len(e.trees) for e in hypothetical)
        act = self.max_batch * (4 * F_raw + 4 * F_used + 4 * T)
        need = pack_bytes + act + TELEMETRY.cost_working_set()
        limit = int(self.admit_fraction * budget)
        if need <= limit:
            self._admit_record(
                f"admitted {entry.model_id}: working set "
                f"~{need} B within {limit} B "
                f"({self.admit_fraction:.0%} of {budget} B HBM)")
            return
        residents = ", ".join(
            f"{m.model_id}(~{m.nbytes}B)" for m in self._models.values()) \
            or "<none>"
        detail = (f"rejected {entry.model_id}: estimated working set "
                  f"~{need} B exceeds {limit} B "
                  f"({self.admit_fraction:.0%} of the {budget} B reported "
                  f"HBM budget); residents: {residents}")
        self._admit_record(detail)
        raise ServeAdmissionError(
            f"serve admission: {detail}; evict a resident model "
            f"(ModelRegistry.evict) or raise the budget")

    # --------------------------------------------------------------- pack
    def entry(self, model_id: str) -> ResidentModel:
        with self._lock:
            e = self._models.get(model_id)
            if e is None:
                raise ServeError(
                    f"model_id {model_id!r} is not resident; loaded: "
                    f"{', '.join(self._order) or '<none>'}")
            return e

    def row_of(self, model_id: str) -> int:
        with self._lock:
            return self._order.index(model_id)

    def residents(self) -> Dict[str, int]:
        with self._lock:
            return {mid: self._models[mid].nbytes for mid in self._order}

    def pack(self) -> Dict[str, "object"]:
        """The shared device buffers, (re)built on demand after a
        load/evict.  One upload per rebuild; every serve executable
        takes these arrays as runtime arguments, so N models share one
        residency."""
        import jax.numpy as jnp
        with self._lock:
            if self._pack is not None:
                return self._pack
            entries = [self._models[mid] for mid in self._order]
            if not entries:
                raise ServeError("no models resident; load one first")
            M = len(entries)
            T = max(e.stack[0].shape[0] for e in entries)
            Mn = max(e.stack[0].shape[1] for e in entries)
            out = {}
            for name, dtype, fill in _STACK_FIELDS:
                if name == "cat_bitset":
                    shape = (M, T, Mn, 8)
                elif name == "num_leaves":
                    shape = (M, T)
                else:
                    shape = (M, T, Mn)
                buf = np.full(shape, fill, dtype=dtype)
                for m, e in enumerate(entries):
                    a = e.stack[{"split_feature": 0, "threshold_bin": 1,
                                 "decision_type": 2, "left_child": 3,
                                 "right_child": 4, "cat_bitset": 5,
                                 "num_leaves": 7}[name]]
                    buf[m][tuple(slice(0, s) for s in a.shape)] = a
                out[name] = jnp.asarray(buf)
            F = max(e.tables["src_col"].shape[0] for e in entries)
            B = max(e.tables["bounds"].shape[1] for e in entries)
            Cc = max(e.tables["cat_vals"].shape[1] for e in entries)
            for key in entries[0].tables:
                shape = {"bounds": (M, F, B), "cat_vals": (M, F, Cc),
                         "cat_bins": (M, F, Cc)}.get(key, (M, F))
                buf = np.full(shape, _TABLE_PADS[key],
                              dtype=entries[0].tables[key].dtype)
                for m, e in enumerate(entries):
                    a = e.tables[key]
                    buf[m][tuple(slice(0, s) for s in a.shape)] = a
                out["tab_" + key] = jnp.asarray(buf)
            self._pack = out
            TELEMETRY.gauge_set("serve/pack_bytes",
                                sum(int(v.nbytes) for v in out.values()))
            TELEMETRY.gauge_set("serve/resident_models", M)
            return self._pack
