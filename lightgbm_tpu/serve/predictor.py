"""Compiled, shape-bucketed prediction over the shared model pack.

One executable per ``(model_id, epoch, batch bucket)``: request batches
are padded up to the next power-of-two bucket (floor ``MIN_BUCKET``) so
a steady request stream hits a handful of compiled programs instead of
one retrace per batch size.  Each executable fuses on-device binning
(serve/binning.py) with the stacked tree routing
(models/device_predict.predict_binned_leaves) and is AOT-compiled
through the existing ``CostJit`` wrapper — the telemetry ``cost``
section gets FLOPs/bytes per bucket for free, and ``device_timing=``
runs get measured per-dispatch p50/p99 under the same labels.

Every request dispatches against one registry ``snapshot()`` — the
entry, pack row, epoch and device arrays pinned together — so a hot
swap that flips mid-request cannot mix generations: in-flight batches
complete against the arrays they were built with.  A swap bumps only
the swapped id's epoch, which retires exactly that model's cached
executables; a load/evict bumps the global ``pack_version`` and clears
everything (the pack shapes changed under every model).

Padded rows are provably inert: routing is a pure per-row map with no
cross-row reduction, so a pad row can only change its OWN (discarded)
output slot.  The executable returns per-tree leaf INDICES; the float64
leaf values are gathered and accumulated on the host in the exact order
of the host tree walk (``GBDT._raw_predict``), which is what makes
serve output bit-identical to ``Booster.predict``.

OOM resilience mirrors the training-side ``_chunk_cap`` ladder: a
RESOURCE_EXHAUSTED-shaped dispatch failure halves the sticky batch cap
(floor 1) and retries — replies are bit-identical across splits because
the host f64 gather is a per-row accumulation in fixed order.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

import numpy as np

from ..models.device_predict import TreeStack, predict_binned_leaves
from ..models.gbdt import _is_oom_error
from ..utils.faults import FAULTS, oom_error
from ..utils.jitcost import cost_jit
from ..utils.telemetry import TELEMETRY
from .registry import ModelRegistry, PackSnapshot, ServeError

# smallest compiled batch shape: buckets below this add executables
# without meaningfully shrinking the padded-dispatch cost
MIN_BUCKET = 8


def _next_bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class BucketedPredictor:
    """Executable cache keyed on ``(model_id, epoch, batch_bucket)``."""

    def __init__(self, registry: ModelRegistry, max_batch: int = 256):
        self.registry = registry
        self.max_batch = int(max_batch)
        self._lock = threading.RLock()
        self._fns: Dict[Tuple, object] = {}
        self._fns_version = -1
        self._batch_cap = None  # sticky OOM ladder cap (None = max_batch)
        self._rows = 0
        self._padded = 0
        self.health = None      # serve/health.ServeHealth, session-wired
        self.drift = None       # obs/drift.DriftAccumulator, session-wired

    # ----------------------------------------------------------- compile
    def _fn_for(self, snap: PackSnapshot, bucket: int,
                with_drift: bool = False):
        """The jitted (CostJit-wrapped) executable for one bucket; built
        once, reused for every later batch in the bucket.  A registry
        pack rebuild (load/evict) invalidates the whole cache; a hot
        swap retires only the swapped model's entries (epoch key).  The
        ``with_drift`` variant additionally returns the per-feature
        bin-occupancy counts of the VALID rows (obs/drift.py) — the
        leaves output is untouched, so replies stay bit-identical."""
        model_id = snap.model_id
        with self._lock:
            if snap.pack_version > self._fns_version:
                self._fns.clear()
                self._fns_version = snap.pack_version
            key = (model_id, snap.pack_version, snap.epoch, bucket,
                   with_drift)
            fn = self._fns.get(key)
            if fn is not None:
                return fn
            # injected compile failure: a named give-up instead of a hang
            FAULTS.maybe_raise(
                "serve/compile",
                lambda site: ServeError(
                    f"injected fault at {site}: giving up on compiling "
                    f"the {model_id}:b{bucket} serve executable"))
            entry = snap.entry
            m = snap.row
            max_depth = entry.max_depth
            num_bin_axis = int(entry.tables["num_bin"].max())

            def leaves_fn(pack, X, n_valid=None):
                import jax.numpy as jnp

                from .binning import bin_occupancy, bin_rows
                tables = {k[4:]: v[m] for k, v in pack.items()
                          if k.startswith("tab_")}
                bins = bin_rows(tables, X)
                # leaf values are gathered on the host; the stack slot
                # only has to exist for the NamedTuple
                stack = TreeStack(
                    pack["split_feature"][m], pack["threshold_bin"][m],
                    pack["decision_type"][m], pack["left_child"][m],
                    pack["right_child"][m], pack["cat_bitset"][m],
                    jnp.zeros((pack["num_leaves"].shape[1], 1),
                              dtype=jnp.float32),
                    pack["num_leaves"][m], max_depth)
                leaves = predict_binned_leaves(stack, bins,
                                               tables["num_bin"],
                                               tables["default_bin"])
                if n_valid is None:
                    return leaves
                return leaves, bin_occupancy(tables, bins, n_valid,
                                             num_bin_axis)

            import jax
            if with_drift:
                jitted = jax.jit(lambda pack, X, n_valid:
                                 leaves_fn(pack, X, n_valid))
            else:
                jitted = jax.jit(leaves_fn)
            fn = cost_jit(f"serve/predict[{model_id}:b{bucket}"
                          f"{':drift' if with_drift else ''}]", jitted)
            # retire this model's previous-epoch executables: they can
            # never be handed out again (snapshots carry the new epoch)
            stale = [k for k in self._fns
                     if k[0] == model_id and k[2] != snap.epoch]
            for k in stale:
                del self._fns[k]
            self._fns[key] = fn
            return fn

    # ---------------------------------------------------------- dispatch
    def _leaves(self, snap: PackSnapshot, X: np.ndarray) -> np.ndarray:
        """Per-tree leaves [T, B] for one chunk (B <= max_batch)."""
        import jax.numpy as jnp
        FAULTS.maybe_raise("serve/oom", oom_error)
        B = X.shape[0]
        bucket = _next_bucket(B)
        model_id = snap.model_id
        drift = self.drift
        if drift is not None and not drift.tracks(model_id):
            drift = None
        fn = self._fn_for(snap, bucket, with_drift=drift is not None)
        pad = bucket - B
        if pad:
            X = np.concatenate(
                [X, np.zeros((pad, X.shape[1]), dtype=X.dtype)])
        pack = snap.pack
        if drift is not None:
            # n_valid is traced, so every partial batch in the bucket
            # reuses one executable; pad rows are masked from the counts
            leaves, occupancy = fn(pack, jnp.asarray(X), jnp.int32(B))
            leaves = np.asarray(leaves)
            drift.note_bins(model_id, np.asarray(occupancy))
        else:
            leaves = np.asarray(fn(pack, jnp.asarray(X)))
        with self._lock:
            self._rows += B
            self._padded += pad
            TELEMETRY.counter_add("serve/batches")
            TELEMETRY.counter_add("serve/rows", B)
            TELEMETRY.counter_add("serve/padded_rows", pad)
            TELEMETRY.gauge_set(
                "serve/pad_ratio",
                round(self._padded / max(self._rows + self._padded, 1), 6))
        if self.health is not None:
            self.health.note_dispatch(model_id, B, pad, bucket)
        return leaves[:, :B]

    def _dispatch_cap(self) -> int:
        with self._lock:
            cap = self.max_batch if self._batch_cap is None \
                else min(self._batch_cap, self.max_batch)
        return max(int(cap), 1)

    def _halve_cap(self, failed_rows: int, exc: BaseException) -> int:
        """One rung down the OOM ladder: sticky, mirroring the training
        side's ``_chunk_cap`` (a batch that OOMed once will OOM again)."""
        new_cap = max(failed_rows // 2, 1)
        with self._lock:
            if self._batch_cap is not None:
                new_cap = min(new_cap, self._batch_cap)
            self._batch_cap = new_cap
        TELEMETRY.counter_add("serve/oom_halvings")
        TELEMETRY.fault_event(
            "serve_oom", site="serve/oom",
            detail=f"dispatch of {failed_rows} rows hit "
                   f"{type(exc).__name__}; retrying at batch {new_cap}")
        if self.health is not None:
            self.health.event("serve_fault", {
                "error": f"{type(exc).__name__}: {exc}",
                "action": f"OOM ladder: retrying at batch {new_cap}",
                "recovered": True})
        return new_cap

    def predict(self, model_id: str, X, raw_score: bool = False):
        """Predictions for raw float rows, exactly as ``Booster.predict``
        shapes them: [B] for single-output models, [B, C] multiclass."""
        snap = self.registry.snapshot(model_id)
        entry = snap.entry
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X)),
                                 dtype=np.float32)
        n_feat = entry.max_feature_idx + 1
        if X.ndim != 2 or X.shape[1] != n_feat:
            raise ServeError(
                f"request matrix has {X.shape[1] if X.ndim == 2 else '?'} "
                f"features but {model_id} was trained with {n_feat}")
        self.registry.note_rows(model_id, X)
        B = X.shape[0]
        C = entry.num_tree_per_iteration
        out = np.zeros((C, B), dtype=np.float64)
        for k in range(C):
            out[k] += entry.init_scores[k]
        done = 0
        while done < B:
            chunk = X[done: done + self._dispatch_cap()]
            try:
                leaves = self._leaves(snap, chunk)
            except Exception as exc:
                if not _is_oom_error(exc) or chunk.shape[0] <= 1:
                    raise
                # RESOURCE_EXHAUSTED at this size: halve and re-dispatch
                # the same rows — bit-identical by construction (per-row
                # f64 gather, fixed accumulation order)
                self._halve_cap(chunk.shape[0], exc)
                continue
            # same accumulation order (and float64 precision) as the
            # host walk in GBDT._raw_predict -> bit-identical output;
            # values come from the entry's leaf snapshot so an in-place
            # refit of the source booster cannot perturb live replies
            for t in range(len(entry.trees)):
                out[t % C, done: done + chunk.shape[0]] += \
                    entry.leaf_values[t][leaves[t]]
            done += chunk.shape[0]
        if entry.average_output:
            out /= max(len(entry.trees) // max(C, 1), 1)
        if self.drift is not None:
            # raw first-output scores (post averaging, pre link), the
            # same scale as the training-score digest in the baseline
            self.drift.note_scores(model_id, out[0])
        if raw_score or entry.objective is None:
            res = out
        else:
            res = entry.objective.convert_output(out)
        if C == 1:
            return res[0]
        return res.T
