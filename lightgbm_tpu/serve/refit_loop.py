"""The closed trainer→server loop: drift-triggered refit → gated swap.

PR 18's drift plane produces the refit trigger (``DriftGate.drifted``)
but nothing consumed it; :class:`RefitLoop` closes the loop.  A
background daemon thread polls the gate every ``refit_poll_s`` seconds
and, when the served traffic has drifted past the PSI threshold:

  1. pulls fresh labeled data from the caller's ``data_source()``
     (returns ``(X, y)`` or ``(X, y, weight)``, or None to skip),
  2. runs ``Booster.refit`` on it — leaf values re-estimated in place,
     tree structure untouched.  Serving is unaffected while this runs:
     the registry's resident entries gather from their OWN leaf-value
     snapshots, never from the live tree objects,
  3. pushes the refitted booster through the quality-gated hot swap
     (``ServeSession.swap``) with the SAME fresh labeled data as the
     shadow-scoring holdout — a candidate that regressed the holdout
     metric (or went non-finite) is rejected, the in-place refit is
     rolled back on the booster (``restore_leaf_values``), and the old
     model keeps serving.

Every attempt lands as a ``serve_refit`` health record (status
``swapped`` / ``rejected`` / ``fault``) plus ``serve/refits`` /
``serve/refit_faults`` counters.  An armed ``serve/refit`` fault site
fails one attempt (the loop survives and keeps polling), and the swap
itself is fault-injectable at the flip via ``serve/swap`` — the full
lifecycle degrades, it never dies.

``run_once()`` is the synchronous single-poll entry point (what the
thread calls; also the deterministic hook for tests and operators
driving the loop from their own scheduler).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..models.refit import restore_leaf_values, snapshot_leaf_values
from ..utils.faults import FAULTS, InjectedFault
from ..utils.telemetry import TELEMETRY
from .registry import ServeError, SwapRejectedError


class RefitLoop:
    """Background drift-poll → ``Booster.refit`` → gated-swap loop for
    one served model.  Start with :meth:`start` (or let
    ``ServeSession.start_refit_loop`` do it); ``stop()`` joins the
    thread.  Counters: ``swaps`` / ``rejected`` / ``faults``."""

    def __init__(self, session, model_id: str, booster,
                 data_source: Callable,
                 poll_s: float = 30.0,
                 decay_rate: Optional[float] = None,
                 quality_threshold: Optional[float] = None,
                 psi_threshold: Optional[float] = None,
                 min_rows: int = 1,
                 max_refits: Optional[int] = None):
        if session.drift_gate is None:
            raise ServeError(
                "the refit loop consumes DriftGate.drifted() as its "
                "trigger; open the session with drift_detect=true")
        self.session = session
        self.model_id = model_id
        self.booster = booster
        self.data_source = data_source
        self.poll_s = max(float(poll_s), 0.01)
        self.decay_rate = decay_rate
        self.quality_threshold = quality_threshold
        self.psi_threshold = psi_threshold
        self.min_rows = max(int(min_rows), 0)
        self.max_refits = max_refits
        self.swaps = 0
        self.rejected = 0
        self.faults = 0
        self.polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- polling
    def run_once(self) -> str:
        """One poll of the trigger: returns ``"idle"`` (not drifted /
        no data), ``"swapped"``, ``"rejected"`` or ``"fault"``."""
        self.polls += 1
        gate = self.session.drift_gate
        stats = gate.stats(self.model_id)
        if stats is None or stats["rows"] < self.min_rows \
                or not gate.drifted(self.model_id, self.psi_threshold):
            return "idle"
        leaf_snapshot = None
        try:
            FAULTS.maybe_raise(
                "serve/refit",
                lambda site: InjectedFault(
                    site, f"injected fault at {site}: refit attempt "
                          f"for {self.model_id} failed"))
            data = self.data_source()
            if data is None:
                return "idle"
            X, y = data[0], data[1]
            weight = data[2] if len(data) > 2 else None
            leaf_snapshot = snapshot_leaf_values(self.booster.gbdt)
            self.booster.refit(X, y, weight=weight,
                               decay_rate=self.decay_rate)
        except Exception as exc:
            # a failed attempt must not take the loop (or serving) down:
            # the old model is still live and untouched
            if leaf_snapshot is not None:
                restore_leaf_values(self.booster.gbdt, leaf_snapshot)
            self.faults += 1
            TELEMETRY.counter_add("serve/refit_faults")
            self._note("fault", drift=stats,
                       error=f"{type(exc).__name__}: {exc}")
            return "fault"
        try:
            self.session.swap(self.model_id, self.booster,
                              holdout=X, label=y,
                              quality_threshold=self.quality_threshold)
        except SwapRejectedError as exc:
            # gate said no: undo the in-place refit so the loop's
            # booster stays in sync with the model that kept serving
            restore_leaf_values(self.booster.gbdt, leaf_snapshot)
            self.rejected += 1
            self._note("rejected", drift=stats, error=str(exc))
            return "rejected"
        self.swaps += 1
        TELEMETRY.counter_add("serve/refits")
        self._note("swapped", drift=stats)
        return "swapped"

    def _note(self, status: str, drift=None, error: str = "") -> None:
        health = getattr(self.session, "health", None)
        if health is None:
            return
        rec = {"model": self.model_id, "status": status,
               "swaps": self.swaps, "rejected": self.rejected,
               "faults": self.faults}
        if drift is not None:
            rec["psi_max"] = drift.get("psi_max")
            rec["rows"] = drift.get("rows")
        if error:
            rec["error"] = error
        health.event("serve_refit", rec)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RefitLoop":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run,
                                        name="serve-refit", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.run_once()
            except Exception:
                # an unexpected poll error (e.g. the session closed
                # under us) ends the loop; serving is unaffected
                return
            if self.max_refits is not None \
                    and self.swaps >= self.max_refits:
                return

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
