"""Request micro-batching: the latency-vs-throughput knob.

Callers submit small row batches and get a Future; a worker thread
drains the queue into per-model dispatches, waiting at most
``max_delay_ms`` past the oldest pending request (or until
``max_batch`` rows have accumulated) before calling the bucketed
predictor.  Coalescing requests into one padded dispatch trades a
bounded amount of added latency for fewer, fuller executables — the
``serve_max_delay_ms=0`` setting degenerates to dispatch-per-request.

Every request carries a monotonic lifecycle timestamp tuple
(enqueue → coalesce-close → dispatch → device-ready → reply) and each
stage wall is recorded as a ``TELEMETRY.record_dispatch`` sample
(``serve/t_queue``, ``serve/t_coalesce``, ``serve/t_dispatch``,
``serve/t_reply``) plus one completed-request sample into the
registry's sliding window (QPS/p50/p99 in ``stats()["serve"]``); at
telemetry level >= 2 the stages also land as Chrome-trace spans on the
``serve`` track.  ``serve/queue_depth`` and ``serve/inflight_batches``
gauges expose the queue's instantaneous state, and
``serve/coalesce_slack_ms`` records how much of the ``max_delay_ms``
budget the last batch left unused — the measured signal for tuning the
delay knob.  A session-scoped serve health stream (serve/health.py)
additionally gets per-request stage walls and per-batch fill for its
periodic ``serve_window`` records.

Overload degrades instead of dying: the queue is bounded by
``serve_max_queue_rows`` total pending rows (0 = unbounded) and a
submit that would exceed the bound is SHED — a named
:class:`ServeOverloadError` immediately, a ``serve/shed_requests``
counter bump and a shed count in the next health window — while every
already-admitted request completes normally.  An armed ``serve/shed``
fault site sheds deterministically regardless of depth.

Failure behavior is explicit: an injected ``serve/enqueue`` fault or a
predictor error becomes a named exception on the affected futures
(never a hang, and a ``serve_fault`` health record), and ``predict``
applies ``queue_timeout_s`` so a stuck dispatch surfaces as a give-up
that names the site.  ``evict_pending()`` eagerly fails requests still
queued for a model being evicted ("evicted while queued", never a
pack-shape surprise at dispatch).  ``close()`` fails pending futures,
bumps the ``serve/closed`` counter and writes the ``serve_summary``
terminal health record — and when the worker does not join within
``join_timeout_s`` it fails the wedged in-flight batch with a named
error plus a ``serve_fault`` record instead of returning silently.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from ..utils.faults import FAULTS
from ..utils.telemetry import TELEMETRY
from .predictor import BucketedPredictor
from .registry import ServeError, ServeOverloadError


class _Request:
    __slots__ = ("model_id", "raw_score", "X", "future", "t_enqueue",
                 "t_coalesce")

    def __init__(self, model_id, raw_score, X):
        self.model_id = model_id
        self.raw_score = raw_score
        self.X = X
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.t_coalesce = None          # stamped when its batch closes


def _fail(future: Future, exc: BaseException) -> bool:
    """Fail a future that may already be resolved (close/evict races
    the worker); returns True when this call set the exception."""
    try:
        future.set_exception(exc)
        return True
    except Exception:
        return False


class MicroBatchQueue:
    """Single-worker micro-batching front of a :class:`BucketedPredictor`."""

    def __init__(self, predictor: BucketedPredictor,
                 max_delay_ms: float = 2.0, max_batch: int = 256,
                 queue_timeout_s: float = 30.0, health=None,
                 max_queue_rows: int = 0):
        self.predictor = predictor
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1000.0
        self.max_batch = int(max_batch)
        self.queue_timeout_s = float(queue_timeout_s)
        self.max_queue_rows = max(int(max_queue_rows), 0)
        self.join_timeout_s = 5.0       # close() worker-join budget
        self.health = health            # serve/health.ServeHealth or None
        self.drift = None               # obs/drift.DriftAccumulator or None
        self._pending = deque()
        self._queued_rows = 0
        self._current = None            # batch the worker is dispatching
        self._cond = threading.Condition()
        self._closed = False
        self._inflight = 0
        self._worker = threading.Thread(target=self._run,
                                        name="serve-batcher", daemon=True)
        self._worker.start()

    # ----------------------------------------------------------- clients
    def submit(self, model_id: str, X, raw_score: bool = False) -> Future:
        """Enqueue one request; resolves to Booster.predict-shaped rows.
        Raises :class:`ServeOverloadError` (load shedding) when the
        pending rows would exceed ``serve_max_queue_rows``."""
        if self._closed:
            raise ServeError("serve queue is closed")
        FAULTS.maybe_raise(
            "serve/enqueue",
            lambda site: ServeError(
                f"injected fault at {site}: request for {model_id} "
                f"rejected at enqueue"))
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X)),
                                 dtype=np.float32)
        rows = int(X.shape[0])
        req = _Request(model_id, bool(raw_score), X)
        with self._cond:
            if self._closed:
                raise ServeError("serve queue is closed")
            forced = FAULTS.check("serve/shed")
            if forced or (self.max_queue_rows
                          and self._queued_rows + rows
                          > self.max_queue_rows):
                self._shed(model_id, rows, self._queued_rows, forced)
            self._pending.append(req)
            self._queued_rows += rows
            depth = len(self._pending)
            self._cond.notify()
        TELEMETRY.counter_add("serve/requests")
        TELEMETRY.gauge_set("serve/queue_depth", depth)
        return req.future

    def _shed(self, model_id: str, rows: int, queued: int,
              forced: bool) -> None:
        """Reject one submit at the door (called under ``_cond``)."""
        TELEMETRY.counter_add("serve/shed_requests")
        TELEMETRY.counter_add("serve/shed_rows", rows)
        if self.health is not None:
            self.health.note_shed(rows)
        if forced:
            raise ServeOverloadError(
                f"injected fault at serve/shed: request for {model_id} "
                f"({rows} rows) shed")
        raise ServeOverloadError(
            f"serve queue at capacity: {queued} rows pending + {rows} "
            f"requested exceeds serve_max_queue_rows="
            f"{self.max_queue_rows}; request for {model_id} shed")

    def predict(self, model_id: str, X, raw_score: bool = False,
                timeout: float = None):
        fut = self.submit(model_id, X, raw_score=raw_score)
        budget = self.queue_timeout_s if timeout is None else float(timeout)
        try:
            return fut.result(timeout=budget)
        except FutureTimeout:
            raise ServeError(
                f"serve request for {model_id} gave up after {budget:.1f}s "
                f"waiting on the batch queue (serve_queue_timeout_s)")

    def evict_pending(self, model_id: str) -> int:
        """Eagerly fail every still-queued request for a model being
        evicted — a named error NOW instead of a pack-shape surprise
        when the worker would have dispatched them."""
        with self._cond:
            keep, dropped = deque(), []
            for r in self._pending:
                (dropped if r.model_id == model_id else keep).append(r)
            self._pending = keep
            self._queued_rows -= sum(r.X.shape[0] for r in dropped)
            depth = len(keep)
        for r in dropped:
            _fail(r.future, ServeError(
                f"model {model_id!r} evicted while queued; request "
                f"failed before dispatch"))
        if dropped:
            TELEMETRY.counter_add("serve/evicted_queued", len(dropped))
            TELEMETRY.gauge_set("serve/queue_depth", depth)
            if self.health is not None:
                self.health.event("serve_fault", {
                    "model": model_id, "requests": len(dropped),
                    "error": "model evicted while queued"})
        return len(dropped)

    def close(self):
        """Stop the worker; pending futures fail with a named error.
        Terminal telemetry makes the abort legible: the ``serve/closed``
        counter and the stream's ``serve_summary`` record.  A worker
        that does not join within ``join_timeout_s`` is reported as
        wedged: its in-flight batch is failed with a named error and a
        ``serve_fault`` record instead of being silently abandoned."""
        with self._cond:
            already = self._closed
            self._closed = True
            leftovers = list(self._pending)
            self._pending.clear()
            self._queued_rows = 0
            self._cond.notify_all()
        for req in leftovers:
            _fail(req.future, ServeError("serve queue closed "
                                         "before dispatch"))
        self._worker.join(timeout=self.join_timeout_s)
        wedged_failed = 0
        if self._worker.is_alive():
            with self._cond:
                stuck = list(self._current or ())
            for req in stuck:
                if _fail(req.future, ServeError(
                        f"serve worker wedged at close: dispatch for "
                        f"{req.model_id} did not complete within "
                        f"{self.join_timeout_s:.1f}s; request failed")):
                    wedged_failed += 1
            TELEMETRY.counter_add("serve/wedged_close")
            if self.health is not None:
                self.health.event("serve_fault", {
                    "error": f"serve worker still alive "
                             f"{self.join_timeout_s:.1f}s after close; "
                             f"in-flight batch abandoned",
                    "requests": wedged_failed,
                    "wedged": True})
        if already:
            return
        TELEMETRY.counter_add("serve/closed")
        TELEMETRY.gauge_set("serve/queue_depth", 0)
        if self.health is not None:
            self.health.close(
                pending_failed=len(leftovers) + wedged_failed)
        elif self.drift is not None:
            # no health stream to flush through: publish the final
            # drift state directly so post-close DriftGate polls and
            # the metrics blob's drift section see all the traffic
            self.drift.publish_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------ worker
    def _take_batch(self):
        """Wait for work, honor the delay window, then drain every pending
        request that matches the oldest one's (model, raw) key up to
        ``max_batch`` rows.  Returns a list of requests or None on close."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return None
            head = self._pending[0]
            deadline = head.t_enqueue + self.max_delay_s
            while not self._closed:
                rows = sum(r.X.shape[0] for r in self._pending
                           if r.model_id == head.model_id
                           and r.raw_score == head.raw_score)
                remaining = deadline - time.perf_counter()
                if rows >= self.max_batch or remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch, keep, rows = [], deque(), 0
            for r in self._pending:
                if (r.model_id == head.model_id
                        and r.raw_score == head.raw_score
                        and rows < self.max_batch):
                    batch.append(r)
                    rows += r.X.shape[0]
                else:
                    keep.append(r)
            self._pending = keep
            self._queued_rows -= rows
            self._current = batch
            depth = len(keep)
        # coalesce-close: the window just ended for every batched
        # request; the slack is how much of the delay budget the batch
        # left on the table (negative = the queue ran past its window,
        # i.e. the worker was busy dispatching when the deadline hit)
        t_close = time.perf_counter()
        for r in batch:
            r.t_coalesce = t_close
        waited_ms = (t_close - batch[0].t_enqueue) * 1e3
        TELEMETRY.gauge_set("serve/coalesce_slack_ms",
                            self.max_delay_s * 1e3 - waited_ms)
        TELEMETRY.gauge_set("serve/queue_depth", depth)
        return batch

    def _run(self):
        while True:
            try:
                batch = self._take_batch()
            except Exception:
                continue
            if batch is None:
                return
            t_close = batch[0].t_coalesce
            t_dispatch = time.perf_counter()
            for r in batch:
                TELEMETRY.record_dispatch("serve/queue_wait",
                                          r.t_enqueue, t_dispatch)
            X = batch[0].X if len(batch) == 1 else \
                np.concatenate([r.X for r in batch])
            with self._cond:
                self._inflight += 1
                TELEMETRY.gauge_set("serve/inflight_batches",
                                    self._inflight)
            try:
                res = self.predictor.predict(batch[0].model_id, X,
                                             raw_score=batch[0].raw_score)
                slices = []
                done = 0
                for r in batch:
                    n = r.X.shape[0]
                    slices.append(res[done: done + n])
                    done += n
            except Exception as exc:
                for r in batch:
                    _fail(r.future, exc)
                with self._cond:
                    self._current = None
                TELEMETRY.counter_add("serve/errors")
                if self.health is not None:
                    self.health.event("serve_fault", {
                        "model": batch[0].model_id,
                        "requests": len(batch),
                        "error": f"{type(exc).__name__}: {exc}"})
                continue
            finally:
                with self._cond:
                    self._inflight -= 1
                    TELEMETRY.gauge_set("serve/inflight_batches",
                                        self._inflight)
            # device-ready: predictor.predict materialized the leaves
            # (np.asarray blocks on the device buffers) and finished the
            # host f64 gather; what remains is slicing + future wakeups
            t_device = time.perf_counter()
            for r, out in zip(batch, slices):
                try:
                    r.future.set_result(out)
                except Exception:
                    pass    # failed at close/evict while we dispatched
            with self._cond:
                self._current = None
            t_reply = time.perf_counter()
            self._record_lifecycle(batch, t_close, t_dispatch, t_device,
                                   t_reply, X.shape[0])

    # ------------------------------------------------------ observability
    def _record_lifecycle(self, batch, t_close, t_dispatch, t_device,
                          t_reply, rows):
        """Stage walls for every request in a replied batch: dispatch
        samples (always), Chrome-trace spans (level >= 2, one per stage
        per batch on the ``serve`` track), the sliding-window sample,
        and the serve health stream's per-request feed."""
        for r in batch:
            TELEMETRY.record_dispatch("serve/t_queue",
                                      r.t_enqueue, t_close)
            TELEMETRY.record_dispatch("serve/t_coalesce",
                                      t_close, t_dispatch)
            TELEMETRY.record_dispatch("serve/t_dispatch",
                                      t_dispatch, t_device)
            TELEMETRY.record_dispatch("serve/t_reply",
                                      t_device, t_reply)
            TELEMETRY.serve_request_done(t_reply - r.t_enqueue,
                                         end=t_reply)
        args = {"requests": len(batch), "rows": int(rows)}
        head = batch[0]
        TELEMETRY.record_span("serve/t_queue", head.t_enqueue,
                              t_close - head.t_enqueue, args, tid="serve")
        TELEMETRY.record_span("serve/t_coalesce", t_close,
                              t_dispatch - t_close, args, tid="serve")
        TELEMETRY.record_span("serve/t_dispatch", t_dispatch,
                              t_device - t_dispatch, args, tid="serve")
        TELEMETRY.record_span("serve/t_reply", t_device,
                              t_reply - t_device, args, tid="serve")
        if self.health is not None:
            for r in batch:
                self.health.note_request(
                    r.model_id, r.X.shape[0],
                    {"t_queue": t_close - r.t_enqueue,
                     "t_coalesce": t_dispatch - t_close,
                     "t_dispatch": t_device - t_dispatch,
                     "t_reply": t_reply - t_device},
                    t_reply - r.t_enqueue)
