"""Request micro-batching: the latency-vs-throughput knob.

Callers submit small row batches and get a Future; a worker thread
drains the queue into per-model dispatches, waiting at most
``max_delay_ms`` past the oldest pending request (or until
``max_batch`` rows have accumulated) before calling the bucketed
predictor.  Coalescing requests into one padded dispatch trades a
bounded amount of added latency for fewer, fuller executables — the
``serve_max_delay_ms=0`` setting degenerates to dispatch-per-request.

Failure behavior is explicit: an injected ``serve/enqueue`` fault or a
predictor error becomes a named exception on the affected futures
(never a hang), and ``predict`` applies ``queue_timeout_s`` so a stuck
dispatch surfaces as a give-up that names the site.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from ..utils.faults import FAULTS
from ..utils.telemetry import TELEMETRY
from .predictor import BucketedPredictor
from .registry import ServeError


class _Request:
    __slots__ = ("model_id", "raw_score", "X", "future", "t_enqueue")

    def __init__(self, model_id, raw_score, X):
        self.model_id = model_id
        self.raw_score = raw_score
        self.X = X
        self.future = Future()
        self.t_enqueue = time.perf_counter()


class MicroBatchQueue:
    """Single-worker micro-batching front of a :class:`BucketedPredictor`."""

    def __init__(self, predictor: BucketedPredictor,
                 max_delay_ms: float = 2.0, max_batch: int = 256,
                 queue_timeout_s: float = 30.0):
        self.predictor = predictor
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1000.0
        self.max_batch = int(max_batch)
        self.queue_timeout_s = float(queue_timeout_s)
        self._pending = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(target=self._run,
                                        name="serve-batcher", daemon=True)
        self._worker.start()

    # ----------------------------------------------------------- clients
    def submit(self, model_id: str, X, raw_score: bool = False) -> Future:
        """Enqueue one request; resolves to Booster.predict-shaped rows."""
        if self._closed:
            raise ServeError("serve queue is closed")
        FAULTS.maybe_raise(
            "serve/enqueue",
            lambda site: ServeError(
                f"injected fault at {site}: request for {model_id} "
                f"rejected at enqueue"))
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X)),
                                 dtype=np.float32)
        req = _Request(model_id, bool(raw_score), X)
        with self._cond:
            if self._closed:
                raise ServeError("serve queue is closed")
            self._pending.append(req)
            self._cond.notify()
        TELEMETRY.counter_add("serve/requests")
        return req.future

    def predict(self, model_id: str, X, raw_score: bool = False,
                timeout: float = None):
        fut = self.submit(model_id, X, raw_score=raw_score)
        budget = self.queue_timeout_s if timeout is None else float(timeout)
        try:
            return fut.result(timeout=budget)
        except FutureTimeout:
            raise ServeError(
                f"serve request for {model_id} gave up after {budget:.1f}s "
                f"waiting on the batch queue (serve_queue_timeout_s)")

    def close(self):
        """Stop the worker; pending futures fail with a named error."""
        with self._cond:
            self._closed = True
            leftovers = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        for req in leftovers:
            req.future.set_exception(ServeError("serve queue closed "
                                                "before dispatch"))
        self._worker.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------ worker
    def _take_batch(self):
        """Wait for work, honor the delay window, then drain every pending
        request that matches the oldest one's (model, raw) key up to
        ``max_batch`` rows.  Returns a list of requests or None on close."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return None
            head = self._pending[0]
            deadline = head.t_enqueue + self.max_delay_s
            while not self._closed:
                rows = sum(r.X.shape[0] for r in self._pending
                           if r.model_id == head.model_id
                           and r.raw_score == head.raw_score)
                remaining = deadline - time.perf_counter()
                if rows >= self.max_batch or remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch, keep, rows = [], deque(), 0
            for r in self._pending:
                if (r.model_id == head.model_id
                        and r.raw_score == head.raw_score
                        and rows < self.max_batch):
                    batch.append(r)
                    rows += r.X.shape[0]
                else:
                    keep.append(r)
            self._pending = keep
            return batch

    def _run(self):
        while True:
            try:
                batch = self._take_batch()
            except Exception:
                continue
            if batch is None:
                return
            t_dispatch = time.perf_counter()
            for r in batch:
                TELEMETRY.record_dispatch("serve/queue_wait",
                                          r.t_enqueue, t_dispatch)
            X = batch[0].X if len(batch) == 1 else \
                np.concatenate([r.X for r in batch])
            try:
                res = self.predictor.predict(batch[0].model_id, X,
                                             raw_score=batch[0].raw_score)
                slices = []
                done = 0
                for r in batch:
                    n = r.X.shape[0]
                    slices.append(res[done: done + n])
                    done += n
            except Exception as exc:
                for r in batch:
                    r.future.set_exception(exc)
                continue
            for r, out in zip(batch, slices):
                r.future.set_result(out)
