"""On-device binning of raw float requests at predict time.

Training quantizes features once on the host (core/binning.py
``BinMapper.value_to_bin``); a prediction service cannot afford a host
pass per request, so the per-feature bin bounds are uploaded ONCE per
model and every request batch is binned on device: one vmapped
``searchsorted`` over the padded ``[F, max_bin]`` upper-bound table,
with the reference missing semantics (``MISSING_NAN`` routes NaN to the
trailing NaN bin, ``MISSING_ZERO`` falls out naturally because zero
lands in ``default_bin``) and categorical lookup as a second
searchsorted over the sorted (category, bin) table.

The device result matches ``value_to_bin`` bit-for-bit on every value
that is not within one float32 ulp of a bin boundary: bounds are
midpoints between observed training values, so real feature values sit
strictly inside their bins and the f32 round-trip cannot move them.
One deliberate difference: unseen categories bin to -1 instead of
``value_to_bin``'s num_bin-1 (which aliases a real category's bin), so
routing can match the host float walk's unseen -> right rule.

Tables are plain numpy here; serve/registry.py stacks the tables of
every resident model into the shared ``[M, F, ...]`` device pack and
serve/predictor.py fuses ``bin_rows`` with the tree routing into one
compiled executable per (model, batch bucket).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.binning import MISSING_NAN

# categorical pad sentinel: larger than any int32 category, keeps the
# padded tail sorted so searchsorted never lands on a pad slot for a
# real category
_CAT_PAD = np.int32(2**31 - 1)


def build_tables(bin_mappers: List, used_feature_indices) -> Dict[str, np.ndarray]:
    """Per-used-feature binning tables for one model (host numpy).

    Keys (F = number of used features):
      src_col    [F] i32  original column in the raw request matrix
      bounds     [F, B] f32  numerical upper bounds, +inf padded; the
                 searchable prefix is ``value_to_bin``'s
                 ``ub[:n_search-1]`` so a plain searchsorted over the
                 padded row reproduces the host result exactly
      num_bin    [F] i32
      default_bin[F] i32
      missing_type [F] i32
      is_cat     [F] bool
      cat_vals   [F, C] i32  sorted category values, _CAT_PAD padded
      cat_bins   [F, C] i32  bin of the matching category slot
    """
    used = np.asarray(used_feature_indices, dtype=np.int32)
    F = len(used)
    mappers = [bin_mappers[int(f)] for f in used]
    n_bounds = 1
    n_cats = 1
    for m in mappers:
        if m.is_categorical:
            n_cats = max(n_cats, len(m.bin_2_categorical))
        else:
            n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN else 0)
            n_bounds = max(n_bounds, max(n_search - 1, 0))
    bounds = np.full((F, n_bounds), np.inf, dtype=np.float32)
    cat_vals = np.full((F, n_cats), _CAT_PAD, dtype=np.int32)
    cat_bins = np.zeros((F, n_cats), dtype=np.int32)
    num_bin = np.zeros(F, dtype=np.int32)
    default_bin = np.zeros(F, dtype=np.int32)
    missing_type = np.zeros(F, dtype=np.int32)
    is_cat = np.zeros(F, dtype=bool)
    for j, m in enumerate(mappers):
        num_bin[j] = m.num_bin
        default_bin[j] = m.default_bin
        missing_type[j] = m.missing_type
        is_cat[j] = m.is_categorical
        if m.is_categorical:
            if m.categorical_2_bin:
                cats = np.fromiter(m.categorical_2_bin.keys(), dtype=np.int64)
                bins_ = np.fromiter(m.categorical_2_bin.values(),
                                    dtype=np.int64)
                order = np.argsort(cats)
                k = len(cats)
                cat_vals[j, :k] = cats[order].astype(np.int32)
                cat_bins[j, :k] = bins_[order].astype(np.int32)
        else:
            n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN
                                    else 0)
            k = max(n_search - 1, 0)
            if k:
                bounds[j, :k] = np.asarray(m.bin_upper_bound[:k],
                                           dtype=np.float32)
    return {"src_col": used, "bounds": bounds, "num_bin": num_bin,
            "default_bin": default_bin, "missing_type": missing_type,
            "is_cat": is_cat, "cat_vals": cat_vals, "cat_bins": cat_bins}


def tables_nbytes(tables: Dict[str, np.ndarray]) -> int:
    return int(sum(int(a.nbytes) for a in tables.values()))


def bin_rows(tables, X):
    """Jittable: raw float rows ``[B, n_raw_features]`` -> unbundled
    bins ``[B, F_used]`` i32 (feed tree routing with ``feat_group=None``).

    ``tables`` holds the (device) arrays from :func:`build_tables` —
    per-model slices when the registry packs multiple models.
    """
    import jax
    import jax.numpy as jnp

    Xu = jnp.take(X, tables["src_col"], axis=1).astype(jnp.float32)

    def one_feature(bounds_f, cats_f, catbins_f, nb_f, mt_f,
                    iscat_f, col):
        nan = jnp.isnan(col)
        v = jnp.where(nan, jnp.float32(0.0), col)
        nbin = jnp.searchsorted(bounds_f, v, side="left").astype(jnp.int32)
        nbin = jnp.where(nan & (mt_f == MISSING_NAN), nb_f - 1, nbin)
        # categorical: non-finite -> -1 -> miss; float truncates toward
        # zero exactly like the host int cast.  Misses bin to -1 (not
        # value_to_bin's num_bin-1, which aliases a REAL category's bin):
        # the router treats negative categorical bins as "not in set",
        # matching the host float walk's unseen/negative/NaN -> right
        ivf = jnp.where(jnp.isfinite(col), col, jnp.float32(-1.0))
        iv = jnp.clip(ivf, -1.0, 2.0**30).astype(jnp.int32)
        pos = jnp.clip(jnp.searchsorted(cats_f, iv), 0,
                       cats_f.shape[0] - 1)
        hit = (cats_f[pos] == iv) & (iv >= 0)
        cbin = jnp.where(hit, catbins_f[pos],
                         jnp.int32(-1)).astype(jnp.int32)
        return jnp.where(iscat_f, cbin, nbin)

    return jax.vmap(one_feature,
                    in_axes=(0, 0, 0, 0, 0, 0, 1),
                    out_axes=1)(
        tables["bounds"], tables["cat_vals"], tables["cat_bins"],
        tables["num_bin"], tables["missing_type"],
        tables["is_cat"], Xu)


def bin_occupancy(tables, bins, n_valid, num_bin_axis: int):
    """Jittable: per-feature occupancy counts ``[F, num_bin_axis]`` i32
    of already-binned rows ``[B, F]`` — the drift plane's data feed.

    Rows at index >= ``n_valid`` are bucket padding and are masked
    out, so the counts describe exactly the replied rows.  Unseen
    categoricals arrive as the -1 sentinel (see :func:`bin_rows`) and
    are counted into the feature's LAST bin, which is where the host
    ``value_to_bin`` puts them in the training binned matrix — serve
    occupancy stays comparable with a baseline counted from that
    matrix.  ``n_valid`` is a traced scalar: one executable per bucket
    serves every partial batch in it.
    """
    import jax.numpy as jnp

    nb = tables["num_bin"]
    counted = jnp.where(bins < 0, nb[None, :] - 1, bins)
    valid = jnp.arange(bins.shape[0]) < n_valid
    hits = (counted[:, :, None] ==
            jnp.arange(num_bin_axis)[None, None, :]) & valid[:, None, None]
    return hits.sum(axis=0).astype(jnp.int32)
