"""lightgbm_tpu.serve — compiled, micro-batching, multi-model prediction.

Layering (each file usable on its own):

  registry.py   multi-model residency: shared [M, T, ...] device pack
                under the HBM budget, admission control, eviction,
                zero-downtime hot swap (per-model pack epochs, quality
                gate, retained-generation rollback)
  binning.py    on-device binning of raw float requests (tables built
                from the training BinMappers, uploaded once per model)
  predictor.py  executable cache keyed (model_id, epoch, batch bucket);
                pow2 shape bucketing, CostJit-compiled, host f64 gather,
                snapshot-pinned dispatch, OOM-halving retry ladder
  queue.py      request micro-batching with per-request futures, the
                serve_max_delay_ms / serve_max_batch knobs and
                serve_max_queue_rows load shedding
  refit_loop.py the closed trainer→server loop: DriftGate poll →
                Booster.refit on fresh labels → quality-gated swap
  health.py     serve health stream: serve_start/serve_window/
                serve_admit/serve_drift/serve_fault/swap_*/serve_refit/
                serve_summary JSONL records (serve_health_out= /
                LIGHTGBM_TPU_SERVE_HEALTH_JSONL)

``drift_detect=true`` additionally wires the model-and-data drift
plane (obs/drift.py) through all four layers: training baselines are
captured at load, the predictor's compiled executables return the
per-feature bin occupancy of every replied batch, windows emit
``serve_drift`` records, and ``session.drift_gate.drifted(model_id)``
is the pollable refit trigger — consumed by ``start_refit_loop()``.

``ServeSession`` wires them together; ``Booster.serve()`` (basic.py)
is the one-liner entry point returning a handle bound to that
booster's model.  See docs/SERVING.md.
"""

from __future__ import annotations

import os
from concurrent.futures import Future

import numpy as np

from ..utils.telemetry import TELEMETRY
from .health import SERVE_HEALTH_ENV, ServeHealth, resolve_serve_health_path
from .predictor import MIN_BUCKET, BucketedPredictor
from .queue import MicroBatchQueue
from .refit_loop import RefitLoop
from .registry import (ModelRegistry, ServeAdmissionError, ServeError,
                       ServeOverloadError, SwapRejectedError,
                       SERVE_ADMIT_FRACTION)

__all__ = [
    "ModelRegistry", "BucketedPredictor", "MicroBatchQueue",
    "ServeSession", "ServeHandle", "ServeHealth", "ServeError",
    "ServeAdmissionError", "ServeOverloadError", "SwapRejectedError",
    "RefitLoop", "SERVE_ADMIT_FRACTION", "MIN_BUCKET",
    "SERVE_HEALTH_ENV", "resolve_serve_health_path",
]


def _gate_metric(pred: np.ndarray, label: np.ndarray) -> float:
    """Holdout metric for the swap quality gate: error rate for
    multiclass probability outputs, mean squared error otherwise
    (objective-agnostic; only RELATIVE candidate-vs-incumbent movement
    is gated, so the unit does not matter)."""
    y = np.asarray(label, dtype=np.float64).ravel()
    p = np.asarray(pred, dtype=np.float64)
    if p.ndim == 2 and p.shape[1] > 1:
        return float(np.mean(np.argmax(p, axis=1) != y))
    return float(np.mean((p.ravel() - y) ** 2))


class ServeSession:
    """One registry + predictor + queue; hosts any number of models.

    ``health_out=`` (the ``serve_health_out`` config parameter; env
    ``LIGHTGBM_TPU_SERVE_HEALTH_JSONL`` wins over both) opens the
    session's own serve health stream — a private writer, never the
    training ``HEALTH`` instance, so serving cannot touch a training
    run's stream or its models."""

    def __init__(self, max_batch: int = 256, max_delay_ms: float = 2.0,
                 queue_timeout_s: float = 30.0,
                 max_queue_rows: int = 65536,
                 admit_fraction: float = SERVE_ADMIT_FRACTION,
                 health_out: str = "", health_window_s: float = 5.0,
                 drift_detect: bool = False,
                 drift_psi_threshold: float = 0.2, drift_topk: int = 5,
                 swap_quality_threshold: float = 0.1,
                 refit_poll_s: float = 30.0):
        path = resolve_serve_health_path(override=health_out)
        self.health = None
        if path:
            self.health = ServeHealth(
                path, window_s=health_window_s,
                meta={"pid": os.getpid(), "max_batch": int(max_batch),
                      "max_delay_ms": float(max_delay_ms)})
        TELEMETRY.gauge_set("serve/max_batch", int(max_batch))
        self.swap_quality_threshold = float(swap_quality_threshold)
        self.refit_poll_s = float(refit_poll_s)
        # model-and-data drift plane (obs/drift.py): baseline capture
        # at load, occupancy/score accumulation in the predictor, one
        # serve_drift record per window, DriftGate as the refit trigger
        self.drift = None
        self.drift_gate = None
        if drift_detect:
            from ..obs.drift import DriftAccumulator, DriftGate
            self.drift = DriftAccumulator(
                psi_threshold=drift_psi_threshold, topk=drift_topk)
            self.drift_gate = DriftGate(self.drift)
        if self.health is not None:
            self.health.drift = self.drift
        self.registry = ModelRegistry(max_batch=max_batch,
                                      admit_fraction=admit_fraction)
        self.registry.health = self.health
        self.registry.drift = self.drift
        self.predictor = BucketedPredictor(self.registry,
                                           max_batch=max_batch)
        self.predictor.health = self.health
        self.predictor.drift = self.drift
        self.queue = MicroBatchQueue(self.predictor,
                                     max_delay_ms=max_delay_ms,
                                     max_batch=max_batch,
                                     queue_timeout_s=queue_timeout_s,
                                     health=self.health,
                                     max_queue_rows=max_queue_rows)
        self.queue.drift = self.drift
        self._refit_loops = []

    @classmethod
    def from_config(cls, config, **overrides):
        """Knobs from a Config (serve_max_batch, serve_max_delay_ms,
        serve_queue_timeout_s, serve_max_queue_rows, serve_health_out,
        serve_health_window_s, drift_detect, drift_psi_threshold,
        drift_topk, swap_quality_threshold, refit_poll_s), keyword
        overrides winning.  Overrides accept both the constructor names
        (``max_batch``) and the config-parameter spellings
        (``serve_max_batch``)."""
        kw = {}
        if config is not None:
            kw = {"max_batch": config.serve_max_batch,
                  "max_delay_ms": config.serve_max_delay_ms,
                  "queue_timeout_s": config.serve_queue_timeout_s,
                  "max_queue_rows": getattr(config,
                                            "serve_max_queue_rows", 65536),
                  "health_out": getattr(config, "serve_health_out", ""),
                  "health_window_s": getattr(config,
                                             "serve_health_window_s", 5.0),
                  "drift_detect": bool(getattr(config, "drift_detect",
                                               False)),
                  "drift_psi_threshold": getattr(config,
                                                 "drift_psi_threshold",
                                                 0.2),
                  "drift_topk": getattr(config, "drift_topk", 5),
                  "swap_quality_threshold": getattr(
                      config, "swap_quality_threshold", 0.1),
                  "refit_poll_s": getattr(config, "refit_poll_s", 30.0)}
        for k, v in overrides.items():
            kw[k[6:] if k.startswith("serve_") else k] = v
        return cls(**kw)

    def load(self, booster, model_id: str = None,
             num_iteration: int = -1) -> str:
        return self.registry.load(booster, model_id=model_id,
                                  num_iteration=num_iteration)

    def evict(self, model_id: str) -> None:
        # fail still-queued requests for the id FIRST (named error, no
        # pack-shape surprise at dispatch), then drop the residency
        self.queue.evict_pending(model_id)
        self.registry.evict(model_id)

    # ------------------------------------------------------------ hot swap
    def swap(self, model_id: str, booster, num_iteration: int = -1,
             holdout=None, label=None, quality_threshold: float = None,
             gated: bool = True) -> float:
        """Zero-downtime replacement of a resident model.

        The default quality gate shadow-scores the candidate on
        ``holdout`` (or, when omitted, on the deterministic reservoir
        of recently served rows) and rejects on non-finite outputs or
        — when ``label`` is provided — on a holdout metric more than
        ``swap_quality_threshold`` worse than the incumbent's
        (:class:`SwapRejectedError`; the old model keeps serving).
        ``gated=False`` skips the gate for candidates already validated
        offline.  Returns the flip pause in seconds."""
        gate = None
        if gated:
            thr = self.swap_quality_threshold \
                if quality_threshold is None else float(quality_threshold)

            def gate(candidate_entry):
                return self._quality_gate(model_id, booster,
                                          candidate_entry, holdout,
                                          label, thr, num_iteration)
        return self.registry.swap(model_id, booster,
                                  num_iteration=num_iteration, gate=gate)

    def rollback(self, model_id: str) -> float:
        """Restore the generation the last swap replaced (one call,
        same atomic flip)."""
        return self.registry.rollback(model_id)

    def _quality_gate(self, model_id, booster, candidate_entry, holdout,
                      label, threshold, num_iteration):
        """(ok, detail) for one swap candidate: finiteness always;
        metric regression vs the incumbent when labels are available.
        Incumbent scores come through the serve path itself
        (bit-identical to Booster.predict of the live generation)."""
        X = holdout if holdout is not None \
            else self.registry.replay_rows(model_id)
        if X is None or len(X) == 0:
            return True, ("no holdout rows available yet; "
                          "finiteness gate skipped")
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X)),
                                 dtype=np.float32)
        cand = np.asarray(booster.predict(X, num_iteration=num_iteration))
        if not np.all(np.isfinite(cand)):
            return False, (f"candidate produced non-finite outputs on "
                           f"{X.shape[0]} holdout rows")
        inc = np.asarray(self.predict_direct(model_id, X))
        if cand.shape != inc.shape:
            return False, (f"candidate output shape {cand.shape} does "
                           f"not match the incumbent's {inc.shape}")
        if label is None:
            return True, (f"finite on {X.shape[0]} holdout rows "
                          f"(no labels; metric gate skipped)")
        cand_m = _gate_metric(cand, label)
        inc_m = _gate_metric(inc, label)
        if cand_m > inc_m * (1.0 + threshold) + 1e-12:
            return False, (
                f"holdout metric regressed: candidate {cand_m:.6g} vs "
                f"incumbent {inc_m:.6g} on {X.shape[0]} rows (more than "
                f"{threshold:.0%} worse; swap_quality_threshold)")
        return True, (f"holdout metric {cand_m:.6g} vs incumbent "
                      f"{inc_m:.6g} on {X.shape[0]} rows (within "
                      f"{threshold:.0%})")

    def start_refit_loop(self, model_id: str, booster, data_source,
                         **kwargs) -> RefitLoop:
        """Start the background drift→refit→swap loop for one model
        (see serve/refit_loop.py).  Defaults: ``poll_s`` from
        ``refit_poll_s``, the gate threshold from
        ``swap_quality_threshold``.  The loop is stopped by
        ``close()``."""
        kwargs.setdefault("poll_s", self.refit_poll_s)
        kwargs.setdefault("quality_threshold",
                          self.swap_quality_threshold)
        loop = RefitLoop(self, model_id, booster, data_source, **kwargs)
        self._refit_loops.append(loop)
        return loop.start()

    def submit(self, model_id: str, X, raw_score: bool = False) -> Future:
        return self.queue.submit(model_id, X, raw_score=raw_score)

    def predict(self, model_id: str, X, raw_score: bool = False,
                timeout: float = None):
        """Micro-batched prediction (blocks on the request's future)."""
        return self.queue.predict(model_id, X, raw_score=raw_score,
                                  timeout=timeout)

    def predict_direct(self, model_id: str, X, raw_score: bool = False):
        """Bypass the queue: same compiled bucketed path, synchronous."""
        return self.predictor.predict(model_id, X, raw_score=raw_score)

    def close(self):
        for loop in self._refit_loops:
            loop.stop()
        self._refit_loops = []
        self.queue.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ServeHandle:
    """A session bound to one model id — what ``Booster.serve`` returns.

    ``handle.session`` is the underlying :class:`ServeSession`; load
    more boosters into it to share the device pack and the queue."""

    def __init__(self, session: ServeSession, model_id: str,
                 owns_session: bool = True):
        self.session = session
        self.model_id = model_id
        self._owns = owns_session

    def predict(self, X, raw_score: bool = False, timeout: float = None):
        return self.session.predict(self.model_id, X,
                                    raw_score=raw_score, timeout=timeout)

    def predict_direct(self, X, raw_score: bool = False):
        return self.session.predict_direct(self.model_id, X,
                                           raw_score=raw_score)

    def submit(self, X, raw_score: bool = False) -> Future:
        return self.session.submit(self.model_id, X, raw_score=raw_score)

    def swap(self, booster, **kwargs) -> float:
        """Hot-swap this handle's model (``ServeSession.swap``)."""
        return self.session.swap(self.model_id, booster, **kwargs)

    def rollback(self) -> float:
        return self.session.rollback(self.model_id)

    def close(self):
        if self._owns:
            self.session.close()
        else:
            self.session.evict(self.model_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
