"""lightgbm_tpu.serve — compiled, micro-batching, multi-model prediction.

Layering (each file usable on its own):

  registry.py   multi-model residency: shared [M, T, ...] device pack
                under the HBM budget, admission control, eviction
  binning.py    on-device binning of raw float requests (tables built
                from the training BinMappers, uploaded once per model)
  predictor.py  executable cache keyed (model_id, batch bucket);
                pow2 shape bucketing, CostJit-compiled, host f64 gather
  queue.py      request micro-batching with per-request futures and the
                serve_max_delay_ms / serve_max_batch knob
  health.py     serve health stream: serve_start/serve_window/
                serve_admit/serve_drift/serve_fault/serve_summary
                JSONL records (serve_health_out= /
                LIGHTGBM_TPU_SERVE_HEALTH_JSONL)

``drift_detect=true`` additionally wires the model-and-data drift
plane (obs/drift.py) through all four layers: training baselines are
captured at load, the predictor's compiled executables return the
per-feature bin occupancy of every replied batch, windows emit
``serve_drift`` records, and ``session.drift_gate.drifted(model_id)``
is the pollable refit trigger.

``ServeSession`` wires them together; ``Booster.serve()`` (basic.py)
is the one-liner entry point returning a handle bound to that
booster's model.  See docs/SERVING.md.
"""

from __future__ import annotations

import os
from concurrent.futures import Future

from ..utils.telemetry import TELEMETRY
from .health import SERVE_HEALTH_ENV, ServeHealth, resolve_serve_health_path
from .predictor import MIN_BUCKET, BucketedPredictor
from .queue import MicroBatchQueue
from .registry import (ModelRegistry, ServeAdmissionError, ServeError,
                       SERVE_ADMIT_FRACTION)

__all__ = [
    "ModelRegistry", "BucketedPredictor", "MicroBatchQueue",
    "ServeSession", "ServeHandle", "ServeHealth", "ServeError",
    "ServeAdmissionError", "SERVE_ADMIT_FRACTION", "MIN_BUCKET",
    "SERVE_HEALTH_ENV", "resolve_serve_health_path",
]


class ServeSession:
    """One registry + predictor + queue; hosts any number of models.

    ``health_out=`` (the ``serve_health_out`` config parameter; env
    ``LIGHTGBM_TPU_SERVE_HEALTH_JSONL`` wins over both) opens the
    session's own serve health stream — a private writer, never the
    training ``HEALTH`` instance, so serving cannot touch a training
    run's stream or its models."""

    def __init__(self, max_batch: int = 256, max_delay_ms: float = 2.0,
                 queue_timeout_s: float = 30.0,
                 admit_fraction: float = SERVE_ADMIT_FRACTION,
                 health_out: str = "", health_window_s: float = 5.0,
                 drift_detect: bool = False,
                 drift_psi_threshold: float = 0.2, drift_topk: int = 5):
        path = resolve_serve_health_path(override=health_out)
        self.health = None
        if path:
            self.health = ServeHealth(
                path, window_s=health_window_s,
                meta={"pid": os.getpid(), "max_batch": int(max_batch),
                      "max_delay_ms": float(max_delay_ms)})
        TELEMETRY.gauge_set("serve/max_batch", int(max_batch))
        # model-and-data drift plane (obs/drift.py): baseline capture
        # at load, occupancy/score accumulation in the predictor, one
        # serve_drift record per window, DriftGate as the refit trigger
        self.drift = None
        self.drift_gate = None
        if drift_detect:
            from ..obs.drift import DriftAccumulator, DriftGate
            self.drift = DriftAccumulator(
                psi_threshold=drift_psi_threshold, topk=drift_topk)
            self.drift_gate = DriftGate(self.drift)
        if self.health is not None:
            self.health.drift = self.drift
        self.registry = ModelRegistry(max_batch=max_batch,
                                      admit_fraction=admit_fraction)
        self.registry.health = self.health
        self.registry.drift = self.drift
        self.predictor = BucketedPredictor(self.registry,
                                           max_batch=max_batch)
        self.predictor.health = self.health
        self.predictor.drift = self.drift
        self.queue = MicroBatchQueue(self.predictor,
                                     max_delay_ms=max_delay_ms,
                                     max_batch=max_batch,
                                     queue_timeout_s=queue_timeout_s,
                                     health=self.health)
        self.queue.drift = self.drift

    @classmethod
    def from_config(cls, config, **overrides):
        """Knobs from a Config (serve_max_batch, serve_max_delay_ms,
        serve_queue_timeout_s, serve_health_out,
        serve_health_window_s, drift_detect, drift_psi_threshold,
        drift_topk), keyword overrides winning.  Overrides accept both
        the constructor names (``max_batch``) and the config-parameter
        spellings (``serve_max_batch``)."""
        kw = {}
        if config is not None:
            kw = {"max_batch": config.serve_max_batch,
                  "max_delay_ms": config.serve_max_delay_ms,
                  "queue_timeout_s": config.serve_queue_timeout_s,
                  "health_out": getattr(config, "serve_health_out", ""),
                  "health_window_s": getattr(config,
                                             "serve_health_window_s", 5.0),
                  "drift_detect": bool(getattr(config, "drift_detect",
                                               False)),
                  "drift_psi_threshold": getattr(config,
                                                 "drift_psi_threshold",
                                                 0.2),
                  "drift_topk": getattr(config, "drift_topk", 5)}
        for k, v in overrides.items():
            kw[k[6:] if k.startswith("serve_") else k] = v
        return cls(**kw)

    def load(self, booster, model_id: str = None,
             num_iteration: int = -1) -> str:
        return self.registry.load(booster, model_id=model_id,
                                  num_iteration=num_iteration)

    def evict(self, model_id: str) -> None:
        self.registry.evict(model_id)

    def submit(self, model_id: str, X, raw_score: bool = False) -> Future:
        return self.queue.submit(model_id, X, raw_score=raw_score)

    def predict(self, model_id: str, X, raw_score: bool = False,
                timeout: float = None):
        """Micro-batched prediction (blocks on the request's future)."""
        return self.queue.predict(model_id, X, raw_score=raw_score,
                                  timeout=timeout)

    def predict_direct(self, model_id: str, X, raw_score: bool = False):
        """Bypass the queue: same compiled bucketed path, synchronous."""
        return self.predictor.predict(model_id, X, raw_score=raw_score)

    def close(self):
        self.queue.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ServeHandle:
    """A session bound to one model id — what ``Booster.serve`` returns.

    ``handle.session`` is the underlying :class:`ServeSession`; load
    more boosters into it to share the device pack and the queue."""

    def __init__(self, session: ServeSession, model_id: str,
                 owns_session: bool = True):
        self.session = session
        self.model_id = model_id
        self._owns = owns_session

    def predict(self, X, raw_score: bool = False, timeout: float = None):
        return self.session.predict(self.model_id, X,
                                    raw_score=raw_score, timeout=timeout)

    def predict_direct(self, X, raw_score: bool = False):
        return self.session.predict_direct(self.model_id, X,
                                           raw_score=raw_score)

    def submit(self, X, raw_score: bool = False) -> Future:
        return self.session.submit(self.model_id, X, raw_score=raw_score)

    def close(self):
        if self._owns:
            self.session.close()
        else:
            self.session.evict(self.model_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
