"""Build lib_lightgbm_tpu.so — the native C API shared library.

The reference ships lib_lightgbm.so built by CMake
(CMakeLists.txt); here the equivalent artifact is compiled from
src/capi/c_api.cpp with the system g++, embedding CPython so the library
works both linked into a C host program and loaded via ctypes from
Python (the python package's own binding path).

Usage:
    python -m lightgbm_tpu.build_capi [output_dir]
or programmatically: build_capi() -> path to the .so (cached).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(here, "src", "capi", "c_api.cpp")


def lib_path(out_dir: str | None = None) -> str:
    if out_dir is None:
        out_dir = os.path.join(os.path.dirname(_source_path()))
    return os.path.join(out_dir, "lib_lightgbm_tpu.so")


def build_capi(out_dir: str | None = None, force: bool = False) -> str:
    src = _source_path()
    out = lib_path(out_dir)
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ldlib = sysconfig.get_config_var("LDLIBRARY") or ""
    pyver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
        f"-I{include}", src, "-o", out,
    ]
    if libdir:
        cmd.insert(-2, f"-L{libdir}")
        cmd.insert(-2, f"-Wl,-rpath,{libdir}")
    # link libpython so a pure-C host gets the interpreter; when loaded
    # from Python via ctypes the symbols are already present and the
    # dependency is satisfied trivially
    abiflags = sysconfig.get_config_var("ABIFLAGS") or ""
    cmd.insert(-2, f"-l{pyver}{abiflags}")
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"building lib_lightgbm_tpu.so failed:\n{' '.join(cmd)}\n"
            f"{proc.stderr[-2000:]}")
    return out


if __name__ == "__main__":
    out_dir = sys.argv[1] if len(sys.argv) > 1 else None
    print(build_capi(out_dir, force=True))
