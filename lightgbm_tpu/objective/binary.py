"""Binary log-loss objective.

Reference: src/objective/binary_objective.hpp:21-180 — labels converted to
±1, sigmoid-scaled logistic gradients, is_unbalance / scale_pos_weight label
weighting, boost-from-average in log-odds.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import check, log_info
from .base import ObjectiveFunction


class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        check(self.sigmoid > 0, "sigmoid parameter must be positive")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label_np
        # the reference accepts ANY labels: positive <=> label > 0
        # (binary_objective.hpp:35 is_pos default)
        is_pos = lab > 0
        cnt_pos = int(is_pos.sum())
        cnt_neg = int(self.num_data - cnt_pos)
        if cnt_neg == 0 or cnt_pos == 0:
            log_info("Contains only one class")
        # is_unbalance: weight each class by the other's frequency
        # (binary_objective.hpp:60-80)
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self.label_weights = (1.0, cnt_pos / cnt_neg)
            else:
                self.label_weights = (cnt_neg / cnt_pos, 1.0)
        else:
            self.label_weights = (1.0, float(self.config.scale_pos_weight))
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg
        self.sign_label = jnp.asarray(np.where(is_pos, 1.0, -1.0),
                                      dtype=jnp.float32)
        w_pos, w_neg = self.label_weights[1], self.label_weights[0]
        self.label_weight_arr = jnp.asarray(
            np.where(is_pos, w_pos, w_neg), dtype=jnp.float32)

    def get_gradients(self, score):
        s = self.sigmoid
        y = self.sign_label
        response = -y * s / (1.0 + jnp.exp(y * s * score))
        abs_response = jnp.abs(response)
        grad = response * self.label_weight_arr
        hess = abs_response * (s - abs_response) * self.label_weight_arr
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        """log-odds of the (weighted) positive rate / sigmoid
        (binary_objective.hpp:131-150)."""
        if self.weights_np is not None:
            suml = float(np.sum((self.label_np > 0) * self.weights_np))
            sumw = float(np.sum(self.weights_np))
        else:
            suml = float(self.cnt_pos)
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, 1e-10), 1e-10), 1.0 - 1e-10)
        init = np.log(pavg / (1.0 - pavg)) / self.sigmoid
        log_info(f"[binary:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={init:.6f}")
        return float(init)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))
