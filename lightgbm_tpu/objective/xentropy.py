"""Cross-entropy objectives for probabilistic labels in [0, 1].

Reference: src/objective/xentropy_objective.hpp:44-146 (CrossEntropy: logistic
link, optional weights act as exposure) and :148-260 (CrossEntropyLambda:
log(1+exp) link with weight-aware gradients).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction


class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label_np
        if lab.min() < 0 or lab.max() > 1:
            raise ValueError("[cross_entropy]: labels must be in [0, 1]")

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        if self.weights is None:
            grad = z - self.label
            hess = z * (1.0 - z)
        else:
            grad = (z - self.label) * self.weights
            hess = z * (1.0 - z) * self.weights
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            p = (np.sum(self.label_np * self.weights_np)
                 / np.sum(self.weights_np))
        else:
            p = float(np.mean(self.label_np))
        p = min(max(p, 1e-10), 1 - 1e-10)
        return float(np.log(p / (1.0 - p)))

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label_np
        if lab.min() < 0 or lab.max() > 1:
            raise ValueError("[cross_entropy_lambda]: labels must be in [0, 1]")

    def get_gradients(self, score):
        """Weight-aware log(1+exp) link (xentropy_objective.hpp:185-213);
        without weights, identical to CrossEntropy."""
        if self.weights is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - self.label, z * (1.0 - z)
        w = self.weights
        y = self.label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d = c - 1.0
        b = (c / (d * d)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id=0):
        """initscore = log(exp(havg) - 1) (xentropy_objective.hpp:254-257)."""
        if self.weights_np is not None:
            havg = (np.sum(self.label_np * self.weights_np)
                    / np.sum(self.weights_np))
        else:
            havg = float(np.mean(self.label_np))
        return float(np.log(max(np.expm1(havg), 1e-20)))

    def convert_output(self, score):
        return np.log1p(np.exp(score))
