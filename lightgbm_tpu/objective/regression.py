"""Regression objective family.

Reference: src/objective/regression_objective.hpp — L2 (:78, with reg_sqrt),
L1 (:189, weighted-median leaf renewal), Huber (:275), Fair (:337), Poisson
(:384, log link), Quantile (:464, quantile leaf renewal), MAPE (:562), Gamma
(:661), Tweedie (:696).  Formulas follow each GetGradients verbatim; leaf
renewal uses the reference's (weighted) percentile definitions
(regression_objective.hpp:19-75).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction, percentile, weighted_percentile


def _renew_by_percentile(obj, leaf_values, leaf_ids, score, alpha,
                         extra_weight=None):
    """Per-leaf residual percentile refit (RenewTreeOutput for L1-family).

    The reference walks each leaf's data indices and computes a percentile of
    (label - score); here leaf membership comes from the grower's leaf_id
    vector."""
    label = obj.label_np
    residual = label - score
    w = obj.weights_np
    if extra_weight is not None:
        w = extra_weight if w is None else w * extra_weight
    out = np.array(leaf_values, dtype=np.float64)
    for leaf in range(len(out)):
        sel = leaf_ids == leaf
        if not sel.any():
            continue
        r = residual[sel]
        if w is None:
            out[leaf] = percentile(r, alpha)
        else:
            out[leaf] = weighted_percentile(r, w[sel], alpha)
    return out


class RegressionL2Loss(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            self.trans_label_np = (np.sign(self.label_np)
                                   * np.sqrt(np.abs(self.label_np)))
            self.trans_label = jnp.asarray(self.trans_label_np,
                                           dtype=jnp.float32)
        else:
            self.trans_label_np = self.label_np
            self.trans_label = self.label
        self.is_constant_hessian = self.weights is None

    def get_gradients(self, score):
        grad = score - self.trans_label
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            avg = (np.sum(self.trans_label_np * self.weights_np)
                   / np.sum(self.weights_np))
        else:
            avg = float(np.mean(self.trans_label_np))
        return float(avg)

    def convert_output(self, score):
        if self.sqrt:
            return np.sign(score) * score * score
        return score


class RegressionL1Loss(ObjectiveFunction):
    name = "regression_l1"
    is_constant_hessian = True
    is_renew_tree_output = True

    def get_gradients(self, score):
        grad = jnp.sign(score - self.label)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            return weighted_percentile(self.label_np.astype(np.float64),
                                       self.weights_np, 0.5)
        return percentile(self.label_np.astype(np.float64), 0.5)

    def renew_tree_output(self, leaf_values, leaf_ids, score):
        return _renew_by_percentile(self, leaf_values, leaf_ids, score, 0.5)


class RegressionHuberLoss(RegressionL2Loss):
    """Huber loss (regression_objective.hpp:275); inherits L2's
    boost-from-average."""
    name = "huber"
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.alpha = float(self.config.alpha)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def convert_output(self, score):
        return score


class RegressionFairLoss(RegressionL2Loss):
    """Fair loss (regression_objective.hpp:337)."""
    name = "fair"
    is_constant_hessian = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.c = float(self.config.fair_c)

    def get_gradients(self, score):
        x = score - self.label
        c = self.c
        grad = c * x / (jnp.abs(x) + c)
        hess = c * c / (jnp.abs(x) + c) ** 2
        return self._apply_weights(grad, hess)


class RegressionPoissonLoss(ObjectiveFunction):
    name = "poisson"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self.label_np < 0):
            raise ValueError("[poisson]: at least one target label is negative")
        self.max_delta_step = float(self.config.poisson_max_delta_step)

    def get_gradients(self, score):
        grad = jnp.exp(score) - self.label
        hess = jnp.exp(score + self.max_delta_step)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            avg = (np.sum(self.label_np * self.weights_np)
                   / np.sum(self.weights_np))
        else:
            avg = float(np.mean(self.label_np))
        return float(np.log(max(avg, 1e-20)))

    def convert_output(self, score):
        return np.exp(score)


class RegressionQuantileLoss(ObjectiveFunction):
    name = "quantile"
    is_constant_hessian = True
    is_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.alpha = float(self.config.alpha)

    def get_gradients(self, score):
        delta = score - self.label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            return weighted_percentile(self.label_np.astype(np.float64),
                                       self.weights_np, self.alpha)
        return percentile(self.label_np.astype(np.float64), self.alpha)

    def renew_tree_output(self, leaf_values, leaf_ids, score):
        return _renew_by_percentile(self, leaf_values, leaf_ids, score,
                                    self.alpha)


class RegressionMAPELoss(ObjectiveFunction):
    name = "mape"
    is_constant_hessian = True
    is_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_weight_np = 1.0 / np.maximum(1.0, np.abs(self.label_np))
        if self.weights_np is not None:
            self.label_weight_np = self.label_weight_np * self.weights_np
        self.label_weight = jnp.asarray(self.label_weight_np,
                                        dtype=jnp.float32)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = (jnp.ones_like(score) if self.weights is None
                else self.weights * jnp.ones_like(score))
        return grad, hess

    def boost_from_score(self, class_id=0):
        return weighted_percentile(self.label_np.astype(np.float64),
                                   self.label_weight_np, 0.5)

    def renew_tree_output(self, leaf_values, leaf_ids, score):
        label = self.label_np
        residual = label - score
        out = np.array(leaf_values, dtype=np.float64)
        for leaf in range(len(out)):
            sel = leaf_ids == leaf
            if sel.any():
                out[leaf] = weighted_percentile(
                    residual[sel], self.label_weight_np[sel], 0.5)
        return out


class RegressionGammaLoss(ObjectiveFunction):
    name = "gamma"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self.label_np <= 0):
            raise ValueError("[gamma]: labels must be positive")

    def get_gradients(self, score):
        grad = 1.0 - self.label * jnp.exp(-score)
        hess = self.label * jnp.exp(-score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            avg = (np.sum(self.label_np * self.weights_np)
                   / np.sum(self.weights_np))
        else:
            avg = float(np.mean(self.label_np))
        return float(np.log(max(avg, 1e-20)))

    def convert_output(self, score):
        return np.exp(score)


class RegressionTweedieLoss(ObjectiveFunction):
    name = "tweedie"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.rho = float(self.config.tweedie_variance_power)

    def get_gradients(self, score):
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        if self.weights_np is not None:
            avg = (np.sum(self.label_np * self.weights_np)
                   / np.sum(self.weights_np))
        else:
            avg = float(np.mean(self.label_np))
        return float(np.log(max(avg, 1e-20)))

    def convert_output(self, score):
        return np.exp(score)
