"""Objective function interface.

Reference: include/LightGBM/objective_function.h:20-80.  Objectives map the
current raw score to per-example (gradient, hessian) pairs; some additionally
provide a boost-from-average initial score (BoostFromScore), an output link
(ConvertOutput), and leaf-output renewal for percentile-fit losses
(IsRenewTreeOutput / RenewTreeOutput).

TPU design: ``get_gradients`` is a pure jnp function over device arrays
(label/weights captured at ``init``), so the GBDT driver can fuse it into the
per-iteration jit.  Shapes: score/grad/hess are ``[num_tree_per_iter, N]``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class ObjectiveFunction:
    name = "custom"
    num_tree_per_iteration = 1
    is_constant_hessian = False
    is_renew_tree_output = False
    need_group = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self.label_np = np.asarray(metadata.label)
        self.weights = (jnp.asarray(metadata.weights, dtype=jnp.float32)
                        if metadata.weights is not None else None)
        self.weights_np = metadata.weights

    # -- training--
    def get_gradients(self, score: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        """Initial raw score (gbdt.cpp:420 BoostFromAverage)."""
        return 0.0

    # -- prediction --
    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Link function applied for human-facing predictions."""
        return score

    # -- leaf renewal (L1/quantile/MAPE) --
    def renew_tree_output(self, leaf_values: np.ndarray, leaf_ids: np.ndarray,
                          score: np.ndarray) -> np.ndarray:
        """Recompute leaf outputs from residual percentiles.  ``leaf_ids`` is
        the per-row leaf assignment of the new tree; ``score`` the raw score
        BEFORE adding this tree.  Returns new leaf values."""
        return leaf_values

    def _apply_weights(self, grad, hess):
        if self.weights is not None:
            return grad * self.weights, hess * self.weights
        return grad, hess

    def __str__(self):
        return self.name


def percentile(values: np.ndarray, alpha: float) -> float:
    """Unweighted percentile matching the reference PercentileFun
    (regression_objective.hpp:19-44): position (1-alpha)*n counted from the
    TOP of the sorted order, linear interpolation by the fractional part."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    s = np.sort(values)[::-1]  # descending: pos counts from the max
    float_pos = (1.0 - alpha) * n
    pos = int(float_pos)
    if pos < 1:
        return float(s[0])
    if pos >= n:
        return float(s[-1])
    bias = float_pos - pos
    v1, v2 = float(s[pos - 1]), float(s[pos])
    return v1 - (v1 - v2) * bias


def weighted_percentile(values: np.ndarray, weights: np.ndarray,
                        alpha: float) -> float:
    """Weighted percentile matching WeightedPercentileFun
    (regression_objective.hpp:46-75)."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    order = np.argsort(values, kind="stable")
    v = values[order]
    cdf = np.cumsum(weights[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(v[pos])
    v1, v2 = float(v[pos - 1]), float(v[pos])
    if pos + 1 < n and cdf[pos + 1] - cdf[pos] >= 1.0:
        return (threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1
    return v2
