"""Objective factory.

Reference: ObjectiveFunction::CreateObjectiveFunction
(src/objective/objective_function.cpp:15-49).
"""

from __future__ import annotations

from ..utils.log import log_fatal
from .base import ObjectiveFunction
from .binary import BinaryLogloss
from .multiclass import MulticlassOVA, MulticlassSoftmax
from .rank import LambdarankNDCG
from .regression import (RegressionFairLoss, RegressionGammaLoss,
                         RegressionHuberLoss, RegressionL1Loss,
                         RegressionL2Loss, RegressionMAPELoss,
                         RegressionPoissonLoss, RegressionQuantileLoss,
                         RegressionTweedieLoss)
from .xentropy import CrossEntropy, CrossEntropyLambda

_REGISTRY = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config) -> ObjectiveFunction:
    name = str(config.objective).strip().lower()
    if name in ("none", "null", "custom", "na"):
        return None
    if name not in _REGISTRY:
        log_fatal(f"Unknown objective type name: {name}")
    return _REGISTRY[name](config)


__all__ = ["ObjectiveFunction", "create_objective"] + \
    [c.__name__ for c in _REGISTRY.values()]
