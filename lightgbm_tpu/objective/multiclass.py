"""Multiclass objectives: softmax (k trees per iteration) and one-vs-all.

Reference: src/objective/multiclass_objective.hpp:24-178 (MulticlassSoftmax:
softmax over per-class scores, grad = p - 1{y=k}, hess = 2 p (1-p);
boost-from-average uses log of class priors) and :180-260 (MulticlassOVA:
k independent binary objectives).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_tree_per_iteration = self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label_np.astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            raise ValueError(
                f"Label must be in [0, {self.num_class}) for multiclass")
        self.label_int = jnp.asarray(lab)
        onehot = np.zeros((self.num_class, self.num_data), dtype=np.float32)
        onehot[lab, np.arange(self.num_data)] = 1.0
        self.label_onehot = jnp.asarray(onehot)
        if self.weights_np is not None:
            probs = np.array([
                float(np.sum((lab == k) * self.weights_np))
                for k in range(self.num_class)])
            probs /= float(np.sum(self.weights_np))
        else:
            probs = np.bincount(lab, minlength=self.num_class) / self.num_data
        self.class_init_probs = probs

    def get_gradients(self, score):
        """score [C, N] -> grad/hess [C, N]."""
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        grad = p - self.label_onehot
        hess = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            grad = grad * self.weights[None, :]
            hess = hess * self.weights[None, :]
        return grad, hess

    def boost_from_score(self, class_id=0):
        """log of the class prior (multiclass_objective.hpp:150-152) —
        softmax of the inits reproduces the priors exactly."""
        return float(np.log(max(1e-15, self.class_init_probs[class_id])))

    def convert_output(self, score):
        """Softmax over classes; score [C, N] or [N, C]."""
        e = np.exp(score - np.max(score, axis=0, keepdims=True))
        return e / np.sum(e, axis=0, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_tree_per_iteration = self.num_class
        self.sigmoid = float(config.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label_np.astype(np.int32)
        self.binary_objs = []
        for k in range(self.num_class):
            sub = BinaryLogloss(self.config)
            meta_k = _BinaryView(np.where(lab == k, 1.0, 0.0).astype(np.float32),
                                 self.weights_np)
            sub.init(meta_k, num_data)
            self.binary_objs.append(sub)

    def get_gradients(self, score):
        grads, hesss = [], []
        for k in range(self.num_class):
            g, h = self.binary_objs[k].get_gradients(score[k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads), jnp.stack(hesss)

    def boost_from_score(self, class_id=0):
        return self.binary_objs[class_id].boost_from_score()

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))


class _BinaryView:
    def __init__(self, label, weights):
        self.label = label
        self.weights = weights
