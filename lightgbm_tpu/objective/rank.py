"""LambdaRank NDCG objective.

Reference: src/objective/rank_objective.hpp:23-230 — per-query pairwise
lambda gradients with delta-NDCG weighting, sigmoid-scaled logistic pair
probabilities, optional lambdamart normalization, label_gain table, and
inverse max-DCG truncated at ``max_position``.

TPU re-design: the reference's per-query OpenMP loop over O(n_q^2) pairs
(GetGradientsForOneQuery, rank_objective.hpp:83-182) becomes a masked
``[P, P]`` pairwise tensor computation vmapped over queries.  Queries are
bucketed by padded length (powers of two) so each bucket compiles once;
buckets are processed in fixed-size query chunks to bound the [C, P, P]
transient.  The sigmoid lookup table (rank_objective.hpp:199-225) is
unnecessary — the VPU evaluates exact sigmoids faster than a gather.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..utils.dcg import DCGCalculator
from ..utils.log import check
from .base import ObjectiveFunction


@functools.partial(jax.jit, static_argnames=("sigmoid", "norm"))
def _chunk_lambdas(scores, labels, mask, inv_max_dcg, gains, sigmoid: float,
                   norm: bool):
    """Pairwise lambdas for a chunk of queries.

    scores/labels/mask: [C, P]; inv_max_dcg: [C]; gains: label-gain table.
    Returns (lambdas [C, P], hessians [C, P]).
    """
    C, P = scores.shape
    neg_inf = jnp.float32(-1e30)
    s = jnp.where(mask, scores, neg_inf)
    order = jnp.argsort(-s, axis=1, stable=True)            # [C, P]
    rank = jnp.zeros_like(order).at[
        jnp.arange(C)[:, None], order].set(jnp.arange(P)[None, :])
    disc = 1.0 / jnp.log2(2.0 + rank.astype(jnp.float32))   # [C, P]
    g = gains[labels]                                        # [C, P]

    sa = s[:, :, None]
    sb = s[:, None, :]
    pair_ok = (mask[:, :, None] & mask[:, None, :]
               & (labels[:, :, None] > labels[:, None, :]))
    delta = sa - sb
    dn = ((g[:, :, None] - g[:, None, :])
          * jnp.abs(disc[:, :, None] - disc[:, None, :])
          * inv_max_dcg[:, None, None])
    if norm:
        best = jnp.max(jnp.where(mask, scores, -jnp.inf), axis=1)
        worst = jnp.min(jnp.where(mask, scores, jnp.inf), axis=1)
        diff_bw = (best != worst)[:, None, None]
        dn = jnp.where(diff_bw & pair_ok, dn / (0.01 + jnp.abs(delta)), dn)
    sig = 1.0 / (1.0 + jnp.exp(sigmoid * delta))
    lam = -sigmoid * dn * sig
    hes = sigmoid * sigmoid * dn * sig * (1.0 - sig)
    lam = jnp.where(pair_ok, lam, 0.0)
    hes = jnp.where(pair_ok, hes, 0.0)

    lambdas = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
    hessians = jnp.sum(hes, axis=2) + jnp.sum(hes, axis=1)
    if norm:
        sum_lambdas = -2.0 * jnp.sum(lam, axis=(1, 2))      # [C]
        factor = jnp.where(sum_lambdas > 0,
                           jnp.log2(1.0 + sum_lambdas)
                           / jnp.maximum(sum_lambdas, 1e-20), 1.0)
        lambdas = lambdas * factor[:, None]
        hessians = hessians * factor[:, None]
    return lambdas, hessians


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    need_group = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check(metadata.query_boundaries is not None,
              "Lambdarank tasks require query information")
        self.sigmoid = float(self.config.sigmoid)
        self.norm = bool(self.config.lambdamart_norm)
        self.max_position = int(self.config.max_position)
        calc = DCGCalculator(self.config.label_gain)
        calc.check_labels(self.label_np)
        self.calc = calc
        boundaries = np.asarray(metadata.query_boundaries)
        self.query_boundaries = boundaries
        nq = len(boundaries) - 1
        inv = np.zeros(nq)
        for q in range(nq):
            lab = self.label_np[boundaries[q]: boundaries[q + 1]]
            m = calc.cal_maxdcg_at_k(self.max_position, lab)
            inv[q] = 1.0 / m if m > 0 else 0.0
        # bucket queries by padded (power-of-two) length, min 8
        sizes = np.diff(boundaries)
        pads = np.maximum(8, 1 << np.ceil(np.log2(np.maximum(sizes, 1)))
                          .astype(np.int64))
        self.buckets: List[Dict] = []
        for p in np.unique(pads):
            qs = np.nonzero(pads == p)[0]
            P = int(p)
            idx = np.full((len(qs), P), -1, dtype=np.int64)
            for row, q in enumerate(qs):
                cnt = sizes[q]
                idx[row, :cnt] = np.arange(boundaries[q], boundaries[q + 1])
            # fixed chunk size keeping the [C, P, P] transient under ~64MB
            chunk = max(1, (1 << 24) // (P * P))
            # right-size C: same chunk count, minimal phantom padding
            nC_min = -(-len(qs) // min(chunk, len(qs)))
            C = -(-len(qs) // nC_min)
            # pad the query count to a multiple of C and reshape to
            # [n_chunks, C, P]: get_gradients lax.scans over the leading
            # axis, so the traced graph holds ONE pairwise body per
            # bucket no matter how many queries there are.  (The old
            # Python chunk loop inlined a [C, P, P] body PER CHUNK —
            # ~19 of them at 2.27M rows — and the remote Mosaic/XLA
            # compile of that graph blew every timeout on v5e,
            # 2026-08-01.)
            pad_q = (-len(qs)) % C
            if pad_q:
                idx = np.concatenate(
                    [idx, np.full((pad_q, P), -1, np.int64)])
            labels = np.where(idx >= 0,
                              self.label_np[np.maximum(idx, 0)],
                              0).astype(np.int32)
            inv_q = np.concatenate(
                [inv[qs], np.zeros(pad_q)]).astype(np.float32)
            nC = idx.shape[0] // C
            self.buckets.append({
                "P": P,
                "idx": jnp.asarray(np.where(idx < 0, 0, idx)
                                   .astype(np.int32).reshape(nC, C, P)),
                "mask": jnp.asarray((idx >= 0).reshape(nC, C, P)),
                "labels": jnp.asarray(labels.reshape(nC, C, P)),
                "inv_max_dcg": jnp.asarray(inv_q.reshape(nC, C)),
            })
        self.gains = jnp.asarray(self.calc.label_gain.astype(np.float32))

    def get_gradients(self, score):
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        for b in self.buckets:   # bounded: one body per P bucket
            def body(carry, chunk):
                g, h = carry
                idx, msk, lab, invd = chunk
                lam, hes = _chunk_lambdas(score[idx], lab, msk, invd,
                                          self.gains,
                                          sigmoid=self.sigmoid,
                                          norm=self.norm)
                flat = idx.reshape(-1)
                keep = msk.reshape(-1)
                g = g.at[flat].add(jnp.where(keep, lam.reshape(-1), 0.0))
                h = h.at[flat].add(jnp.where(keep, hes.reshape(-1), 0.0))
                return (g, h), None

            (grad, hess), _ = lax.scan(
                body, (grad, hess),
                (b["idx"], b["mask"], b["labels"], b["inv_max_dcg"]))
        if self.weights is not None:
            grad = grad * self.weights
            hess = hess * self.weights
        return grad, hess

    def boost_from_score(self, class_id=0):
        return 0.0
