"""The binned training Dataset.

Reference: include/LightGBM/dataset.h:283-637 + src/io/dataset.cpp (Dataset,
FeatureGroup, bin storage) and src/io/dataset_loader.cpp (construction from
raw data: sample -> FindBin -> quantize all rows).

TPU-first design departure (SURVEY.md §7): instead of per-group
dense/sparse/4-bit bin storage classes with OpenMP push pipelines
(src/io/dense_bin.hpp:48, sparse_bin.hpp:73), the dataset is ONE dense
HBM-resident bin matrix ``[num_data, num_used_features]`` of uint8/uint16.
Everything downstream (histograms, partitions) is a vectorized XLA/Pallas op
over this matrix.  Sparse features stay dense here: bins compress the value
range to <=max_bin levels, so a column is 1-2 bytes/row regardless of sparsity
— EFB-style bundling becomes a pure memory optimization (later round) rather
than a correctness requirement.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils.log import check, log_fatal, log_info, log_warning
from .binning import (BIN_TYPE_CATEGORICAL, BIN_TYPE_NUMERICAL, BinMapper,
                      MISSING_NAN, MISSING_NONE, MISSING_ZERO)
from .bundle import BundleSpec, build_bundle, quantize_bundled
from .metadata import Metadata

_BINARY_MAGIC = b"lightgbm_tpu.dataset.v1\x00"


class FeatureInfo:
    """Per-used-feature metadata consumed by the tree learner.

    ``group``/``offset`` locate the feature inside the physical bin matrix
    (EFB bundling, core/bundle.py): column ``group`` holds this feature's
    bins at ``offset + bin``.  Unbundled datasets have group == the
    feature's own column and offset == 0.
    """

    __slots__ = ("num_bin", "missing_type", "default_bin", "is_categorical",
                 "monotone", "penalty", "group", "offset")

    def __init__(self, num_bin, missing_type, default_bin, is_categorical,
                 monotone=0, penalty=1.0, group=0, offset=0):
        self.num_bin = num_bin
        self.missing_type = missing_type
        self.default_bin = default_bin
        self.is_categorical = is_categorical
        self.monotone = monotone
        self.penalty = penalty
        self.group = group
        self.offset = offset


class TpuDataset:
    """Binned dataset: dense uint8/16 matrix + per-feature BinMappers + Metadata."""

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[BinMapper] = []       # one per original feature
        self.used_feature_indices: np.ndarray = np.array([], dtype=np.int32)
        self.binned: Optional[np.ndarray] = None     # [N, F_used] uint8/uint16
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_num_bin: int = 0
        self.monotone_constraints: Optional[List[int]] = None
        self.feature_penalty: Optional[List[float]] = None
        self.bundle: Optional[BundleSpec] = None   # EFB packing; None = plain
        self._device_binned = None

    # ------------------------------------------------------------ construction
    @classmethod
    def from_numpy(cls, data: np.ndarray, label: Optional[np.ndarray] = None,
                   config: Optional[Config] = None,
                   weights: Optional[np.ndarray] = None,
                   group: Optional[np.ndarray] = None,
                   init_score: Optional[np.ndarray] = None,
                   categorical_features: Sequence[int] = (),
                   feature_names: Optional[List[str]] = None,
                   reference: Optional["TpuDataset"] = None) -> "TpuDataset":
        """Build a dataset from a raw [N, F] float matrix.

        Mirrors DatasetLoader::CostructFromSampleData (dataset_loader.cpp:553):
        sample rows -> per-feature BinMapper::FindBin -> quantize every row.
        When ``reference`` is given, its bin mappers are reused so validation
        data aligns with training bins (Dataset::CreateValid, dataset.cpp:435).
        """
        cfg = config or Config()
        data = np.asarray(data)
        if data.ndim != 2:
            raise ValueError("data must be 2-dimensional [num_data, num_features]")
        n, num_features = data.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_features
        ds.feature_names = (list(feature_names) if feature_names
                            else [f"Column_{i}" for i in range(num_features)])

        from ..utils.phase import GLOBAL_TIMER
        if reference is not None:
            check(reference.num_total_features == num_features,
                  "validation data has a different number of features")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_indices = reference.used_feature_indices
            ds.max_num_bin = reference.max_num_bin
            ds.monotone_constraints = reference.monotone_constraints
            ds.feature_penalty = reference.feature_penalty
            ds.feature_names = list(reference.feature_names)
            ds.bundle = reference.bundle
        else:
            with GLOBAL_TIMER.phase("bin_find"):
                ds._fit_bin_mappers(data, cfg,
                                    set(int(c) for c in categorical_features))
                ds._build_bundle(cfg, lambda f, sample_idx=ds._sample_idx: (
                    np.asarray(data[sample_idx, ds.used_feature_indices[f]],
                               dtype=np.float64)))

        with GLOBAL_TIMER.phase("bin_quantize"):
            ds._quantize(data)
        ds.metadata.init(n)
        if label is not None:
            ds.metadata.set_label(label)
        if weights is not None:
            ds.metadata.set_weights(weights)
        if group is not None:
            ds.metadata.set_query(group)
        if init_score is not None:
            ds.metadata.set_init_score(init_score)
        return ds

    def _fit_bin_mappers(self, data: np.ndarray, cfg: Config,
                         categorical: set) -> None:
        sample_idx = self._pick_sample(data.shape[0], cfg)
        self._fit_bin_mappers_from_cols(
            cfg, categorical, data.shape[1],
            lambda f: np.asarray(data[sample_idx, f], dtype=np.float64),
            len(sample_idx))

    def _fit_bin_mappers_from_cols(self, cfg: Config, categorical: set,
                                   num_features: int, col_vals_fn,
                                   total_sample_cnt: int) -> None:
        """Shared bin-fitting tail for the dense and sparse constructors.

        ``col_vals_fn(f)`` returns feature f's sampled values; for sparse
        input these are the NONZEROS only — ``total_sample_cnt -
        len(values)`` values are implicitly zero (the reference's sparse
        FindBin convention, bin.cpp:210).

        Multi-process runs shard this loop: each rank fits the BinMappers
        of its modulo-strided feature subset from its local sample, then
        the serialized mappers are allgathered and merged — the
        reference's distributed bin finding
        (dataset_loader.cpp:933-1034)."""
        from ..parallel import network
        world, rank = network.binning_world()
        max_bin_by_feature = list(cfg.max_bin_by_feature or [])

        def fit_one(f):
            bt = (BIN_TYPE_CATEGORICAL if f in categorical
                  else BIN_TYPE_NUMERICAL)
            mb = (max_bin_by_feature[f] if f < len(max_bin_by_feature)
                  else cfg.max_bin)
            return BinMapper().find_bin(
                col_vals_fn(f), total_sample_cnt=total_sample_cnt,
                max_bin=mb, min_data_in_bin=cfg.min_data_in_bin,
                min_split_data=cfg.min_data_in_leaf,
                bin_type=bt, use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing)

        if world > 1:
            local = {f: fit_one(f).to_dict()
                     for f in range(rank, num_features, world)}
            merged = {}
            for part in network.allgather_obj(local):
                merged.update(part)
            check(len(merged) == num_features,
                  "distributed bin finding did not cover every feature")
            self.bin_mappers = [BinMapper.from_dict(merged[f])
                                for f in range(num_features)]
        else:
            self.bin_mappers = [fit_one(f) for f in range(num_features)]
        used = [f for f, m in enumerate(self.bin_mappers) if not m.is_trivial]
        if not used:
            log_warning("There are no meaningful features, as all feature "
                        "values are constant.")
        self.used_feature_indices = np.asarray(used, dtype=np.int32)
        self.max_num_bin = max((self.bin_mappers[f].num_bin for f in used),
                               default=1)
        if cfg.monotone_constraints:
            mc = list(cfg.monotone_constraints)
            check(len(mc) == self.num_total_features,
                  "monotone_constraints length must equal number of features")
            self.monotone_constraints = [int(x) for x in mc]
        if cfg.feature_contri:
            fc = list(cfg.feature_contri)
            check(len(fc) == self.num_total_features,
                  "feature_contri length must equal number of features")
            self.feature_penalty = [float(x) for x in fc]

    @classmethod
    def from_scipy(cls, data, label: Optional[np.ndarray] = None,
                   config: Optional[Config] = None,
                   weights: Optional[np.ndarray] = None,
                   group: Optional[np.ndarray] = None,
                   init_score: Optional[np.ndarray] = None,
                   categorical_features: Sequence[int] = (),
                   feature_names: Optional[List[str]] = None,
                   reference: Optional["TpuDataset"] = None) -> "TpuDataset":
        """Build a dataset from a scipy sparse matrix WITHOUT densifying
        the raw values (LGBM_DatasetCreateFromCSR path, c_api.cpp:560).

        Bins are found from per-column nonzeros (implicit zeros counted via
        ``total_sample_cnt``, the reference's sparse FindBin convention,
        bin.cpp:210), and the quantized matrix is written column-by-column
        — peak extra memory is one dense column, and under EFB the result
        is the bundled [N, num_groups] matrix directly.
        """
        cfg = config or Config()
        csr = data.tocsr()
        n, num_features = csr.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_features
        ds.feature_names = (list(feature_names) if feature_names
                            else [f"Column_{i}" for i in range(num_features)])
        csc = csr.tocsc()

        if reference is not None:
            check(reference.num_total_features == num_features,
                  "validation data has a different number of features")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_indices = reference.used_feature_indices
            ds.max_num_bin = reference.max_num_bin
            ds.monotone_constraints = reference.monotone_constraints
            ds.feature_penalty = reference.feature_penalty
            ds.feature_names = list(reference.feature_names)
            ds.bundle = reference.bundle
        else:
            sample_idx = ds._pick_sample(n, cfg)
            sample_csc = (csc if len(sample_idx) >= n
                          else csr[sample_idx].tocsc())
            S = len(sample_idx)
            ds._fit_bin_mappers_from_cols(
                cfg, set(int(c) for c in categorical_features), num_features,
                lambda f: np.asarray(
                    sample_csc.data[sample_csc.indptr[f]:
                                    sample_csc.indptr[f + 1]],
                    dtype=np.float64),
                S)

            def sample_col(j):
                f = int(ds.used_feature_indices[j])
                out = np.zeros(S, dtype=np.float64)
                sl = slice(sample_csc.indptr[f], sample_csc.indptr[f + 1])
                out[sample_csc.indices[sl]] = sample_csc.data[sl]
                return out

            ds._build_bundle(cfg, sample_col)

        used = ds.used_feature_indices
        default_bins = np.asarray(
            [ds.bin_mappers[f].default_bin for f in used], dtype=np.int64)

        def col_bins(j):
            """Full [N] bin column of used feature j from the CSC slices;
            implicit zeros land on default_bin (== value_to_bin(0))."""
            f = int(used[j])
            m = ds.bin_mappers[f]
            out = np.full(n, default_bins[j], dtype=np.int64)
            sl = slice(csc.indptr[f], csc.indptr[f + 1])
            out[csc.indices[sl]] = m.value_to_bin(
                np.asarray(csc.data[sl], dtype=np.float64))
            return out

        if ds.bundle is not None:
            ds.binned = quantize_bundled(col_bins, ds.bundle, default_bins, n)
        else:
            dtype = np.uint8 if ds.max_num_bin <= 256 else np.uint16
            out = np.empty((n, len(used)), dtype=dtype)
            for j in range(len(used)):
                out[:, j] = col_bins(j).astype(dtype)
            ds.binned = out
        ds._device_binned = None
        ds.metadata.init(n)
        if label is not None:
            ds.metadata.set_label(label)
        if weights is not None:
            ds.metadata.set_weights(weights)
        if group is not None:
            ds.metadata.set_query(group)
        if init_score is not None:
            ds.metadata.set_init_score(init_score)
        return ds

    def _pick_sample(self, n: int, cfg: Config) -> np.ndarray:
        rng = np.random.RandomState(cfg.data_random_seed)
        sample_cnt = min(n, cfg.bin_construct_sample_cnt)
        self._sample_idx = (np.arange(n) if sample_cnt >= n
                            else rng.choice(n, sample_cnt, replace=False))
        return self._sample_idx

    def _build_bundle(self, cfg: Config, sample_col_fn) -> None:
        """EFB grouping from the binning sample (Dataset::Construct ->
        FastFeatureBundling, src/io/dataset.cpp:235-241).
        ``sample_col_fn(j)`` -> raw [S] float64 sample of used feature j.

        Multi-process runs take rank 0's grouping for everyone: the
        BundleSpec defines the physical column layout, and ranks deriving
        it from their own local samples could disagree — then sharded
        histograms would combine mismatched columns."""
        if not cfg.enable_bundle or len(self.used_feature_indices) <= 1:
            return
        from ..parallel import network
        world, rank = network.binning_world()
        used = self.used_feature_indices
        num_bins = np.asarray([self.bin_mappers[f].num_bin for f in used],
                              dtype=np.int64)
        spec = None
        if rank == 0:
            default_bins = np.asarray(
                [self.bin_mappers[f].default_bin for f in used],
                dtype=np.int64)
            sparse_rates = np.asarray(
                [self.bin_mappers[f].sparse_rate for f in used])

            def nonzero_fn(j):
                m = self.bin_mappers[used[j]]
                return m.value_to_bin(sample_col_fn(j)) != default_bins[j]

            S = len(self._sample_idx)
            spec = build_bundle(nonzero_fn, len(used), S, num_bins,
                                sparse_rates, cfg.sparse_threshold,
                                cfg.max_conflict_rate)
        if world > 1:
            groups = network.allgather_obj(
                spec.to_dict() if spec is not None else None)[0]
            spec = (BundleSpec.from_dict(groups, num_bins)
                    if groups is not None else None)
        self.bundle = spec
        if self.bundle is not None:
            log_info(f"EFB bundled {len(used)} features into "
                     f"{self.bundle.num_groups} groups")

    def _quantize(self, data: np.ndarray) -> None:
        used = self.used_feature_indices

        if self.bundle is not None:
            default_bins = np.asarray(
                [self.bin_mappers[f].default_bin for f in used],
                dtype=np.int64)

            def col_fn(j):
                return self.bin_mappers[used[j]].value_to_bin(
                    np.asarray(data[:, used[j]], dtype=np.float64))

            self.binned = quantize_bundled(col_fn, self.bundle, default_bins,
                                           data.shape[0])
            self._device_binned = None
            return
        dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
        # native one-pass quantizer for the NUMERICAL columns
        # (src/native/fastbin.cpp lgbmtpu_quantize_rows*) — the
        # per-column numpy loop paid 21-43s at 10.5M rows; categorical
        # columns (dict lookups) stay on the python path
        from .binning import BIN_TYPE_NUMERICAL
        from .native import quantize_rows_native
        out = np.empty((data.shape[0], len(used)), dtype=dtype)
        done = [False] * len(used)
        if isinstance(data, np.ndarray) and data.ndim == 2:
            num_pos = [j for j, f in enumerate(used)
                       if self.bin_mappers[f].bin_type
                       == BIN_TYPE_NUMERICAL]
            if num_pos:
                nat = quantize_rows_native(
                    data, [used[j] for j in num_pos], self.bin_mappers,
                    dtype)
                if nat is not None:
                    out[:, num_pos] = nat
                    for j in num_pos:
                        done[j] = True
        for j, f in enumerate(used):
            if not done[j]:
                out[:, j] = self.bin_mappers[f].value_to_bin(
                    np.asarray(data[:, f], dtype=np.float64)).astype(dtype)
        self.binned = out
        self._device_binned = None

    # ---------------------------------------------------------------- accessors
    @property
    def num_used_features(self) -> int:
        return len(self.used_feature_indices)

    @property
    def num_columns(self) -> int:
        """Physical bin-matrix columns (== groups under EFB)."""
        return (self.bundle.num_groups if self.bundle is not None
                else len(self.used_feature_indices))

    @property
    def max_column_bin(self) -> int:
        """Max bins of any physical column (histogram bin-axis size)."""
        return (int(self.bundle.group_num_bin.max(initial=1))
                if self.bundle is not None else self.max_num_bin)

    @property
    def column_bins(self) -> np.ndarray:
        """Per-column bin counts (feature-parallel stripes balance on this,
        as the reference balances shards by #bins —
        feature_parallel_tree_learner.cpp:36-47)."""
        if self.bundle is not None:
            return np.asarray(self.bundle.group_num_bin, dtype=np.int64)
        return np.asarray([self.bin_mappers[f].num_bin
                           for f in self.used_feature_indices],
                          dtype=np.int64)

    def feature_infos(self) -> List[FeatureInfo]:
        infos = []
        for j, f in enumerate(self.used_feature_indices):
            m = self.bin_mappers[f]
            mono = 0
            if self.monotone_constraints is not None:
                mono = self.monotone_constraints[f]
            pen = 1.0
            if self.feature_penalty is not None:
                pen = self.feature_penalty[f]
            if self.bundle is not None:
                grp = int(self.bundle.feat_group[j])
                off = int(self.bundle.feat_offset[j])
            else:
                grp, off = j, 0
            infos.append(FeatureInfo(m.num_bin, m.missing_type, m.default_bin,
                                     m.is_categorical, mono, pen, grp, off))
        return infos

    def real_threshold(self, used_feature: int, bin_threshold: int) -> float:
        """Bin threshold -> real-valued threshold for the saved model
        (reference Dataset::RealThreshold)."""
        f = int(self.used_feature_indices[used_feature])
        return self.bin_mappers[f].bin_to_value(int(bin_threshold))

    def inner_feature_index(self, real_feature: int) -> int:
        hits = np.nonzero(self.used_feature_indices == real_feature)[0]
        return int(hits[0]) if len(hits) else -1

    def host_binned(self) -> np.ndarray:
        """Row-major [N, F] host bin matrix — the exact byte image
        ``device_binned`` uploads (shared with the host-spill store so
        resident and spilled training see identical device bytes)."""
        return self.binned

    def host_binned_T(self, row_multiple: int = 1,
                      packed4: bool = False) -> np.ndarray:
        """Host-side feature-major training layout — the exact byte
        image ``device_binned_T`` uploads (see there for the layout
        contract); factored out so the host-spill store streams the
        same bytes the resident path would."""
        npad = (-self.num_data) % row_multiple
        t = np.ascontiguousarray(self.binned.T)
        if npad:
            t = np.pad(t, ((0, 0), (0, npad)))
        if packed4:
            from ..ops.pallas_histogram import pack_bins_4bit
            t = pack_bins_4bit(t)
        return t

    def drop_device_cache(self) -> None:
        """Release the cached device copies of the bin matrix (the
        host-spill tier streams from the host arrays instead; keeping
        the device cache alive would defeat the spill)."""
        self._device_binned = None
        self._device_binned_T = None
        self._device_binned_T_key = None

    def device_binned(self):
        """The bin matrix as a device array (uploaded once, cached)."""
        import jax.numpy as jnp
        if self._device_binned is None:
            from ..utils.telemetry import TELEMETRY
            TELEMETRY.counter_add("transfer/h2d_bytes",
                                  int(self.binned.nbytes))
            self._device_binned = jnp.asarray(self.binned)
        return self._device_binned

    def device_binned_T(self, row_multiple: int = 1, packed4: bool = False):
        """Feature-major [F, Npad] bin matrix, rows padded to a multiple of
        ``row_multiple`` (pad rows are bin 0; training must give them zero
        weight).  This is the training layout: each feature is a contiguous
        lane stream for the histogram kernels.  ``packed4`` packs two
        <=16-bin columns per byte (Dense4bitsBin equivalent,
        dense_nbits_bin.hpp:42): [ceil(F/2), Npad] on device."""
        import jax.numpy as jnp
        key = getattr(self, "_device_binned_T_key", None)
        if key != (row_multiple, packed4):
            t = self.host_binned_T(row_multiple, packed4)
            from ..utils.telemetry import TELEMETRY
            TELEMETRY.counter_add("transfer/h2d_bytes", int(t.nbytes))
            self._device_binned_T = jnp.asarray(t)
            self._device_binned_T_key = (row_multiple, packed4)
        return self._device_binned_T

    def check_align(self, other: "TpuDataset") -> None:
        """Fatal unless ``other``'s bins align with this dataset's
        (Dataset::CheckAlign / BinMapper::CheckAlign, dataset.h:301,
        bin.h:86): binned routing on mismatched mappers is silently
        wrong, so the mismatch must be an error."""
        msg = ("Cannot use this dataset: its bin mappers differ from the "
               "training data's (construct it with the training set as "
               "reference)")
        if other.bin_mappers is self.bin_mappers:
            pass
        elif other.num_total_features != self.num_total_features:
            log_fatal(msg)
        else:
            for ma, mb in zip(self.bin_mappers, other.bin_mappers):
                if (ma.num_bin != mb.num_bin
                        or ma.bin_type != mb.bin_type
                        or ma.missing_type != mb.missing_type
                        # equal_nan: MISSING_NAN mappers end with a NaN bound
                        or not np.array_equal(ma.bin_upper_bound,
                                              mb.bin_upper_bound,
                                              equal_nan=True)
                        # categorical routing lives in the category->bin
                        # map, not the (unused) numerical bounds
                        or ma.bin_2_categorical != mb.bin_2_categorical):
                    log_fatal(msg)
        sb, ob = self.bundle, other.bundle
        if (sb is None) != (ob is None) or (
                sb is not None and ob is not sb
                and (not np.array_equal(sb.feat_group, ob.feat_group)
                     or not np.array_equal(sb.feat_offset, ob.feat_offset))):
            log_fatal("Cannot use this dataset: its EFB column layout "
                      "differs from the training data's")

    def create_valid(self, data, label: Optional[np.ndarray] = None,
                     **kwargs) -> "TpuDataset":
        if hasattr(data, "tocsr"):            # scipy sparse
            return TpuDataset.from_scipy(data, label=label, reference=self,
                                         **kwargs)
        return TpuDataset.from_numpy(data, label=label, reference=self, **kwargs)

    def add_features_from(self, other: "TpuDataset") -> None:
        """Merge another dataset's feature columns into this one
        (Dataset::addFeaturesFrom, src/io/dataset.cpp:AddFeaturesFrom;
        LGBM_DatasetAddFeaturesFrom).  Row counts must match; the source's
        metadata (labels etc.) is ignored, as in the reference."""
        from ..utils.log import check
        check(self.num_data == other.num_data,
              "Cannot add features from other Dataset with a different "
              "number of rows")
        check(self.bundle is None and other.bundle is None,
              "add_features_from does not support EFB-bundled datasets; "
              "construct with enable_bundle=false")
        offset = self.num_total_features
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.used_feature_indices = np.concatenate([
            self.used_feature_indices,
            np.asarray(other.used_feature_indices, dtype=np.int32) + offset,
        ]).astype(np.int32)
        self.num_total_features += other.num_total_features
        self.feature_names = list(self.feature_names) + [
            (n if n not in self.feature_names else f"{n}_dup")
            for n in other.feature_names]
        if self.monotone_constraints is not None \
                or other.monotone_constraints is not None:
            a = self.monotone_constraints or [0] * offset
            b = other.monotone_constraints or [0] * other.num_total_features
            self.monotone_constraints = list(a) + list(b)
        if self.feature_penalty is not None \
                or other.feature_penalty is not None:
            a = self.feature_penalty or [1.0] * offset
            b = other.feature_penalty or [1.0] * other.num_total_features
            self.feature_penalty = list(a) + list(b)
        dtype = (np.uint16 if (self.binned.dtype == np.uint16
                               or other.binned.dtype == np.uint16)
                 else np.uint8)
        self.binned = np.concatenate(
            [self.binned.astype(dtype), other.binned.astype(dtype)], axis=1)
        self.max_num_bin = max(self.max_num_bin, other.max_num_bin)
        self._device_binned = None
        self._device_binned_T_key = None

    # ----------------------------------------------------------- binary cache
    def save_binary(self, filename: str) -> None:
        """Binary dataset cache (reference Dataset::SaveBinaryFile,
        dataset.cpp:624; format is ours, token-checked the same way)."""
        import json
        meta = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "feature_names": self.feature_names,
            "used_feature_indices": self.used_feature_indices.tolist(),
            "max_num_bin": self.max_num_bin,
            "bin_mappers": [m.to_dict() for m in self.bin_mappers],
            "has_weights": self.metadata.weights is not None,
            "has_query": self.metadata.query_boundaries is not None,
            "has_init_score": self.metadata.init_score is not None,
            "binned_dtype": str(self.binned.dtype),
            "bundle": (self.bundle.to_dict() if self.bundle is not None
                       else None),
        }
        blob = json.dumps(meta).encode()
        from ..utils.file_io import open_file
        with open_file(filename, "wb") as fh:
            fh.write(_BINARY_MAGIC)
            fh.write(struct.pack("<q", len(blob)))
            fh.write(blob)
            fh.write(self.binned.tobytes())
            fh.write(self.metadata.label.astype(np.float32).tobytes())
            if self.metadata.weights is not None:
                fh.write(self.metadata.weights.astype(np.float32).tobytes())
            if self.metadata.query_boundaries is not None:
                fh.write(struct.pack("<q", len(self.metadata.query_boundaries)))
                fh.write(self.metadata.query_boundaries.astype(np.int32).tobytes())
            if self.metadata.init_score is not None:
                fh.write(struct.pack("<q", len(self.metadata.init_score)))
                fh.write(self.metadata.init_score.astype(np.float64).tobytes())
        log_info(f"Saved binary dataset to {filename}")

    @classmethod
    def load_binary(cls, filename: str) -> "TpuDataset":
        import json

        from ..utils.file_io import open_file
        with open_file(filename, "rb") as fh:
            magic = fh.read(len(_BINARY_MAGIC))
            if magic != _BINARY_MAGIC:
                log_fatal(f"{filename} is not a lightgbm_tpu binary dataset")
            (blob_len,) = struct.unpack("<q", fh.read(8))
            meta = json.loads(fh.read(blob_len).decode())
            ds = cls()
            ds.num_data = meta["num_data"]
            ds.num_total_features = meta["num_total_features"]
            ds.feature_names = meta["feature_names"]
            ds.used_feature_indices = np.asarray(meta["used_feature_indices"],
                                                 dtype=np.int32)
            ds.max_num_bin = meta["max_num_bin"]
            ds.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
            if meta.get("bundle") is not None:
                used_nb = np.asarray(
                    [ds.bin_mappers[f].num_bin
                     for f in ds.used_feature_indices], dtype=np.int64)
                ds.bundle = BundleSpec.from_dict(meta["bundle"], used_nb)
            dtype = np.dtype(meta["binned_dtype"])
            ncols = ds.num_columns
            nbytes = ds.num_data * ncols * dtype.itemsize
            ds.binned = np.frombuffer(fh.read(nbytes), dtype=dtype).reshape(
                ds.num_data, ncols).copy()
            ds.metadata.init(ds.num_data)
            ds.metadata.label = np.frombuffer(
                fh.read(4 * ds.num_data), dtype=np.float32).copy()
            if meta["has_weights"]:
                ds.metadata.weights = np.frombuffer(
                    fh.read(4 * ds.num_data), dtype=np.float32).copy()
            if meta["has_query"]:
                (qlen,) = struct.unpack("<q", fh.read(8))
                ds.metadata.query_boundaries = np.frombuffer(
                    fh.read(4 * qlen), dtype=np.int32).copy()
            if meta["has_init_score"]:
                (slen,) = struct.unpack("<q", fh.read(8))
                ds.metadata.init_score = np.frombuffer(
                    fh.read(8 * slen), dtype=np.float64).copy()
        return ds
