"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Reference: src/io/parser.{cpp,hpp} (Parser::CreateParser :92 auto-detects by
counting separators on sample lines; CSVParser/TSVParser/LibSVMParser) and
the DatasetLoader text pipeline (src/io/dataset_loader.cpp:162-260: label
column extraction, weight/group/ignore columns, header handling).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import LightGBMError, check, log_info, log_warning


def _detect_format(sample_lines: List[str]) -> str:
    """Count separators like Parser::CreateParser (parser.cpp:30-90)."""
    def stats(line: str) -> Tuple[int, int, int]:
        return line.count(","), line.count("\t"), line.count(":")

    cnt = [stats(l) for l in sample_lines if l.strip()]
    if not cnt:
        raise LightGBMError("Empty data file")
    tabs = min(c[1] for c in cnt)
    commas = min(c[0] for c in cnt)
    colons = min(c[2] for c in cnt)
    if tabs > 0:
        return "tsv"
    if commas > 0:
        return "csv"
    if colons > 0:
        return "libsvm"
    return "csv"  # single-column fallback


def _parse_dense(lines: List[str], sep: str) -> np.ndarray:
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rows.append([float(tok) if tok not in ("", "na", "nan", "NaN", "NULL")
                     else np.nan for tok in line.split(sep)])
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), np.nan)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
    return out


def _parse_libsvm(lines: List[str]) -> np.ndarray:
    """label idx:val idx:val ... (1-based or 0-based indices accepted)."""
    parsed = []
    max_idx = -1
    for line in lines:
        line = line.strip()
        if not line:
            continue
        toks = line.split()
        label = float(toks[0])
        feats = {}
        for tok in toks[1:]:
            if ":" not in tok:
                continue
            k, v = tok.split(":", 1)
            idx = int(k)
            feats[idx] = float(v)
            max_idx = max(max_idx, idx)
        parsed.append((label, feats))
    out = np.zeros((len(parsed), max_idx + 2))
    for i, (label, feats) in enumerate(parsed):
        out[i, 0] = label
        for k, v in feats.items():
            out[i, k + 1] = v
    return out


def _column_index(spec: str, header_names: Optional[List[str]]) -> int:
    """Resolve 'name:<col>' / numeric column spec (config.h label_column)."""
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names is None or name not in header_names:
            raise LightGBMError(f"Column name {name} not found in header")
        return header_names.index(name)
    return int(spec)


def load_file_to_dataset(filename: str, config: Config, reference=None):
    """Text file -> TpuDataset (DatasetLoader::LoadFromFile,
    dataset_loader.cpp:162)."""
    from .dataset import TpuDataset

    if not os.path.exists(filename):
        raise LightGBMError(f"Data file {filename} doesn't exist")
    if filename.endswith(".bin") or _is_binary(filename):
        return TpuDataset.load_binary(filename)

    with open(filename) as fh:
        lines = fh.readlines()
    header_names: Optional[List[str]] = None
    if config.header and lines:
        first = lines[0].strip()
        sep = "\t" if "\t" in first else ","
        header_names = first.split(sep)
        lines = lines[1:]

    fmt = _detect_format(lines[:32])
    log_info(f"Loading {filename} as {fmt}")
    if fmt == "libsvm":
        mat = _parse_libsvm(lines)
        label_col = 0
    else:
        sep = "\t" if fmt == "tsv" else ","
        mat = _parse_dense(lines, sep)
        label_col = (_column_index(config.label_column, header_names)
                     if config.label_column else 0)

    ncol = mat.shape[1]
    weight_col = (_column_index(config.weight_column, header_names)
                  if config.weight_column else -1)
    group_col = (_column_index(config.group_column, header_names)
                 if config.group_column else -1)
    ignore_cols = set()
    if config.ignore_column:
        for tok in str(config.ignore_column).split(","):
            tok = tok.strip()
            if tok:
                ignore_cols.add(_column_index(tok, header_names))

    label = mat[:, label_col]
    weights = mat[:, weight_col] if weight_col >= 0 else None
    qids = mat[:, group_col] if group_col >= 0 else None
    drop = {label_col} | ignore_cols
    if weight_col >= 0:
        drop.add(weight_col)
    if group_col >= 0:
        drop.add(group_col)
    feat_cols = [c for c in range(ncol) if c not in drop]
    X = mat[:, feat_cols]
    feat_names = ([header_names[c] for c in feat_cols] if header_names
                  else None)

    cat_idx: List[int] = []
    if config.categorical_feature:
        for tok in str(config.categorical_feature).split(","):
            tok = tok.strip()
            if not tok:
                continue
            orig = _column_index(tok, header_names)
            # map original column index to feature index after drops
            if orig in feat_cols:
                cat_idx.append(feat_cols.index(orig))

    ds = TpuDataset.from_numpy(
        X, label=label, config=config, weights=weights,
        categorical_features=cat_idx, feature_names=feat_names,
        reference=reference)
    if qids is not None:
        ds.metadata.set_query_from_ids(qids)
    # group file sidecar: <data>.query (dataset_loader.cpp query file load)
    qfile = filename + ".query"
    if qids is None and os.path.exists(qfile):
        groups = np.loadtxt(qfile, dtype=np.int64, ndmin=1)
        ds.metadata.set_query(groups)
    wfile = filename + ".weight"
    if weights is None and os.path.exists(wfile):
        ds.metadata.set_weights(np.loadtxt(wfile, ndmin=1))
    ifile = filename + ".init"
    if os.path.exists(ifile):
        ds.metadata.set_init_score(np.loadtxt(ifile, ndmin=1).ravel())
    return ds


def _is_binary(filename: str) -> bool:
    from .dataset import _BINARY_MAGIC
    with open(filename, "rb") as fh:
        head = fh.read(len(_BINARY_MAGIC))
    return head == _BINARY_MAGIC


def parse_file_to_matrix(filename: str, has_header: bool,
                         num_features: int, label_column: str = ""):
    """Parse a prediction input file into (X [N, num_features], label).

    Matches the CLI predict path's handling (Predictor file pipeline,
    reference src/application/predictor.hpp:69-110): auto-detected
    CSV/TSV/LibSVM, label column stripped (column 0 unless
    ``label_column`` names another, as in the CLI config), width aligned
    to the model's feature count.  Dense files whose width already equals
    the model's feature count are treated as label-free; LibSVM always
    carries a leading label.
    """
    with open(filename) as fh:
        lines = fh.readlines()
    header_names = None
    if has_header and lines:
        sep = "\t" if "\t" in lines[0] else ","
        header_names = lines[0].strip().split(sep)
        lines = lines[1:]
    fmt = _detect_format(lines[:32])
    if fmt == "libsvm":
        mat = _parse_libsvm(lines)
        label_col = 0
    else:
        sep = "\t" if fmt == "tsv" else ","
        mat = _parse_dense(lines, sep)
        if mat.shape[1] == num_features:   # no label column present
            return mat, None
        label_col = (_column_index(label_column, header_names)
                     if label_column else 0)
    label = mat[:, label_col]
    X = np.delete(mat, label_col, axis=1)
    if X.shape[1] < num_features:
        X = np.pad(X, ((0, 0), (0, num_features - X.shape[1])))
    elif X.shape[1] > num_features:
        X = X[:, :num_features]
    return X, label
