"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Reference: src/io/parser.{cpp,hpp} (Parser::CreateParser :92 auto-detects by
counting separators on sample lines; CSVParser/TSVParser/LibSVMParser) and
the DatasetLoader text pipeline (src/io/dataset_loader.cpp:162-260: label
column extraction, weight/group/ignore columns, header handling).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.file_io import open_file, uri_scheme
from ..utils import file_io
from ..utils.log import LightGBMError, check, log_info, log_warning


def _detect_format(sample_lines: List[str]) -> str:
    """Count separators like Parser::CreateParser (parser.cpp:30-90)."""
    def stats(line: str) -> Tuple[int, int, int]:
        return line.count(","), line.count("\t"), line.count(":")

    cnt = [stats(l) for l in sample_lines if l.strip()]
    if not cnt:
        raise LightGBMError("Empty data file")
    tabs = min(c[1] for c in cnt)
    commas = min(c[0] for c in cnt)
    colons = min(c[2] for c in cnt)
    if tabs > 0:
        return "tsv"
    if commas > 0:
        return "csv"
    if colons > 0:
        return "libsvm"
    return "csv"  # single-column fallback


_NA_TOKENS = ("", "na", "nan", "NaN", "NULL", "N/A", "NA", "null")


def _parse_dense(lines: List[str], sep: str) -> np.ndarray:
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rows.append([float(tok) if tok not in _NA_TOKENS
                     else np.nan for tok in line.split(sep)])
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), np.nan)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
    return out


def _parse_libsvm(lines: List[str]) -> np.ndarray:
    """label idx:val idx:val ... (1-based or 0-based indices accepted)."""
    parsed = []
    max_idx = -1
    for line in lines:
        line = line.strip()
        if not line:
            continue
        toks = line.split()
        label = float(toks[0])
        feats = {}
        for tok in toks[1:]:
            if ":" not in tok:
                continue
            k, v = tok.split(":", 1)
            try:
                idx = int(k)
            except ValueError:
                continue             # qid:-style prefixes are skipped
            feats[idx] = float(v)
            max_idx = max(max_idx, idx)
        parsed.append((label, feats))
    out = np.zeros((len(parsed), max_idx + 2))
    for i, (label, feats) in enumerate(parsed):
        out[i, 0] = label
        for k, v in feats.items():
            out[i, k + 1] = v
    return out


_CHUNK_ROWS = 200_000


def _read_head(filename: str, max_bytes: int = 1 << 16,
               want_lines: int = 34) -> List[str]:
    """First lines of the file for format/width detection — the whole file
    is never read into Python strings (dataset_loader.cpp:741's streaming
    stance; the old readlines() path held ~2GB of str objects at 10M
    rows).  The buffer grows until it holds ``want_lines`` complete lines
    (very wide rows — thousands of features — exceed a fixed buffer)."""
    with open_file(filename) as fh:
        head = fh.read(max_bytes)
        truncated = len(head) == max_bytes
        while truncated and head.count("\n") < want_lines:
            more = fh.read(max_bytes)
            head += more
            truncated = len(more) == max_bytes
    lines = head.splitlines()
    # only a buffer-boundary cut makes the tail line incomplete; a short
    # file's last line is complete even without a trailing newline
    if truncated and len(lines) > 1 and not head.endswith("\n"):
        lines = lines[:-1]
    return lines


def _iter_dense_chunks(filename: str, sep: str, skip_rows: int,
                       chunk_rows: int = _CHUNK_ROWS):
    """Yield [chunk, ncol] float64 arrays from a CSV/TSV file via pandas'
    C tokenizer (the numpy-tokenized chunked reader; peak memory is one
    chunk)."""
    import pandas as pd
    handle = None
    if uri_scheme(filename):
        # pandas accepts file objects but does not close caller-supplied
        # handles — close deterministically even on a mid-parse failure
        handle = filename = open_file(filename)
    try:
        reader = pd.read_csv(filename, sep=sep, header=None,
                             skiprows=skip_rows, chunksize=chunk_rows,
                             na_values=list(_NA_TOKENS), dtype=np.float64,
                             keep_default_na=True)
        for chunk in reader:
            yield chunk.to_numpy(dtype=np.float64)
    finally:
        if handle is not None:
            handle.close()


def _read_dense_matrix(filename: str, sep: str, skip_rows: int) -> np.ndarray:
    """Whole-file dense parse, chunked C tokenizer with a pure-Python
    fallback for ragged/odd files."""
    try:
        chunks = list(_iter_dense_chunks(filename, sep, skip_rows))
        return (np.vstack(chunks) if len(chunks) > 1 else chunks[0])
    except Exception:
        with open_file(filename) as fh:
            lines = fh.readlines()[skip_rows:]
        return _parse_dense(lines, sep)


def _column_index(spec: str, header_names: Optional[List[str]]) -> int:
    """Resolve 'name:<col>' / numeric column spec (config.h label_column)."""
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names is None or name not in header_names:
            raise LightGBMError(f"Column name {name} not found in header")
        return header_names.index(name)
    return int(spec)


def load_file_to_dataset(filename: str, config: Config, reference=None):
    """Text file -> TpuDataset (DatasetLoader::LoadFromFile,
    dataset_loader.cpp:162)."""
    from .dataset import TpuDataset

    if not file_io.exists(filename):
        raise LightGBMError(f"Data file {filename} doesn't exist")
    if filename.endswith(".bin") or _is_binary(filename):
        return TpuDataset.load_binary(filename)

    import time
    t0 = time.perf_counter()
    head = _read_head(filename)
    header_names: Optional[List[str]] = None
    skip_rows = 0
    if config.header and head:
        first = head[0].strip()
        sep = "\t" if "\t" in first else ","
        header_names = first.split(sep)
        head = head[1:]
        skip_rows = 1

    if not head:
        raise LightGBMError(
            f"Data file {filename} contains no data rows"
            + (" (only a header)" if config.header else ""))
    fmt = _detect_format(head[:32])
    log_info(f"Loading {filename} as {fmt}")
    sep = "\t" if fmt == "tsv" else ","
    ncol = (_parse_libsvm(head[:32]).shape[1] if fmt == "libsvm"
            else len(head[0].strip().split(sep)))
    label_col = (0 if fmt == "libsvm"
                 else (_column_index(config.label_column, header_names)
                       if config.label_column else 0))
    weight_col = (_column_index(config.weight_column, header_names)
                  if config.weight_column else -1)
    group_col = (_column_index(config.group_column, header_names)
                 if config.group_column else -1)
    ignore_cols = set()
    if config.ignore_column:
        for tok in str(config.ignore_column).split(","):
            tok = tok.strip()
            if tok:
                ignore_cols.add(_column_index(tok, header_names))
    drop = {label_col} | ignore_cols
    if weight_col >= 0:
        drop.add(weight_col)
    if group_col >= 0:
        drop.add(group_col)
    def resolve_cols(width):
        """Final feature columns / names / categorical indices for the
        ACTUAL parsed width (ragged/libsvm files can exceed the head)."""
        cols = [c for c in range(width) if c not in drop]
        names = ([header_names[c] for c in cols]
                 if header_names and width <= len(header_names) else None)
        cats: List[int] = []
        if config.categorical_feature:
            for tok in str(config.categorical_feature).split(","):
                tok = tok.strip()
                if not tok:
                    continue
                orig = _column_index(tok, header_names)
                # map original column index to feature index after drops
                if orig in cols:
                    cats.append(cols.index(orig))
        return cols, names, cats

    feat_cols, feat_names, cat_idx = resolve_cols(ncol)

    ds = None
    if fmt != "libsvm" and config.two_round:
        try:
            ds = _load_two_round(filename, sep, skip_rows, config, label_col,
                                 weight_col, group_col, feat_cols, feat_names,
                                 cat_idx, reference, t0, ncol, resolve_cols)
        except (LightGBMError, MemoryError):
            # MemoryError must not fall through: two_round exists BECAUSE
            # the file doesn't fit in RAM, and the one-round fallback
            # would only OOM harder
            raise
        except Exception as e:
            # the streaming C tokenizer rejects ragged/odd dense files the
            # one-round path handles via its pure-Python fallback — keep
            # behavior consistent between the two modes for the same file
            log_warning(f"two_round streaming parse failed ({e}); "
                        f"falling back to one-round loading")
            ds = None
    if ds is not None:
        qids = ds._qids_tmp
        del ds._qids_tmp
    else:
        if fmt == "libsvm":
            mat = None
            if skip_rows == 0:
                # native two-pass tokenizer (src/native/textparse.cpp);
                # the Python parser is the spec and the fallback
                from .native import parse_libsvm_native
                try:
                    with open_file(filename, "rb") as fh:
                        mat = parse_libsvm_native(fh.read())
                except MemoryError:
                    # the readlines() fallback holds the same bytes as
                    # millions of str objects — it can only OOM harder
                    raise
                except Exception:
                    mat = None
            if mat is None:
                with open_file(filename) as fh:
                    lines = fh.readlines()[skip_rows:]
                mat = _parse_libsvm(lines)
        else:
            mat = _read_dense_matrix(filename, sep, skip_rows)
        if mat.shape[1] != ncol:
            # the head under-estimated the width (libsvm tail features or
            # a ragged dense file through the fallback parser)
            feat_cols, feat_names, cat_idx = resolve_cols(mat.shape[1])
        t_read = time.perf_counter() - t0
        label = mat[:, label_col]
        weights = mat[:, weight_col] if weight_col >= 0 else None
        qids = mat[:, group_col] if group_col >= 0 else None
        X = mat[:, feat_cols]
        t0b = time.perf_counter()
        ds = TpuDataset.from_numpy(
            X, label=label, config=config, weights=weights,
            categorical_features=cat_idx, feature_names=feat_names,
            reference=reference)
        log_info(f"load: read={t_read:.2f}s "
                 f"bin={time.perf_counter() - t0b:.2f}s")
    if qids is not None:
        ds.metadata.set_query_from_ids(qids)
    # group file sidecar: <data>.query (dataset_loader.cpp query file load)
    qfile = filename + ".query"
    if qids is None and os.path.exists(qfile):
        groups = np.loadtxt(qfile, dtype=np.int64, ndmin=1)
        ds.metadata.set_query(groups)
    wfile = filename + ".weight"
    if ds.metadata.weights is None and os.path.exists(wfile):
        ds.metadata.set_weights(np.loadtxt(wfile, ndmin=1))
    ifile = filename + ".init"
    if os.path.exists(ifile):
        ds.metadata.set_init_score(np.loadtxt(ifile, ndmin=1).ravel())
    return ds


def _load_two_round(filename: str, sep: str, skip_rows: int, config: Config,
                    label_col: int, weight_col: int, group_col: int,
                    feat_cols: List[int], feat_names, cat_idx, reference,
                    t0: float, ncol: int = -1, resolve_cols=None):
    """Two-pass low-memory loading (two_round config;
    dataset_loader.cpp:741-840 SampleTextDataFromFile + two-round
    ExtractFeatures): pass 1 streams chunks keeping only a uniform
    reservoir sample for bin finding plus the label/weight/query columns;
    pass 2 streams again and quantizes straight into the preallocated bin
    matrix.  Peak memory = binned matrix + one raw chunk + the sample."""
    import time

    from .bundle import bundle_dtype, quantize_bundled
    from .dataset import TpuDataset

    rng = np.random.RandomState(config.data_random_seed)
    S_target = int(config.bin_construct_sample_cnt)
    sample_rows: List[np.ndarray] = []
    sample_full: Optional[np.ndarray] = None
    labels, weights, qids = [], [], []
    n_seen = 0
    for chunk in _iter_dense_chunks(filename, sep, skip_rows):
        k = chunk.shape[0]
        if n_seen == 0 and resolve_cols is not None \
                and chunk.shape[1] != ncol:
            # the head buffer truncated a very wide first row; re-resolve
            # the column roles from the true parsed width
            feat_cols, feat_names, cat_idx = resolve_cols(chunk.shape[1])
        labels.append(np.ascontiguousarray(chunk[:, label_col]))
        if weight_col >= 0:
            weights.append(np.ascontiguousarray(chunk[:, weight_col]))
        if group_col >= 0:
            qids.append(np.ascontiguousarray(chunk[:, group_col]))
        if reference is None:
            feats = chunk[:, feat_cols]
            take_head = max(0, min(S_target - n_seen, k))
            if take_head:
                sample_rows.append(feats[:take_head].copy())
            if take_head < k:
                if sample_full is None:
                    sample_full = np.vstack(sample_rows)
                    sample_rows = []
                # vectorized reservoir: global row i replaces a random
                # slot with probability S/(i+1)
                gi = n_seen + np.arange(take_head, k)
                slots = (rng.random_sample(len(gi))
                         * (gi + 1)).astype(np.int64)
                hit = slots < S_target
                for r, s in zip(np.nonzero(hit)[0], slots[hit]):
                    sample_full[s] = feats[take_head + r]
        n_seen += k
    if reference is None and sample_full is None:
        sample_full = (np.vstack(sample_rows) if sample_rows
                       else np.zeros((0, len(feat_cols))))
    t_pass1 = time.perf_counter() - t0

    N = n_seen
    ds = TpuDataset()
    ds.num_data = N
    ds.num_total_features = len(feat_cols)
    ds.feature_names = (list(feat_names) if feat_names
                        else [f"Column_{i}" for i in range(len(feat_cols))])
    if reference is not None:
        check(reference.num_total_features == len(feat_cols),
              "validation data has a different number of features")
        ds.bin_mappers = reference.bin_mappers
        ds.used_feature_indices = reference.used_feature_indices
        ds.max_num_bin = reference.max_num_bin
        ds.monotone_constraints = reference.monotone_constraints
        ds.feature_penalty = reference.feature_penalty
        ds.feature_names = list(reference.feature_names)
        ds.bundle = reference.bundle
    else:
        S = sample_full.shape[0]
        ds._sample_idx = np.arange(S)
        ds._fit_bin_mappers_from_cols(
            config, set(int(c) for c in cat_idx), len(feat_cols),
            lambda f: np.asarray(sample_full[:, f], dtype=np.float64), S)
        ds._build_bundle(config, lambda j: np.asarray(
            sample_full[:, ds.used_feature_indices[j]], dtype=np.float64))
    t_bin = time.perf_counter() - t0 - t_pass1

    used = ds.used_feature_indices
    default_bins = np.asarray([ds.bin_mappers[f].default_bin for f in used],
                              dtype=np.int64)
    if ds.bundle is not None:
        dtype = bundle_dtype(ds.bundle)
    else:
        dtype = np.uint8 if ds.max_num_bin <= 256 else np.uint16
    out = np.zeros((N, ds.num_columns), dtype=dtype)
    off = 0
    for chunk in _iter_dense_chunks(filename, sep, skip_rows):
        feats = chunk[:, feat_cols]
        k = feats.shape[0]

        def col_bins(j, feats=feats):
            f = int(used[j])
            return ds.bin_mappers[f].value_to_bin(
                np.asarray(feats[:, f], dtype=np.float64))

        if ds.bundle is not None:
            quantize_bundled(col_bins, ds.bundle, default_bins, k,
                             out=out[off:off + k])
        else:
            # native one-pass chunk quantizer for the numerical columns
            # (fastbin.cpp, same path as TpuDataset._quantize); the
            # remainder takes the per-column fallback
            from .binning import BIN_TYPE_NUMERICAL
            from .native import quantize_rows_native
            num_pos = [j for j in range(len(used))
                       if ds.bin_mappers[int(used[j])].bin_type
                       == BIN_TYPE_NUMERICAL]
            nat = (quantize_rows_native(feats, [int(used[j])
                                                for j in num_pos],
                                        ds.bin_mappers, dtype)
                   if num_pos else None)
            if nat is not None:
                out[off:off + k, num_pos] = nat
                rest = [j for j in range(len(used)) if j not in
                        set(num_pos)]
            else:
                rest = range(len(used))
            for j in rest:
                out[off:off + k, j] = col_bins(j).astype(dtype)
        off += k
    ds.binned = out
    ds._device_binned = None
    t_pass2 = time.perf_counter() - t0 - t_pass1 - t_bin
    log_info(f"two-round load: sample_pass={t_pass1:.2f}s bin={t_bin:.2f}s "
             f"quantize_pass={t_pass2:.2f}s rows={N}")

    ds.metadata.init(N)
    ds.metadata.set_label(np.concatenate(labels) if labels
                          else np.zeros(0))
    if weights:
        ds.metadata.set_weights(np.concatenate(weights))
    ds._qids_tmp = np.concatenate(qids) if qids else None
    return ds


def _is_binary(filename: str) -> bool:
    from .dataset import _BINARY_MAGIC
    with open_file(filename, "rb") as fh:
        head = fh.read(len(_BINARY_MAGIC))
    return head == _BINARY_MAGIC


def parse_file_to_matrix(filename: str, has_header: bool,
                         num_features: int, label_column: str = ""):
    """Parse a prediction input file into (X [N, num_features], label).

    Matches the CLI predict path's handling (Predictor file pipeline,
    reference src/application/predictor.hpp:69-110): auto-detected
    CSV/TSV/LibSVM, label column stripped (column 0 unless
    ``label_column`` names another, as in the CLI config), width aligned
    to the model's feature count.  Dense files whose width already equals
    the model's feature count are treated as label-free; LibSVM always
    carries a leading label.
    """
    head = _read_head(filename)
    header_names = None
    skip_rows = 0
    if has_header and head:
        sep = "\t" if "\t" in head[0] else ","
        header_names = head[0].strip().split(sep)
        head = head[1:]
        skip_rows = 1
    fmt = _detect_format(head[:32])
    if fmt == "libsvm":
        with open(filename) as fh:
            lines = fh.readlines()[skip_rows:]
        mat = _parse_libsvm(lines)
        label_col = 0
    else:
        sep = "\t" if fmt == "tsv" else ","
        mat = _read_dense_matrix(filename, sep, skip_rows)
        if mat.shape[1] == num_features:   # no label column present
            return mat, None
        label_col = (_column_index(label_column, header_names)
                     if label_column else 0)
    label = mat[:, label_col]
    X = np.delete(mat, label_col, axis=1)
    if X.shape[1] < num_features:
        X = np.pad(X, ((0, 0), (0, num_features - X.shape[1])))
    elif X.shape[1] > num_features:
        X = X[:, :num_features]
    return X, label
