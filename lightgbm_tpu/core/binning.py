"""Feature quantization: value -> integer bin mapping.

Reimplements the reference's BinMapper behavior (include/LightGBM/bin.h:78-246,
src/io/bin.cpp:25-410) in numpy: greedy equal-ish-frequency bin-bound finding
(``GreedyFindBin`` bin.cpp:74), the zero-aware split of the value range
(``FindBinWithZeroAsOneBin`` bin.cpp:152), missing handling (None/Zero/NaN),
and categorical bin mapping by descending count with a 99% mass cutoff
(bin.cpp:310-375).  Bin *assignment* (``ValueToBin`` bin.h:496-549) is
vectorized with ``np.searchsorted`` so full columns quantize in one shot.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import check, log_warning

K_ZERO_THRESHOLD = 1e-35
K_SPARSE_THRESHOLD = 0.8

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

MISSING_TYPE_NAMES = {MISSING_NONE: "none", MISSING_ZERO: "zero",
                      MISSING_NAN: "nan"}
MISSING_TYPE_FROM_NAME = {v: k for k, v in MISSING_TYPE_NAMES.items()}

BIN_TYPE_NUMERICAL = 0
BIN_TYPE_CATEGORICAL = 1


def _next_after_up(a: float) -> float:
    """std::nextafter(a, +inf) (reference Common::GetDoubleUpperBound)."""
    return math.nextafter(a, math.inf)


def _double_equal_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a, inf) for ordered a<=b (Common::CheckDoubleEqualOrdered)."""
    return b <= _next_after_up(a)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """Greedy equal-frequency-ish bin upper bounds (bin.cpp:74-150).

    Dispatches to the native implementation (src/native/fastbin.cpp —
    this Python body is its spec and fallback); the interpreter loop over
    ~200k distinct sample values per feature dominated single-core
    dataset construction."""
    check(max_bin > 0, "max_bin must be positive")
    from .native import greedy_find_bin_native
    native = greedy_find_bin_native(distinct_values, counts, max_bin,
                                    total_cnt, min_data_in_bin)
    if native is not None:
        return native
    return _greedy_find_bin_py(distinct_values, counts, max_bin, total_cnt,
                               min_data_in_bin)


def _greedy_find_bin_py(distinct_values: np.ndarray, counts: np.ndarray,
                        max_bin: int, total_cnt: int,
                        min_data_in_bin: int) -> List[float]:
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                val = _next_after_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _double_equal_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur_cnt = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    # values with huge counts get their own bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = float(distinct_values[0])
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower_bounds[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bounds or not _double_equal_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Split the range at zero so one bin holds exactly zero (bin.cpp:152-208)."""
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    left_cnt_data = int(counts[left_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())
    cnt_zero = int(total_sample_cnt) - left_cnt_data - right_cnt_data

    nz = np.nonzero(distinct_values > -K_ZERO_THRESHOLD)[0]
    left_cnt = int(nz[0]) if len(nz) else len(distinct_values)

    bounds: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bounds = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    nz = np.nonzero(distinct_values[left_cnt:] > K_ZERO_THRESHOLD)[0]
    right_start = left_cnt + int(nz[0]) if len(nz) else -1

    right_max_bin = max_bin - 1 - len(bounds)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:],
                                       counts[right_start:], right_max_bin,
                                       right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(math.inf)
    check(len(bounds) <= max_bin, "bin bound count exceeds max_bin")
    return bounds


def _distinct_with_zero(values_sorted: np.ndarray, zero_cnt: int):
    """Distinct values/counts from a sorted sample, zero block spliced in at
    its ordered position (bin.cpp:236-270).  Adjacent float-equal values
    merge, keeping the larger value.

    Vectorized as run-length grouping: a new group starts wherever the
    next value exceeds nextafter(previous) — the same chained adjacent
    comparison the scalar loop made (the former Python loop cost ~0.7s
    per feature at the 200k-row binning sample)."""
    n = len(values_sorted)
    if n == 0:
        return (np.asarray([0.0]), np.asarray([zero_cnt], dtype=np.int64))
    v = np.asarray(values_sorted, dtype=np.float64)
    boundary = v[1:] > np.nextafter(v[:-1], np.inf)
    idx = np.flatnonzero(boundary) + 1
    starts = np.concatenate([[0], idx]).astype(np.int64)
    ends = np.concatenate([idx, [n]]).astype(np.int64)
    dvals = v[ends - 1]                 # keep the larger of float-equals
    dcnts = ends - starts
    firsts = v[starts]
    if v[0] > 0.0 and zero_cnt > 0:
        dvals = np.concatenate([[0.0], dvals])
        dcnts = np.concatenate([[zero_cnt], dcnts])
    elif v[n - 1] < 0.0 and zero_cnt > 0:
        dvals = np.concatenate([dvals, [0.0]])
        dcnts = np.concatenate([dcnts, [zero_cnt]])
    else:
        # the scalar loop splices a zero block (even with count 0) at the
        # unique negative->positive group boundary
        pos = np.flatnonzero((dvals[:-1] < 0.0) & (firsts[1:] > 0.0))
        if len(pos):
            p = int(pos[0]) + 1
            dvals = np.insert(dvals, p, 0.0)
            dcnts = np.insert(dcnts, p, zero_cnt)
    return dvals, dcnts.astype(np.int64)


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """True when no split of this feature can satisfy min-data (bin.cpp:40-72)."""
    if bin_type == BIN_TYPE_NUMERICAL:
        left = 0
        for i in range(len(cnt_in_bin) - 1):
            left += int(cnt_in_bin[i])
            if left >= filter_cnt and total_cnt - left >= filter_cnt:
                return False
        return True
    # categorical: one-vs-rest viability
    if len(cnt_in_bin) <= 2:
        for i in range(len(cnt_in_bin) - 1):
            left = int(cnt_in_bin[i])
            if left >= filter_cnt and total_cnt - left >= filter_cnt:
                return False
        return True
    return False


class BinMapper:
    """Per-feature value->bin quantizer (reference BinMapper, bin.h:78-246)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.bin_type: int = BIN_TYPE_NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # ------------------------------------------------------------------ fit
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 20,
                 bin_type: int = BIN_TYPE_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> "BinMapper":
        """Fit bin bounds from a (possibly subsampled) value sample.

        ``total_sample_cnt - len(values)`` values are implicitly zero: sparse
        columns pass only their non-zero entries (bin.cpp:210-235).
        """
        values = np.asarray(values, dtype=np.float64)
        num_sample_values = len(values)
        nan_mask = np.isnan(values)
        values = values[~nan_mask]
        na_cnt = int(nan_mask.sum())

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NONE if na_cnt == 0 else MISSING_NAN
        if not use_missing:
            na_cnt = 0  # NaNs already dropped; they simply vanish from the sample

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)
        values_sorted = np.sort(values, kind="stable")
        distinct, counts = _distinct_with_zero(values_sorted, zero_cnt)
        if len(distinct) == 0:
            self.is_trivial = True
            return self
        self.min_val = float(distinct[0])
        self.max_val = float(distinct[-1])

        cnt_in_bin: List[int] = []
        if bin_type == BIN_TYPE_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin, total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin, total_sample_cnt, min_data_in_bin)
            else:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin - 1, total_sample_cnt - na_cnt,
                    min_data_in_bin)
                bounds.append(math.nan)  # trailing NaN bin
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            # count per bin for trivial-feature filtering
            cnt_in_bin = [0] * self.num_bin
            i_bin = 0
            for v, c in zip(distinct, counts):
                while v > self.bin_upper_bound[i_bin]:
                    i_bin += 1
                cnt_in_bin[i_bin] += int(c)
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            check(self.num_bin <= max_bin, "num_bin exceeds max_bin")
        else:
            cnt_in_bin = self._find_bin_categorical(
                distinct, counts, max_bin, min_data_in_bin, total_sample_cnt,
                na_cnt)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and _need_filter(
                cnt_in_bin, int(total_sample_cnt), min_split_data, bin_type):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(np.array([0.0]))[0])
            if bin_type == BIN_TYPE_CATEGORICAL:
                check(self.default_bin > 0, "categorical default_bin must be > 0")
            self.sparse_rate = cnt_in_bin[self.default_bin] / max(total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0
        return self

    def _find_bin_categorical(self, distinct: np.ndarray, counts: np.ndarray,
                              max_bin: int, min_data_in_bin: int,
                              total_sample_cnt: int, na_cnt: int) -> List[int]:
        """Categorical mapping: by descending count, 99% mass cutoff
        (bin.cpp:310-375)."""
        ints: List[int] = []
        int_counts: List[int] = []
        for v, c in zip(distinct, counts):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                log_warning("Met negative value in categorical features, "
                            "will convert it to NaN")
            elif ints and iv == ints[-1]:
                int_counts[-1] += int(c)
            else:
                ints.append(iv)
                int_counts.append(int(c))
        self.num_bin = 0
        rest_cnt = int(total_sample_cnt) - na_cnt
        cnt_in_bin: List[int] = []
        if rest_cnt > 0:
            if ints and ints[-1] // 100 > len(ints):
                log_warning("Met categorical feature which contains sparse values. "
                            "Consider renumbering to consecutive integers "
                            "started from zero")
            order = sorted(range(len(ints)), key=lambda i: (-int_counts[i], ints[i]))
            ints = [ints[i] for i in order]
            int_counts = [int_counts[i] for i in order]
            # avoid first bin being category 0 (bin 0 is the "default"/other bin)
            if ints and ints[0] == 0:
                if len(ints) == 1:
                    ints.append(ints[0] + 1)
                    int_counts.append(0)
                ints[0], ints[1] = ints[1], ints[0]
                int_counts[0], int_counts[1] = int_counts[1], int_counts[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            used_cnt = 0
            eff_max_bin = min(len(ints), max_bin)
            self.bin_2_categorical = []
            self.categorical_2_bin = {}
            cur = 0
            while cur < len(ints) and (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                if int_counts[cur] < min_data_in_bin and cur > 1:
                    break
                self.bin_2_categorical.append(ints[cur])
                self.categorical_2_bin[ints[cur]] = self.num_bin
                used_cnt += int_counts[cur]
                cnt_in_bin.append(int_counts[cur])
                self.num_bin += 1
                cur += 1
            if cur == len(ints) and na_cnt > 0:
                self.bin_2_categorical.append(-1)
                self.categorical_2_bin[-1] = self.num_bin
                cnt_in_bin.append(0)
                self.num_bin += 1
            self.missing_type = (MISSING_NONE if cur == len(ints) and na_cnt == 0
                                 else MISSING_NAN)
            if cnt_in_bin:
                # the last bin absorbs any leftover mass (reference adds
                # total - used to the final bin's count for filtering purposes)
                cnt_in_bin[-1] += int(total_sample_cnt) - used_cnt
        return cnt_in_bin

    # ---------------------------------------------------------------- apply
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (bin.h:496-549)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_NUMERICAL:
            nan_mask = np.isnan(values)
            v = np.where(nan_mask, 0.0, values)
            ub = self.bin_upper_bound
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            # first bin whose upper bound >= value  (value <= ub[bin])
            bins = np.searchsorted(ub[:max(n_search - 1, 0)], v, side="left")
            if self.missing_type == MISSING_NAN:
                bins = np.where(nan_mask, self.num_bin - 1, bins)
            return bins.astype(np.int32)
        # categorical
        out = np.full(values.shape, self.num_bin - 1, dtype=np.int32)
        nan_mask = ~np.isfinite(values)
        iv = np.where(nan_mask, -1, values).astype(np.int64)
        if self.categorical_2_bin:
            cats = np.fromiter(self.categorical_2_bin.keys(), dtype=np.int64)
            bins_ = np.fromiter(self.categorical_2_bin.values(), dtype=np.int64)
            order = np.argsort(cats)
            cats, bins_ = cats[order], bins_[order]
            pos = np.searchsorted(cats, iv)
            pos = np.clip(pos, 0, len(cats) - 1)
            hit = (cats[pos] == iv) & (iv >= 0)
            out = np.where(hit, bins_[pos], out).astype(np.int32)
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative real value of a bin (used for threshold realization;
        reference BinMapper::BinToValue)."""
        if self.bin_type == BIN_TYPE_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    @property
    def is_categorical(self) -> bool:
        return self.bin_type == BIN_TYPE_CATEGORICAL

    # ------------------------------------------------------------ serialization
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.bin_type = int(d["bin_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m
