"""Per-dataset metadata: labels, weights, query boundaries, init scores.

Reference: include/LightGBM/dataset.h:41-250 (`Metadata`),
src/io/metadata.cpp (CheckOrPartition, query-boundary construction,
auto query weights).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils.log import check, log_fatal


class Metadata:
    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None          # [N] f32
        self.weights: Optional[np.ndarray] = None        # [N] f32 or None
        self.query_boundaries: Optional[np.ndarray] = None  # [Q+1] i32 or None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None     # [N*num_class] f64 or None

    def init(self, num_data: int) -> None:
        self.num_data = num_data
        if self.label is None:
            self.label = np.zeros(num_data, dtype=np.float32)

    def set_label(self, label: np.ndarray) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).ravel()
        check(len(label) == self.num_data,
              f"Length of label ({len(label)}) != num_data ({self.num_data})")
        self.label = label

    def set_weights(self, weights: Optional[np.ndarray]) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.ascontiguousarray(weights, dtype=np.float32).ravel()
        check(len(weights) == self.num_data,
              f"Length of weights ({len(weights)}) != num_data ({self.num_data})")
        self.weights = weights
        self._update_query_weights()

    def set_query(self, query: Optional[np.ndarray]) -> None:
        """Accepts per-query group sizes (LightGBM's group field)."""
        if query is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        query = np.ascontiguousarray(query, dtype=np.int64).ravel()
        boundaries = np.concatenate([[0], np.cumsum(query)]).astype(np.int32)
        check(int(boundaries[-1]) == self.num_data,
              f"Sum of query counts ({int(boundaries[-1])}) != num_data "
              f"({self.num_data})")
        self.query_boundaries = boundaries
        self._update_query_weights()

    def set_query_from_ids(self, qids: np.ndarray) -> None:
        """Build boundaries from a per-row query-id column (CLI group column)."""
        qids = np.asarray(qids).ravel()
        change = np.nonzero(np.diff(qids))[0] + 1
        boundaries = np.concatenate([[0], change, [len(qids)]]).astype(np.int32)
        self.query_boundaries = boundaries
        self._update_query_weights()

    def _update_query_weights(self) -> None:
        """Average member weight per query (metadata.cpp query weight calc)."""
        if self.weights is None or self.query_boundaries is None:
            self.query_weights = None
            return
        b = self.query_boundaries
        sums = np.add.reduceat(self.weights, b[:-1])
        cnts = np.diff(b)
        self.query_weights = (sums / np.maximum(cnts, 1)).astype(np.float32)

    def set_init_score(self, init_score: Optional[np.ndarray]) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.ascontiguousarray(init_score, dtype=np.float64).ravel()
        if len(init_score) % max(self.num_data, 1) != 0:
            log_fatal(f"Initial score size {len(init_score)} is not a multiple "
                      f"of num_data {self.num_data}")
        self.init_score = init_score

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1

    def subset(self, indices: np.ndarray) -> "Metadata":
        out = Metadata(len(indices))
        if self.label is not None:
            out.label = self.label[indices]
        if self.weights is not None:
            out.weights = self.weights[indices]
        if self.init_score is not None:
            nc = len(self.init_score) // self.num_data
            out.init_score = np.concatenate(
                [self.init_score[c * self.num_data + indices] for c in range(nc)])
        # query subsetting is only valid when indices respect query boundaries;
        # the engine's cv() path groups folds by query before calling this.
        return out
