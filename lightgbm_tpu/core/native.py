"""ctypes loader for the native binning hot path (src/native/fastbin.cpp).

The reference keeps bin construction in C++ (bin.cpp:74-208); the Python
greedy loop costs ~0.4s per feature at the default 200k-row sample on a
single core, so dataset construction at HIGGS scale spent most of its time
here.  Built on demand with the system g++; everything degrades to the
pure-Python implementation when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "src", "native", "fastbin.cpp")


def _host_tag() -> str:
    """Short hash of this host's CPU capabilities: -march=native builds
    are keyed by it, so a checkout shared across heterogeneous hosts
    (NFS multi-machine training) rebuilds per ISA instead of SIGILLing
    on a foreign host's vectorized .so."""
    import hashlib
    import platform
    raw = platform.machine()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    raw += line
                    break
    except OSError:
        pass
    return hashlib.md5(raw.encode()).hexdigest()[:8]


def _build(src: str, out: str) -> None:
    # -march=native vectorizes the quantizer's compare-count (the 8.8x
    # vs -O2); the output filename carries _host_tag() so the cache
    # never crosses ISAs
    cmd = ["g++", "-O3", "-march=native", "-fPIC", "-shared",
           "-std=c++17", src, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-500:])


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None when unavailable
    (no g++ / read-only tree) — callers fall back to Python."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    src = _source_path()
    if not os.path.exists(src):
        return None
    out = os.path.join(os.path.dirname(src),
                       f"libfastbin.{_host_tag()}.so")
    try:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            _build(src, out)
        _lib = ctypes.CDLL(out)
        _lib.lgbmtpu_greedy_find_bin.restype = ctypes.c_int64
        _lib.lgbmtpu_greedy_find_bin.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double)]
        _lib.lgbmtpu_values_to_bins.restype = None
        _lib.lgbmtpu_values_to_bins.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]
    except Exception as e:  # noqa: BLE001 — binning must keep working
        from ..utils.log import log_warning
        log_warning(f"native fastbin unavailable ({type(e).__name__}: "
                    f"{str(e)[-200:]}); falling back to the (much slower) "
                    f"Python bin-bound loop")
        _lib = None
    return _lib


def greedy_find_bin_native(distinct_values: np.ndarray, counts: np.ndarray,
                           max_bin: int, total_cnt: int,
                           min_data_in_bin: int):
    """Native greedy_find_bin; returns a list of bounds or None when the
    library is unavailable."""
    L = lib()
    if L is None:
        return None
    dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
    ct = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(int(max_bin), 1) + 1, dtype=np.float64)
    n = L.lgbmtpu_greedy_find_bin(
        dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ct.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(dv), int(max_bin), int(total_cnt), int(min_data_in_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return list(out[:n])


_text_lib: Optional[ctypes.CDLL] = None
_text_tried = False


def text_lib() -> Optional[ctypes.CDLL]:
    """Native LibSVM tokenizer (src/native/textparse.cpp), built on first
    use like fastbin; None -> callers fall back to the Python parser."""
    global _text_lib, _text_tried
    if _text_tried:
        return _text_lib
    _text_tried = True
    src = os.path.join(os.path.dirname(_source_path()), "textparse.cpp")
    if not os.path.exists(src):
        return None
    out = os.path.join(os.path.dirname(src),
                       f"libtextparse.{_host_tag()}.so")
    try:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            _build(src, out)
        _text_lib = ctypes.CDLL(out)
        _text_lib.lgbmtpu_libsvm_scan.restype = ctypes.c_int64
        _text_lib.lgbmtpu_libsvm_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        _text_lib.lgbmtpu_libsvm_fill.restype = ctypes.c_int64
        _text_lib.lgbmtpu_libsvm_fill.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_int64]
    except Exception as e:  # noqa: BLE001 — parsing must keep working
        from ..utils.log import log_warning
        log_warning(f"native textparse unavailable ({type(e).__name__}: "
                    f"{str(e)[-200:]}); falling back to the Python "
                    f"LibSVM parser")
        _text_lib = None
    return _text_lib


def parse_libsvm_native(data: bytes):
    """bytes -> dense [n, max_idx + 2] float64 (label in column 0), or
    None when the native tokenizer is unavailable."""
    L = text_lib()
    if L is None:
        return None
    n_rows = ctypes.c_int64(0)
    max_idx = ctypes.c_int64(-1)
    if L.lgbmtpu_libsvm_scan(data, len(data), ctypes.byref(n_rows),
                             ctypes.byref(max_idx)) != 0:
        return None
    out = np.zeros((n_rows.value, max(max_idx.value, -1) + 2),
                   dtype=np.float64)
    filled = L.lgbmtpu_libsvm_fill(
        data, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_rows.value, out.shape[1])
    if filled != n_rows.value:
        return None
    return out


def _bind_quantize(L) -> bool:
    """Bind the quantizer symbols; False when the loaded .so predates
    them (stale build cache) — callers fall back to Python."""
    if getattr(L, "_quantize_bound", None) is not None:
        return L._quantize_bound
    try:
        L.lgbmtpu_quantize_rows
        L.lgbmtpu_quantize_rows_f32
    except AttributeError:
        L._quantize_bound = False
        return False
    L.lgbmtpu_quantize_rows.restype = None
    L.lgbmtpu_quantize_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_void_p]
    L.lgbmtpu_quantize_rows_f32.restype = None
    L.lgbmtpu_quantize_rows_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_void_p]
    L._quantize_bound = True
    return True


def quantize_rows_native(data: np.ndarray, feat_idx, mappers,
                         out_dtype) -> Optional[np.ndarray]:
    """One native pass quantizing every NUMERICAL used column of a
    row-major float matrix (core/binning.value_to_bin semantics); None
    when unavailable or any column is categorical (caller falls back).

    ~10x the per-column numpy path at 10M rows: no strided column
    copies, bounds stay in cache, and the output is written once.
    """
    from .binning import BIN_TYPE_NUMERICAL
    L = lib()
    if L is None:
        return None
    if data.dtype == np.float32:
        is_f64 = 0
    elif data.dtype == np.float64:
        is_f64 = 1
    else:
        return None
    if any(mappers[f].bin_type != BIN_TYPE_NUMERICAL for f in feat_idx):
        return None
    if not _bind_quantize(L):
        return None
    # contiguity copy LAST: it is only worth the memory once the native
    # path is certain to run
    if not data.flags.c_contiguous:
        data = np.ascontiguousarray(data)
    n, f_total = data.shape
    n_used = len(feat_idx)
    bounds = []
    offs = np.zeros(n_used + 1, dtype=np.int64)
    mt = np.zeros(n_used, dtype=np.int32)
    nb = np.zeros(n_used, dtype=np.int32)
    for j, f in enumerate(feat_idx):
        m = mappers[f]
        n_search = m.num_bin - (1 if m.missing_type == 2 else 0)
        ub = np.asarray(m.bin_upper_bound,
                        dtype=np.float64)[:max(n_search - 1, 0)]
        bounds.append(ub)
        offs[j + 1] = offs[j] + len(ub)
        mt[j] = m.missing_type
        nb[j] = m.num_bin
    flat = (np.concatenate(bounds) if bounds
            else np.zeros(0, np.float64))
    fidx = np.asarray(feat_idx, dtype=np.int64)
    out = np.empty((n, n_used), dtype=out_dtype)
    max_nb = int(np.max(offs[1:] - offs[:-1], initial=0))
    if is_f64 == 0 and out_dtype == np.uint8 and max_nb <= 128:
        # f32 fast path with EXACT thresholds: t[b] = smallest float
        # whose f64 value is > ub[b]; then ub[b] < (double)v  <=>
        # v >= t[b] because v's f64 image is exact and t[b] is the
        # least representable value past the bound
        t = flat.astype(np.float32)
        not_past = t.astype(np.float64) <= flat
        t = np.where(not_past, np.nextafter(t, np.float32(np.inf)), t)
        t = np.ascontiguousarray(t, dtype=np.float32)
        L.lgbmtpu_quantize_rows_f32(
            data.ctypes.data_as(ctypes.c_void_p), n, f_total,
            fidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n_used,
            t.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            mt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            nb.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    L.lgbmtpu_quantize_rows(
        data.ctypes.data_as(ctypes.c_void_p), is_f64, n, f_total,
        fidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n_used,
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        mt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nb.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        1 if out_dtype == np.uint16 else 0,
        out.ctypes.data_as(ctypes.c_void_p))
    return out
