"""ctypes loader for the native binning hot path (src/native/fastbin.cpp).

The reference keeps bin construction in C++ (bin.cpp:74-208); the Python
greedy loop costs ~0.4s per feature at the default 200k-row sample on a
single core, so dataset construction at HIGGS scale spent most of its time
here.  Built on demand with the system g++; everything degrades to the
pure-Python implementation when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "src", "native", "fastbin.cpp")


def _build(src: str, out: str) -> None:
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", src, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-500:])


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None when unavailable
    (no g++ / read-only tree) — callers fall back to Python."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    src = _source_path()
    if not os.path.exists(src):
        return None
    out = os.path.join(os.path.dirname(src), "libfastbin.so")
    try:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            _build(src, out)
        _lib = ctypes.CDLL(out)
        _lib.lgbmtpu_greedy_find_bin.restype = ctypes.c_int64
        _lib.lgbmtpu_greedy_find_bin.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double)]
        _lib.lgbmtpu_values_to_bins.restype = None
        _lib.lgbmtpu_values_to_bins.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]
    except Exception as e:  # noqa: BLE001 — binning must keep working
        from ..utils.log import log_warning
        log_warning(f"native fastbin unavailable ({type(e).__name__}: "
                    f"{str(e)[-200:]}); falling back to the (much slower) "
                    f"Python bin-bound loop")
        _lib = None
    return _lib


def greedy_find_bin_native(distinct_values: np.ndarray, counts: np.ndarray,
                           max_bin: int, total_cnt: int,
                           min_data_in_bin: int):
    """Native greedy_find_bin; returns a list of bounds or None when the
    library is unavailable."""
    L = lib()
    if L is None:
        return None
    dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
    ct = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(int(max_bin), 1) + 1, dtype=np.float64)
    n = L.lgbmtpu_greedy_find_bin(
        dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ct.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(dv), int(max_bin), int(total_cnt), int(min_data_in_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return list(out[:n])


_text_lib: Optional[ctypes.CDLL] = None
_text_tried = False


def text_lib() -> Optional[ctypes.CDLL]:
    """Native LibSVM tokenizer (src/native/textparse.cpp), built on first
    use like fastbin; None -> callers fall back to the Python parser."""
    global _text_lib, _text_tried
    if _text_tried:
        return _text_lib
    _text_tried = True
    src = os.path.join(os.path.dirname(_source_path()), "textparse.cpp")
    if not os.path.exists(src):
        return None
    out = os.path.join(os.path.dirname(src), "libtextparse.so")
    try:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            _build(src, out)
        _text_lib = ctypes.CDLL(out)
        _text_lib.lgbmtpu_libsvm_scan.restype = ctypes.c_int64
        _text_lib.lgbmtpu_libsvm_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        _text_lib.lgbmtpu_libsvm_fill.restype = ctypes.c_int64
        _text_lib.lgbmtpu_libsvm_fill.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_int64]
    except Exception as e:  # noqa: BLE001 — parsing must keep working
        from ..utils.log import log_warning
        log_warning(f"native textparse unavailable ({type(e).__name__}: "
                    f"{str(e)[-200:]}); falling back to the Python "
                    f"LibSVM parser")
        _text_lib = None
    return _text_lib


def parse_libsvm_native(data: bytes):
    """bytes -> dense [n, max_idx + 2] float64 (label in column 0), or
    None when the native tokenizer is unavailable."""
    L = text_lib()
    if L is None:
        return None
    n_rows = ctypes.c_int64(0)
    max_idx = ctypes.c_int64(-1)
    if L.lgbmtpu_libsvm_scan(data, len(data), ctypes.byref(n_rows),
                             ctypes.byref(max_idx)) != 0:
        return None
    out = np.zeros((n_rows.value, max(max_idx.value, -1) + 2),
                   dtype=np.float64)
    filled = L.lgbmtpu_libsvm_fill(
        data, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_rows.value, out.shape[1])
    if filled != n_rows.value:
        return None
    return out
