"""ctypes loader for the native binning hot path (src/native/fastbin.cpp).

The reference keeps bin construction in C++ (bin.cpp:74-208); the Python
greedy loop costs ~0.4s per feature at the default 200k-row sample on a
single core, so dataset construction at HIGGS scale spent most of its time
here.  Built on demand with the system g++; everything degrades to the
pure-Python implementation when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "src", "native", "fastbin.cpp")


def _build(src: str, out: str) -> None:
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", src, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-500:])


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None when unavailable
    (no g++ / read-only tree) — callers fall back to Python."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    src = _source_path()
    if not os.path.exists(src):
        return None
    out = os.path.join(os.path.dirname(src), "libfastbin.so")
    try:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            _build(src, out)
        _lib = ctypes.CDLL(out)
        _lib.lgbmtpu_greedy_find_bin.restype = ctypes.c_int64
        _lib.lgbmtpu_greedy_find_bin.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double)]
        _lib.lgbmtpu_values_to_bins.restype = None
        _lib.lgbmtpu_values_to_bins.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]
    except Exception as e:  # noqa: BLE001 — binning must keep working
        from ..utils.log import log_warning
        log_warning(f"native fastbin unavailable ({type(e).__name__}: "
                    f"{str(e)[-200:]}); falling back to the (much slower) "
                    f"Python bin-bound loop")
        _lib = None
    return _lib


def greedy_find_bin_native(distinct_values: np.ndarray, counts: np.ndarray,
                           max_bin: int, total_cnt: int,
                           min_data_in_bin: int):
    """Native greedy_find_bin; returns a list of bounds or None when the
    library is unavailable."""
    L = lib()
    if L is None:
        return None
    dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
    ct = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(int(max_bin), 1) + 1, dtype=np.float64)
    n = L.lgbmtpu_greedy_find_bin(
        dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ct.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(dv), int(max_bin), int(total_cnt), int(min_data_in_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return list(out[:n])
