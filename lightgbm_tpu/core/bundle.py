"""Exclusive Feature Bundling (EFB).

Reference: Dataset::FindGroups + FastFeatureBundling
(src/io/dataset.cpp:68-213) and FeatureGroup (include/LightGBM/
feature_group.h:33): mutually-(almost-)exclusive sparse features are packed
into one physical bin column, so a 10k-feature 99%-sparse matrix costs a
handful of dense byte columns instead of 10k.

TPU-first encoding (differs from the reference's per-group bin_offsets with
most-frequent-bin elision, feature_group.h:46-70, but serves the same
contract):

  * every multi-feature bundle is ONE column of the dense bin matrix;
  * column value 0 = "every member feature is at its default bin";
  * member feature ``f`` with bin ``b != default_bin[f]`` stores
    ``offset[f] + b`` (offsets accumulate ``1 + sum(num_bin)`` so ranges
    never collide; the per-feature default slot is simply never written);
  * conflicts (two members non-default on one row) keep the LAST member's
    value — the same bounded-information-loss tradeoff the reference
    accepts via ``max_conflict_rate`` (dataset.cpp:93-101);
  * at scan time the per-feature histogram is gathered back out of the
    group histogram and the default-bin slot is reconstructed as
    ``leaf_total - sum(stored bins)`` — the reference's FixHistogram
    (dataset.cpp:948-967) in vectorized form (ops/split.expand_group_hist).

Single-feature groups store the plain bin with offset 0, so when no
bundling happens the matrix is bit-identical to the unbundled layout.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

# 8-bit popcount table for packed conflict counting
_POPCOUNT8 = np.array([bin(i).count("1") for i in range(256)],
                      dtype=np.int32)

# group bin budget: keeps every bundled column uint8 and inside the pallas
# kernels' 256-bin ceiling (the reference GPU path uses the same cap,
# dataset.cpp:152: max_bin per group forced <= 256 when GPU is enabled)
MAX_BINS_PER_GROUP = 256


class BundleSpec:
    """Static description of the feature -> column packing."""

    __slots__ = ("groups", "feat_group", "feat_offset", "group_num_bin")

    def __init__(self, groups: List[List[int]], num_bins: np.ndarray):
        self.groups = [list(g) for g in groups]
        F = int(sum(len(g) for g in groups))
        self.feat_group = np.zeros(F, dtype=np.int32)
        self.feat_offset = np.zeros(F, dtype=np.int32)
        self.group_num_bin = np.zeros(len(groups), dtype=np.int32)
        for gi, g in enumerate(groups):
            if len(g) == 1:
                f = g[0]
                self.feat_group[f] = gi
                self.feat_offset[f] = 0
                self.group_num_bin[gi] = int(num_bins[f])
            else:
                off = 1                       # slot 0 = all-default
                for f in g:
                    self.feat_group[f] = gi
                    self.feat_offset[f] = off
                    off += int(num_bins[f])
                self.group_num_bin[gi] = off

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    def to_dict(self) -> dict:
        return {"groups": self.groups}

    @classmethod
    def from_dict(cls, d: dict, num_bins: np.ndarray) -> "BundleSpec":
        return cls(d["groups"], num_bins)


def find_groups(packed: np.ndarray, nnz: np.ndarray, num_bins: np.ndarray,
                is_bundleable: np.ndarray, max_conflict_cnt: int,
                max_bins_per_group: int = MAX_BINS_PER_GROUP
                ) -> List[List[int]]:
    """Greedy conflict-bounded grouping (Dataset::FindGroups,
    src/io/dataset.cpp:68-138).

    Args:
      packed: [F, ceil(S/8)] uint8 — per-feature non-default bitmask on the
        binning sample (np.packbits of the bool mask).
      nnz: [F] int — non-default count per feature on the sample.
      num_bins: [F] int — bins per feature.
      is_bundleable: [F] bool — sparse enough to enter a bundle
        (sparse_rate >= sparse_threshold); others become singletons.
      max_conflict_cnt: total conflicting sample rows allowed per group
        (int(max_conflict_rate * sample_cnt), dataset.cpp:157).

    Returns groups as lists of feature indices, ordered so bundleable
    multi-feature groups come first, then singletons in feature order.
    """
    F = packed.shape[0]
    cand = [f for f in range(F) if is_bundleable[f]]
    # by descending non-zero count (the second, usually-better order the
    # reference tries, dataset.cpp:168-176)
    cand.sort(key=lambda f: -int(nnz[f]))
    group_feats: List[List[int]] = []
    group_mask: List[np.ndarray] = []
    group_bins: List[int] = []
    group_conflicts: List[int] = []
    for f in cand:
        placed = False
        fb = 1 + int(num_bins[f])      # +1: the shared all-default slot
        for gi in range(len(group_feats)):
            if group_bins[gi] + int(num_bins[f]) > max_bins_per_group:
                continue
            conflicts = int(
                _POPCOUNT8[packed[f] & group_mask[gi]].sum())
            if group_conflicts[gi] + conflicts > max_conflict_cnt:
                continue
            group_feats[gi].append(f)
            group_mask[gi] |= packed[f]
            group_bins[gi] += int(num_bins[f])
            group_conflicts[gi] += conflicts
            placed = True
            break
        if not placed:
            group_feats.append([f])
            group_mask.append(packed[f].copy())
            group_bins.append(fb)
            group_conflicts.append(0)

    # bundles of one revert to plain singleton storage
    groups = [g for g in group_feats if len(g) > 1]
    single = sorted(f for g in group_feats if len(g) == 1 for f in g)
    non_cand = [f for f in range(F) if not is_bundleable[f]]
    groups.extend([f] for f in sorted(single + non_cand))
    return groups


def build_bundle(sample_nonzero_fn, num_features: int, sample_cnt: int,
                 num_bins: np.ndarray, sparse_rates: np.ndarray,
                 sparse_threshold: float, max_conflict_rate: float
                 ) -> Optional[BundleSpec]:
    """Decide the bundling for a dataset from its binning sample.

    ``sample_nonzero_fn(f)`` returns the [S] bool non-default mask of used
    feature ``f`` on the sample (a callable so sparse inputs materialize
    one column at a time); masks are bit-packed immediately, so peak
    memory is F * S/8 bytes.

    Returns None when bundling would not change the layout (all
    singletons) — the caller then keeps the plain per-feature matrix.
    """
    F, S = num_features, sample_cnt
    if F <= 1 or S <= 0:
        return None
    is_bundleable = np.asarray(sparse_rates) >= sparse_threshold
    if int(is_bundleable.sum()) <= 1:
        return None
    packed = np.zeros((F, (S + 7) // 8), dtype=np.uint8)
    nnz = np.zeros(F, dtype=np.int64)
    for f in range(F):
        if not is_bundleable[f]:
            continue
        mask = np.asarray(sample_nonzero_fn(f), dtype=bool)
        packed[f] = np.packbits(mask)
        nnz[f] = int(mask.sum())
    groups = find_groups(packed, nnz, num_bins, is_bundleable,
                         int(max_conflict_rate * S))
    spec = BundleSpec(groups, num_bins)
    if spec.num_groups == F:
        return None
    return spec


def bundle_dtype(spec: BundleSpec):
    return (np.uint8 if int(spec.group_num_bin.max(initial=1)) <= 256
            else np.uint16)


def quantize_bundled(per_feature_bin_cols, spec: BundleSpec,
                     default_bins: np.ndarray, num_rows: int,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pack per-feature bin columns into the bundled [N, G] uint8/16 matrix.

    ``per_feature_bin_cols(f)`` returns the [num_rows] integer bin column
    of used feature ``f`` (a callable so sparse/chunked inputs materialize
    one column at a time; FeatureGroup::PushData, feature_group.h:131).
    ``out``, when given, is the destination slice (chunked loading writes
    straight into a preallocated matrix).
    """
    dtype = bundle_dtype(spec)
    if out is None:
        out = np.zeros((num_rows, spec.num_groups), dtype=dtype)
    for gi, g in enumerate(spec.groups):
        if len(g) == 1:
            out[:, gi] = per_feature_bin_cols(g[0]).astype(dtype)
            continue
        out[:, gi] = 0
        col = out[:, gi]                  # a view; writes go through
        for f in g:
            bins_f = per_feature_bin_cols(f)
            nz = bins_f != default_bins[f]
            col[nz] = (int(spec.feat_offset[f]) + bins_f[nz]).astype(dtype)
    return out
