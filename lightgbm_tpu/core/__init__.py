from .binning import BinMapper
from .dataset import TpuDataset
from .metadata import Metadata

__all__ = ["BinMapper", "TpuDataset", "Metadata"]
