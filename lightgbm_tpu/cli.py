"""CLI application: train / predict / convert_model / refit.

Reference: src/main.cpp + src/application/application.cpp — LoadParameters
(:48: argv key=value pairs + ``config=`` file), InitTrain (:165: network
init, data load, boosting init), Train (:201: iterate + metric output +
snapshots + final model save), Predict (:212: batch file prediction to
output_result), ConvertModel (if-else C++ codegen), plus the same config
file syntax so the reference's examples/*/train.conf run unchanged.

Run as ``python -m lightgbm_tpu train.conf [key=value ...]`` or
``python -m lightgbm_tpu task=train data=... objective=...``.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from .config import Config, kv2map
from .core.parser import load_file_to_dataset
from .metric import default_metric_for_objective, metric_canonical_name
from .models.boosting_factory import create_boosting
from .objective import create_objective
from .utils.log import LightGBMError, Timer, log_fatal, log_info, log_warning


def load_parameters(argv: List[str]) -> Dict[str, str]:
    """argv key=value pairs + optional config file (application.cpp:48-81)."""
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            if os.path.exists(arg):
                arg = f"config={arg}"
            elif arg.strip().lower() in ("train", "training", "predict",
                                         "prediction", "test",
                                         "convert_model", "refit",
                                         "refit_tree"):
                # subcommand convenience: `... predict data=...` must
                # not silently fall through to the default task=train
                arg = f"task={arg.strip()}"
        kv2map(params, arg)
    config_file = params.get("config", params.get("config_file", ""))
    if config_file:
        file_params: Dict[str, str] = {}
        with open(config_file) as fh:
            for line in fh:
                kv2map(file_params, line)
        # CLI args override config-file values
        for k, v in file_params.items():
            params.setdefault(k, v)
    return params


class Application:
    def __init__(self, argv: List[str]):
        self.params = load_parameters(argv)
        self.config = Config.from_params(self.params)

    def run(self) -> None:
        task = str(self.config.task).strip().lower()
        if task in ("train", "training"):
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task in ("refit", "refit_tree"):
            self.refit()
        else:
            log_fatal(f"Unknown task type {task}")

    # -------------------------------------------------------------- training
    def _load_data(self):
        cfg = self.config
        if not cfg.data:
            log_fatal("No training data, set data=... in config")
        with Timer("load train data", print_on_exit=True):
            train = load_file_to_dataset(cfg.data, cfg)
        valids = []
        names = []
        for i, vf in enumerate(cfg.valid or []):
            with Timer(f"load valid data {vf}", print_on_exit=True):
                valids.append(load_file_to_dataset(str(vf), cfg,
                                                   reference=train))
            names.append(os.path.basename(str(vf)))
        return train, valids, names

    def train(self) -> None:
        cfg = self.config
        train, valids, names = self._load_data()
        if cfg.save_binary:
            train.save_binary(cfg.data + ".bin")
        objective = create_objective(cfg)
        if objective is not None:
            objective.init(train.metadata, train.num_data)
        booster = create_boosting(cfg, train, objective)
        if cfg.input_model:
            from .basic import Booster as PyBooster
            from .models.serialization import load_trees_into
            init = PyBooster(model_file=cfg.input_model)
            load_trees_into(booster, init)
        for name, vset in zip(names, valids):
            booster.add_valid_data(name, vset)
        metric_names = list(cfg.metric)
        if not metric_names:
            d = default_metric_for_objective(cfg.objective)
            metric_names = [d] if d else []
        booster.setup_metrics(metric_names)

        log_info(f"Started training for {cfg.num_iterations} iterations")
        start = time.perf_counter()
        # Chunked stepping (tpu_boost_chunk): the step is clamped so it
        # never crosses a metric/snapshot boundary — chunk-granularity
        # reporting keeps exactly the per-iteration schedule.
        chunk = booster.boost_chunk_size()
        freqs = [f for f in ((cfg.metric_freq if metric_names else 0),
                             cfg.snapshot_freq) if f > 0]
        from .utils.phase import profile_session
        from .utils.telemetry import TELEMETRY
        done = 0
        # profiler window is exception-safe: a mid-training error must
        # not leak an open jax profiler trace session
        with profile_session(), TELEMETRY.memory_session():
            while done < cfg.num_iterations:
                step = min(chunk, cfg.num_iterations - done)
                for f in freqs:
                    step = min(step, f - done % f)
                stop = (booster.train_chunk(step) if step > 1
                        else booster.train_one_iter())
                it = done + step - 1
                done += step
                if (cfg.metric_freq > 0 and (it + 1) % cfg.metric_freq == 0
                        and metric_names):
                    if cfg.is_provide_training_metric:
                        for mname, val, _ in booster.eval_train():
                            log_info(f"Iteration:{it + 1}, training "
                                     f"{mname} : {val:g}")
                    for vi, vname in enumerate(names):
                        for mname, val, _ in booster.eval_valid(vi):
                            log_info(f"Iteration:{it + 1}, valid_{vi + 1} "
                                     f"{mname} : {val:g}")
                if (cfg.snapshot_freq > 0
                        and (it + 1) % cfg.snapshot_freq == 0):
                    snap = f"{cfg.output_model}.snapshot_iter_{it + 1}"
                    self._save_model(booster, snap)
                    log_info(f"Saved snapshot to {snap}")
                if stop:
                    break
                log_info(f"{time.perf_counter() - start:.6f} seconds "
                         f"elapsed, finished iteration {it + 1}")
        self._save_model(booster, cfg.output_model)
        if cfg.metrics_out:
            import json
            with open(cfg.metrics_out, "w") as fh:
                json.dump(TELEMETRY.metrics_blob(), fh, indent=1)
            log_info(f"Wrote training metrics to {cfg.metrics_out}")
        TELEMETRY.maybe_export_trace()
        log_info(f"Finished training, saved model to {cfg.output_model}")

    def _save_model(self, booster, filename: str) -> None:
        from .models.serialization import save_model_to_string
        with open(filename, "w") as fh:
            fh.write(save_model_to_string(booster, self.config))

    # ------------------------------------------------------------ prediction
    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        from .basic import Booster as PyBooster
        booster = PyBooster(model_file=cfg.input_model)
        X, _ = self._load_predict_matrix(booster)
        result = booster.predict(
            X, num_iteration=cfg.num_iteration_predict,
            raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib)
        result = np.asarray(result)
        with open(cfg.output_result, "w") as fh:
            for row in result.reshape(result.shape[0], -1):
                fh.write("\t".join(f"{v:g}" for v in row) + "\n")
        log_info(f"Finished prediction, wrote results to {cfg.output_result}")

    def _load_predict_matrix(self, booster):
        cfg = self.config
        from .core.parser import parse_file_to_matrix
        return parse_file_to_matrix(
            cfg.data, bool(cfg.header), booster.gbdt.max_feature_idx + 1,
            label_column=cfg.label_column)

    # ---------------------------------------------------------- model convert
    def convert_model(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        if cfg.convert_model_language not in ("", "cpp"):
            log_fatal("Only cpp is supported as convert_model_language")
        from .basic import Booster as PyBooster
        from .models.convert import model_to_if_else
        booster = PyBooster(model_file=cfg.input_model)
        code = model_to_if_else(booster.gbdt)
        with open(cfg.convert_model, "w") as fh:
            fh.write(code)
        log_info(f"Converted model to if-else code at {cfg.convert_model}")

    # ------------------------------------------------------------------ refit
    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        from .basic import Booster as PyBooster
        booster = PyBooster(model_file=cfg.input_model)
        X, label = self._load_predict_matrix(booster)
        if label is None:
            log_fatal("Refit requires labeled data; the data file has no "
                      "label column")
        leaf_preds = booster.predict(X, pred_leaf=True)
        from .core.metadata import Metadata
        from .models.refit import refit_model
        meta = Metadata(len(label))
        meta.set_label(np.asarray(label))
        refit_model(booster.gbdt, meta, np.asarray(leaf_preds), cfg)
        self._save_model(booster.gbdt, cfg.output_model)
        log_info(f"Finished refit, saved model to {cfg.output_model}")


def main(argv: Optional[List[str]] = None) -> None:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m lightgbm_tpu <config-file|key=value> ...")
        sys.exit(1)
    Application(argv).run()


if __name__ == "__main__":
    main()
