"""CLI application: train / predict / convert_model / refit.

Reference: src/main.cpp + src/application/application.cpp — LoadParameters
(:48: argv key=value pairs + ``config=`` file), InitTrain (:165: network
init, data load, boosting init), Train (:201: iterate + metric output +
snapshots + final model save), Predict (:212: batch file prediction to
output_result), ConvertModel (if-else C++ codegen), plus the same config
file syntax so the reference's examples/*/train.conf run unchanged.

Run as ``python -m lightgbm_tpu train.conf [key=value ...]`` or
``python -m lightgbm_tpu task=train data=... objective=...``.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from .config import Config, kv2map
from .core.parser import load_file_to_dataset
from .metric import default_metric_for_objective, metric_canonical_name
from .models.boosting_factory import create_boosting
from .objective import create_objective
from .utils.log import LightGBMError, Timer, log_fatal, log_info, log_warning


def load_parameters(argv: List[str]) -> Dict[str, str]:
    """argv key=value pairs + optional config file (application.cpp:48-81)."""
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            if os.path.exists(arg):
                arg = f"config={arg}"
            elif arg.strip().lower() in ("train", "training", "predict",
                                         "prediction", "test",
                                         "convert_model", "refit",
                                         "refit_tree", "sched"):
                # subcommand convenience: `... predict data=...` must
                # not silently fall through to the default task=train
                arg = f"task={arg.strip()}"
        kv2map(params, arg)
    config_file = params.get("config", params.get("config_file", ""))
    if config_file:
        file_params: Dict[str, str] = {}
        with open(config_file) as fh:
            for line in fh:
                kv2map(file_params, line)
        # CLI args override config-file values
        for k, v in file_params.items():
            params.setdefault(k, v)
    return params


class Application:
    def __init__(self, argv: List[str]):
        self.params = load_parameters(argv)
        self.config = Config.from_params(self.params)
        # arm fault injection for the whole run (env wins over config);
        # re-armed with counters reset when a boosting object binds the
        # same config, so per-iteration specs replay deterministically
        from .utils.faults import FAULTS
        FAULTS.configure(getattr(self.config, "fault_injection", ""))

    def run(self) -> None:
        task = str(self.config.task).strip().lower()
        if task == "sched" or (task in ("train", "training")
                               and str(self.config.sched).strip()):
            self.sched()
        elif task in ("train", "training"):
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task in ("refit", "refit_tree"):
            self.refit()
        else:
            log_fatal(f"Unknown task type {task}")

    # -------------------------------------------------------------- training
    def _load_data(self):
        cfg = self.config
        if not cfg.data:
            log_fatal("No training data, set data=... in config")
        with Timer("load train data", print_on_exit=True):
            train = load_file_to_dataset(cfg.data, cfg)
        valids = []
        names = []
        for i, vf in enumerate(cfg.valid or []):
            with Timer(f"load valid data {vf}", print_on_exit=True):
                valids.append(load_file_to_dataset(str(vf), cfg,
                                                   reference=train))
            names.append(os.path.basename(str(vf)))
        return train, valids, names

    def train(self) -> None:
        cfg = self.config
        # compile_cache= knob: persistent XLA compilation cache, enabled
        # before the first traced computation so every compile of this
        # run can hit (or seed) the on-disk cache
        from .utils import maybe_enable_compile_cache
        maybe_enable_compile_cache(cfg)
        # multi-host lifecycle: bind the collective retry policy and
        # bring the jax.distributed world up (config/env driven) BEFORE
        # data loading — sharded ingest bins against bin bounds synced
        # via allgather_obj, which needs the world
        from .parallel import distributed, network
        network.configure(cfg)
        distributed.maybe_initialize(cfg)
        dist_active = distributed.is_active()
        train, valids, names = self._load_data()
        if cfg.save_binary:
            train.save_binary(cfg.data + ".bin")
        objective = create_objective(cfg)
        if objective is not None:
            objective.init(train.metadata, train.num_data)
        booster = create_boosting(cfg, train, objective)
        resume_snap = None
        if cfg.resume:
            # multi-host: elect the newest snapshot iteration ALL hosts
            # possess (allgather of local manifests) so every host rolls
            # to the same point; single-host falls through to plain
            # local discovery inside elect_snapshot
            resume_snap, _ = distributed.elect_snapshot(cfg.output_model)
            if resume_snap is None:
                log_warning("resume=true but no resumable snapshot next to "
                            f"{cfg.output_model}; starting from scratch")
        if cfg.input_model and resume_snap is None:
            from .basic import Booster as PyBooster
            from .models.serialization import load_trees_into
            init = PyBooster(model_file=cfg.input_model)
            load_trees_into(booster, init)
        for name, vset in zip(names, valids):
            booster.add_valid_data(name, vset)
        metric_names = list(cfg.metric)
        if not metric_names:
            d = default_metric_for_objective(cfg.objective)
            metric_names = [d] if d else []
        booster.setup_metrics(metric_names)
        done = 0
        if resume_snap is not None:
            done = self._resume(booster, resume_snap)
            if dist_active:
                # resume boundary: a host that failed to roll to the
                # elected snapshot must surface as a named missing rank
                # here, not as a divergent model later
                distributed.barrier("resume")

        from .utils.telemetry import HEALTH
        # streaming run-health layer: resume compacts the existing
        # stream past the snapshot iteration and keeps appending, so a
        # killed+resumed run yields ONE contiguous stream
        health_path = HEALTH.resolve_path(cfg)
        if health_path:
            meta = {"source": "cli", "stream": "train",
                    "num_iterations": int(cfg.num_iterations)}
            if dist_active:
                meta["rank"] = distributed.rank()
                meta["world"] = distributed.world()
            HEALTH.open(
                health_path,
                resume_iter=done if resume_snap is not None else None,
                meta=meta)

        # fleet observability plane (obs/, metrics v6): measure the
        # clock-offset table here — the one aligned point where the
        # blocking ping/pong collective cannot interleave with any
        # other — then post/collect attribution windows at iteration
        # boundaries (never blocking) and once, blocking, at summary
        from .obs import fleet as fleet_obs
        fleet_obs.start(cfg)

        log_info(f"Started training for {cfg.num_iterations} iterations")
        start = time.perf_counter()
        from .utils.faults import FAULTS
        from .utils.phase import PROFILE_WINDOW, profile_session
        from .utils.telemetry import TELEMETRY
        # Chunked stepping (tpu_boost_chunk): when the attached metrics
        # are device-computable, the in-scan eval path evaluates them
        # inside the chunk scan at unchanged per-iteration cadence; a
        # host-only metric falls back to per-iteration stepping (blocker
        # named in the boost/inscan_blocked[...] gauge).  Off the in-scan
        # path the step is clamped so it never crosses a metric boundary
        # — chunk-granularity reporting keeps exactly the per-iteration
        # schedule.  Snapshot boundaries always clamp.
        chunk = booster.boost_chunk_size()
        use_inscan = False
        has_eval = bool(metric_names) and cfg.metric_freq > 0 and (
            bool(names) or cfg.is_provide_training_metric)
        explicit = int(cfg.tpu_boost_chunk) != 0
        if has_eval and (chunk > 1 or explicit):
            blocker = booster.setup_inscan_eval(
                cfg.is_provide_training_metric)
            if blocker is None:
                use_inscan = True
            else:
                TELEMETRY.gauge_set(f"boost/inscan_blocked[{blocker}]", 1)
                chunk = 1
        # the reference CLI reports valid sets positionally
        vlabel = {"training": "training"}
        for _vi, _vname in enumerate(names):
            vlabel[_vname] = f"valid_{_vi + 1}"
        freqs = [f for f in (
            (cfg.metric_freq if metric_names and not use_inscan else 0),
            cfg.snapshot_freq) if f > 0]
        # a preempted job (SIGTERM from the scheduler, ctrl-C) must still
        # report: raise SystemExit so the salvage/metrics/trace/health
        # flushes in the finally below run before the process dies.
        # Signal handlers only bind in the main thread; elsewhere the
        # default disposition stays (the finally still runs on exceptions)
        import signal as _signal

        def _graceful_stop(signum, frame):
            # multi-host: the first SIGTERM is a preemption notice —
            # note it and let the loop drain the whole fleet to one
            # synchronized snapshot (a second signal force-exits);
            # single-host keeps the direct salvage-and-exit path
            if dist_active and distributed.local_preemption() is None:
                distributed.note_local_preemption(f"signal {signum}")
                return
            raise SystemExit(128 + signum)

        prev_handlers = {}
        for _sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                prev_handlers[_sig] = _signal.signal(_sig, _graceful_stop)
            except (ValueError, OSError):
                pass
        failed = False
        preempted = False
        preempt_target = None
        try:
            # profiler window is exception-safe: a mid-training error must
            # not leak an open jax profiler trace session
            with profile_session(cfg), TELEMETRY.memory_session():
                while done < cfg.num_iterations:
                    step = min(chunk, cfg.num_iterations - done)
                    for f in freqs:
                        step = min(step, f - done % f)
                    if preempt_target is not None:
                        # draining: stop exactly at the fleet-agreed
                        # iteration, never past it
                        step = min(step, preempt_target - done)
                    # a profile_window boundary splits the chunk so the
                    # capture covers exactly the requested span
                    step = PROFILE_WINDOW.clamp_step(done, step)
                    PROFILE_WINDOW.step(done)
                    stop = (booster.train_chunk(step)
                            if (step > 1 or use_inscan)
                            else booster.train_one_iter())
                    it = done + step - 1
                    done += step
                    if use_inscan:
                        # replay the chunk's per-iteration metric rows at
                        # the metric_freq cadence points
                        for j, vals in booster.take_inscan_evals():
                            if (j + 1) % cfg.metric_freq != 0:
                                continue
                            eval_rec = {}
                            for sname, mname, val, _hb in (
                                    booster.inscan_result_list(vals)):
                                label = vlabel.get(sname, sname)
                                log_info(f"Iteration:{j + 1}, {label} "
                                         f"{mname} : {val:g}")
                                eval_rec[f"{label}/{mname}"] = float(val)
                            if eval_rec and HEALTH.active:
                                HEALTH.record("eval", {"iter": int(j),
                                                       "in_scan": True,
                                                       "metrics": eval_rec})
                    elif (cfg.metric_freq > 0
                            and (it + 1) % cfg.metric_freq == 0
                            and metric_names):
                        eval_rec = {}
                        if cfg.is_provide_training_metric:
                            for mname, val, _ in booster.eval_train():
                                log_info(f"Iteration:{it + 1}, training "
                                         f"{mname} : {val:g}")
                                eval_rec[f"training/{mname}"] = float(val)
                        for vi, vname in enumerate(names):
                            for mname, val, _ in booster.eval_valid(vi):
                                log_info(f"Iteration:{it + 1}, "
                                         f"valid_{vi + 1} "
                                         f"{mname} : {val:g}")
                                eval_rec[f"valid_{vi + 1}/{mname}"] = \
                                    float(val)
                        if eval_rec and HEALTH.active:
                            HEALTH.record("eval", {"iter": int(it),
                                                   "in_scan": False,
                                                   "metrics": eval_rec})
                    if (cfg.snapshot_freq > 0
                            and (it + 1) % cfg.snapshot_freq == 0):
                        self._write_snapshot(booster, it + 1)
                    FAULTS.maybe_raise("train/kill", n=it)
                    if dist_active:
                        # fleet plane window post/collect — non-blocking
                        # by contract, so it cannot race the preemption
                        # negotiate below
                        fleet_obs.maybe_sync(done)
                    if dist_active and preempt_target is None:
                        # deterministic preemption injection: the
                        # dist/preempt site stands in for a scheduler
                        # SIGTERM on this host
                        if FAULTS.check("dist/preempt", n=it):
                            distributed.note_local_preemption(
                                "injected dist/preempt")
                        notice = distributed.preempt_notice()
                        if notice is not None:
                            # rebroadcast (idempotent) so every host
                            # sees the notice, then agree on the drain
                            # target: the max progress across the fleet
                            distributed.publish_preempt(
                                str(notice.get("reason", "preempt")),
                                done)
                            preempt_target = (
                                distributed.negotiate_preempt_target(
                                    done))
                            log_warning(
                                f"preemption notice ({notice}); "
                                "draining the fleet to iteration "
                                f"{preempt_target}")
                    if preempt_target is not None \
                            and done >= preempt_target:
                        # every host is at the agreed iteration: meet,
                        # snapshot synchronously, leave cleanly
                        distributed.barrier("preempt")
                        self._write_snapshot(booster, done)
                        preempted = True
                        break
                    if stop:
                        break
                    log_info(f"{time.perf_counter() - start:.6f} seconds "
                             f"elapsed, finished iteration {it + 1}")
                if dist_active and not preempted:
                    # summary sync: post the final attribution window
                    # and collect everything pending.  Blocking is safe
                    # (and bounded) only here: every rank reaches this
                    # aligned point on the normal-completion path
                    fleet_obs.final_sync(done)
        except BaseException:
            failed = True
            raise
        finally:
            # the run's observability and completed work survive a crash:
            # salvage the trees that finished, then always flush the
            # metrics blob and the Chrome trace
            if failed:
                self._salvage_partial(booster)
            # close the stream first (writing its summary record) so the
            # metrics blob's health digest covers the whole run; settle
            # the async tree pipeline so the last iterations' records
            # land before the summary (best-effort on the crash path)
            if health_path:
                try:
                    booster.models
                except Exception:
                    pass
                HEALTH.close(aborted=failed)
            if cfg.metrics_out:
                import json
                try:
                    with open(cfg.metrics_out, "w") as fh:
                        json.dump(TELEMETRY.metrics_blob(), fh, indent=1)
                    log_info(f"Wrote training metrics to {cfg.metrics_out}")
                except OSError as e:
                    log_warning(f"could not write {cfg.metrics_out}: {e}")
            TELEMETRY.maybe_export_trace()
            for _sig, _prev in prev_handlers.items():
                try:
                    _signal.signal(_sig, _prev)
                except (ValueError, OSError):
                    pass
        if preempted:
            # the whole fleet checkpointed at the same iteration; exit
            # with the "try again later" code so the scheduler restarts
            # the job, which resumes from the elected snapshot
            log_warning(
                f"preempted at iteration {done}: synchronized snapshot "
                f"written; exiting {distributed.PREEMPT_EXIT_CODE} for "
                "restart with resume=true")
            raise SystemExit(distributed.PREEMPT_EXIT_CODE)
        self._save_model(booster, cfg.output_model)
        log_info(f"Finished training, saved model to {cfg.output_model}")

    # ------------------------------------------------------------ scheduling
    def sched(self) -> None:
        """task=sched / sched=SPEC: run the spec file's jobs through
        the multi-tenant scheduler (docs/SCHEDULING.md).  CLI key=value
        arguments override the spec's scheduler knobs."""
        spec_path = str(self.config.sched).strip()
        if not spec_path:
            log_fatal("No job spec, set sched=jobs.spec for task=sched")
        from .sched import run_spec_file
        overrides = {k: v for k, v in self.params.items()
                     if k not in ("config", "config_file", "task",
                                  "sched")}
        summary = run_spec_file(spec_path, overrides=overrides)
        log_info(
            f"Scheduler finished: {summary['done']} job(s) done, "
            f"{summary['failed']} failed, {summary['slices']} slice(s), "
            f"policy={summary['policy']}, "
            f"cross_job_cache_hits={summary['cross_job_cache_hits']}")
        if summary["failed"] or summary.get("rejected"):
            raise SystemExit(1)

    def _resume(self, booster, snapshot_file: str) -> int:
        """Load the newest snapshot's trees + exact sidecar state; the
        run continues from iteration N with the same key stream, scores
        and bagging masks as if it had never stopped."""
        from .basic import Booster as PyBooster
        from .models.serialization import load_trees_into
        from .utils.snapshots import restore_snapshot_state
        from .utils.telemetry import TELEMETRY
        init = PyBooster(model_file=snapshot_file)
        load_trees_into(booster, init)
        it = restore_snapshot_state(booster, snapshot_file)
        TELEMETRY.fault_event("resume", site="snapshot/io", iteration=it,
                              detail=os.path.basename(snapshot_file))
        log_info(f"Resumed training from {snapshot_file} (iteration {it})")
        return it

    def _write_snapshot(self, booster, iteration: int) -> None:
        """save_period snapshot + exact-state sidecar.  An IO failure
        here is survivable: logged and counted, training continues —
        losing one snapshot must not abort a long run."""
        cfg = self.config
        from .models.serialization import save_model_to_string
        from .parallel import distributed
        from .utils.snapshots import prune_snapshots, save_snapshot
        from .utils.telemetry import TELEMETRY
        snap = f"{cfg.output_model}.snapshot_iter_{iteration}"
        # snapshot boundary: all hosts reach the same iteration before
        # any writes — a dead host trips the timeout naming its rank
        # instead of leaving a half-fleet snapshot generation
        distributed.barrier("snapshot")
        try:
            # save_snapshot retries transient IO once (shared policy in
            # utils/retry.py) and probes the snapshot/io fault site per
            # attempt; only a persistent failure reaches this except
            save_snapshot(booster, snap,
                          save_model_to_string(booster, self.config))
            prune_snapshots(cfg.output_model, int(cfg.snapshot_keep))
        except OSError as e:
            log_warning(f"snapshot write at iteration {iteration} failed "
                        f"({e}); training continues without it")
            TELEMETRY.fault_event("snapshot_io", site="snapshot/io",
                                  iteration=iteration, detail=str(e))
            return
        log_info(f"Saved snapshot to {snap}")

    def _salvage_partial(self, booster) -> None:
        """Crash path: keep whatever trees completed before the failure
        so a run that dies at iteration 900/1000 does not cost the whole
        model.  Best-effort — the original exception stays primary."""
        partial = f"{self.config.output_model}.partial"
        try:
            self._save_model(booster, partial)
        except Exception as e:
            log_warning(f"could not salvage partial model: {e}")
            return
        from .utils.telemetry import TELEMETRY
        done = int(booster.current_iteration())
        TELEMETRY.fault_event("partial_save", iteration=done,
                              detail=os.path.basename(partial))
        log_warning(f"training aborted; salvaged {done}-iteration partial "
                    f"model to {partial}")

    def _save_model(self, booster, filename: str) -> None:
        from .models.serialization import save_model_to_string
        from .utils.file_io import atomic_write_text
        atomic_write_text(filename,
                          save_model_to_string(booster, self.config))

    # ------------------------------------------------------------ prediction
    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        from .basic import Booster as PyBooster
        booster = PyBooster(model_file=cfg.input_model)
        X, _ = self._load_predict_matrix(booster)
        result = booster.predict(
            X, num_iteration=cfg.num_iteration_predict,
            raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib)
        result = np.asarray(result)
        with open(cfg.output_result, "w") as fh:
            for row in result.reshape(result.shape[0], -1):
                fh.write("\t".join(f"{v:g}" for v in row) + "\n")
        log_info(f"Finished prediction, wrote results to {cfg.output_result}")

    def _load_predict_matrix(self, booster):
        cfg = self.config
        from .core.parser import parse_file_to_matrix
        return parse_file_to_matrix(
            cfg.data, bool(cfg.header), booster.gbdt.max_feature_idx + 1,
            label_column=cfg.label_column)

    # ---------------------------------------------------------- model convert
    def convert_model(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        if cfg.convert_model_language not in ("", "cpp"):
            log_fatal("Only cpp is supported as convert_model_language")
        from .basic import Booster as PyBooster
        from .models.convert import model_to_if_else
        booster = PyBooster(model_file=cfg.input_model)
        code = model_to_if_else(booster.gbdt)
        with open(cfg.convert_model, "w") as fh:
            fh.write(code)
        log_info(f"Converted model to if-else code at {cfg.convert_model}")

    # ------------------------------------------------------------------ refit
    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log_fatal("No model file, set input_model=...")
        from .basic import Booster as PyBooster
        booster = PyBooster(model_file=cfg.input_model)
        X, label = self._load_predict_matrix(booster)
        if label is None:
            log_fatal("Refit requires labeled data; the data file has no "
                      "label column")
        leaf_preds = booster.predict(X, pred_leaf=True)
        from .core.metadata import Metadata
        from .models.refit import refit_model
        meta = Metadata(len(label))
        meta.set_label(np.asarray(label))
        refit_model(booster.gbdt, meta, np.asarray(leaf_preds), cfg)
        self._save_model(booster.gbdt, cfg.output_model)
        log_info(f"Finished refit, saved model to {cfg.output_model}")


def main(argv: Optional[List[str]] = None) -> None:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m lightgbm_tpu <config-file|key=value> ...")
        sys.exit(1)
    Application(argv).run()


if __name__ == "__main__":
    main()
