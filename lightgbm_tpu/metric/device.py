"""Device-side (jittable) metric kernels for in-scan evaluation.

The chunked boosting loop (models/gbdt.py ``train_chunk``) can carry the
valid-set score vectors through its lax.scan and evaluate the attached
built-in metrics per iteration ON DEVICE, returning a ``[T, n_cols]``
array that rides the existing async chunk fetch.  This module builds
that evaluation program from the host-side metric objects produced by
``GBDT.setup_metrics`` — same formulas as metric/__init__.py, expressed
in jnp over the device score buffers.

Numerics: the kernels run in f32 (the training dtype).  Probability
clipping uses 1e-7 instead of the host metrics' 1e-15 because
``1 - 1e-15`` rounds to exactly 1.0 in f32 and ``log(1 - p)`` would hit
log(0).  In-scan values are therefore bit-identical across chunk sizes
(same program, same state upload points) but only approximately equal
to the host f64 per-iteration path.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# f32-safe probability clip (host metrics use 1e-15 in f64; see module doc)
_EPS = 1e-7

# metric canonical names with a device kernel below; everything else
# (map, cross_entropy_lambda, custom fevals) blocks in-scan evaluation
DEVICE_METRICS = frozenset({
    "l2", "rmse", "l1", "quantile", "huber", "fair", "poisson", "mape",
    "gamma", "gamma_deviance", "tweedie", "binary_logloss", "binary_error",
    "auc", "multi_logloss", "multi_error", "cross_entropy",
    "kullback_leibler", "ndcg",
})


class DeviceEval(NamedTuple):
    """A compiled-in evaluation program for the chunk scan body.

    ``eval_fn(train_score, vscores, arrays) -> [n_cols] f32`` is pure jnp
    (traceable inside the scan); ``arrays`` is the per-set device-array
    pytree passed as a jit argument (labels/weights/rank tables embedded
    as constants would bloat the program by O(N) bytes).  ``columns``
    maps the output vector to (set_name, metric_name, higher_better)
    rows in the legacy eval order: "training" first when requested, then
    the valid sets in attachment order."""
    columns: Tuple[Tuple[str, str, bool], ...]
    eval_fn: Callable
    arrays: Tuple[dict, ...]
    vbins: Tuple[jax.Array, ...]


def _link_for(objective) -> Optional[Callable]:
    """Device-side equivalent of ``objective.convert_output`` (np-based,
    unusable under jit) applied to a [C, N] raw score; None when the
    objective's link has no kernel here."""
    name = getattr(objective, "name", "")
    if name == "regression":
        if getattr(objective, "sqrt", False):
            return lambda s: jnp.sign(s) * s * s
        return lambda s: s
    if name in ("regression_l1", "huber", "fair", "quantile", "mape",
                "lambdarank"):
        return lambda s: s
    if name in ("binary", "multiclassova"):
        sig = float(objective.sigmoid)
        return lambda s: 1.0 / (1.0 + jnp.exp(-sig * s))
    if name == "multiclass":
        def softmax(s):
            e = jnp.exp(s - jnp.max(s, axis=0, keepdims=True))
            return e / jnp.sum(e, axis=0, keepdims=True)
        return softmax
    if name == "cross_entropy":
        return lambda s: 1.0 / (1.0 + jnp.exp(-s))
    if name == "cross_entropy_lambda":
        return lambda s: jnp.log1p(jnp.exp(s))
    if name in ("poisson", "gamma", "tweedie"):
        return jnp.exp
    return None


class _Blocked(Exception):
    def __init__(self, what: str):
        super().__init__(what)
        self.what = what


def _build_ndcg_tables(m) -> Tuple[dict, list]:
    """Host-precomputed rank tables for one NDCG metric: padded [Q, P]
    doc-index/mask/gain tables plus per-(query, k) 1/maxDCG (label-only,
    so computable once up front) and the position discounts."""
    b = np.asarray(m.boundaries, dtype=np.int64)
    Q = len(b) - 1
    ks = [int(k) for k in m.eval_at]
    P = int(max((b[1:] - b[:-1]).max(), 1)) if Q > 0 else 1
    idx = np.zeros((Q, P), dtype=np.int32)
    mask = np.zeros((Q, P), dtype=bool)
    gains = np.zeros((Q, P), dtype=np.float32)
    inv_max = np.zeros((Q, len(ks)), dtype=np.float32)
    perfect = np.zeros((Q, len(ks)), dtype=bool)
    lg = m.calc.label_gain
    for q in range(Q):
        s, e = int(b[q]), int(b[q + 1])
        L = e - s
        idx[q, :L] = np.arange(s, e)
        mask[q, :L] = True
        gains[q, :L] = lg[m.label[s:e].astype(np.int64)]
        for i, k in enumerate(ks):
            md = m.calc.cal_maxdcg_at_k(k, m.label[s:e])
            if md <= 0:
                perfect[q, i] = True       # no relevant docs = perfect
            else:
                inv_max[q, i] = 1.0 / md
    qw = (np.asarray(m.query_weights, dtype=np.float32)
          if m.query_weights is not None
          else np.ones(Q, dtype=np.float32))
    # [K, P] masked discounts: discount(pos) for pos < k, else 0
    disc = np.zeros((len(ks), P), dtype=np.float32)
    pos = np.arange(P)
    for i, k in enumerate(ks):
        disc[i] = np.where(pos < k, 1.0 / np.log2(2.0 + pos), 0.0)
    arrays = {
        "ndcg_idx": jnp.asarray(idx), "ndcg_mask": jnp.asarray(mask),
        "ndcg_gain": jnp.asarray(gains), "ndcg_inv": jnp.asarray(inv_max),
        "ndcg_perfect": jnp.asarray(perfect), "ndcg_qw": jnp.asarray(qw),
        "ndcg_disc": jnp.asarray(disc),
    }
    return arrays, ks


def _build_set_program(metrics, metadata, num_data: int, objective):
    """One eval set -> (columns, arrays dict, set_fn(raw [C, N], A))."""
    N = int(num_data)
    w_np = metadata.weights
    has_w = w_np is not None
    sum_w = float(np.sum(w_np)) if has_w else float(N)
    arrays = {"label": jnp.asarray(np.asarray(metadata.label,
                                              dtype=np.float32))}
    if has_w:
        arrays["w"] = jnp.asarray(np.asarray(w_np, dtype=np.float32))

    def avg(x, A):
        if has_w:
            return jnp.sum(x * A["w"]) / sum_w
        return jnp.mean(x)

    columns: List[Tuple[str, bool]] = []
    fns = []          # each: (p, raw, A) -> [k] f32

    def scalar(fn):
        return lambda p, raw, A: jnp.reshape(fn(p, raw, A), (1,))

    for m in metrics:
        name = m.name
        if name not in DEVICE_METRICS:
            raise _Blocked(name)
        cfg = m.config
        if name == "l2":
            fns.append(scalar(lambda p, raw, A: avg(
                (A["label"] - p[0]) ** 2, A)))
        elif name == "rmse":
            fns.append(scalar(lambda p, raw, A: jnp.sqrt(avg(
                (A["label"] - p[0]) ** 2, A))))
        elif name == "l1":
            fns.append(scalar(lambda p, raw, A: avg(
                jnp.abs(A["label"] - p[0]), A)))
        elif name == "quantile":
            a = float(cfg.alpha)
            def q_fn(p, raw, A, a=a):
                d = A["label"] - p[0]
                return avg(jnp.where(d >= 0, a * d, (a - 1.0) * d), A)
            fns.append(scalar(q_fn))
        elif name == "huber":
            a = float(cfg.alpha)
            def h_fn(p, raw, A, a=a):
                d = jnp.abs(A["label"] - p[0])
                return avg(jnp.where(d <= a, 0.5 * d * d,
                                     a * (d - 0.5 * a)), A)
            fns.append(scalar(h_fn))
        elif name == "fair":
            c = float(cfg.fair_c)
            def f_fn(p, raw, A, c=c):
                x = jnp.abs(A["label"] - p[0])
                return avg(c * c * (x / c - jnp.log1p(x / c)), A)
            fns.append(scalar(f_fn))
        elif name == "poisson":
            def po_fn(p, raw, A):
                pm = jnp.maximum(p[0], 1e-15)
                return avg(pm - A["label"] * jnp.log(pm), A)
            fns.append(scalar(po_fn))
        elif name == "mape":
            fns.append(scalar(lambda p, raw, A: avg(
                jnp.abs(A["label"] - p[0])
                / jnp.maximum(1.0, jnp.abs(A["label"])), A)))
        elif name == "gamma":
            def g_fn(p, raw, A):
                pm = jnp.maximum(p[0], 1e-15)
                x = A["label"] / pm
                return avg(x + jnp.log(pm)
                           - jnp.log(jnp.maximum(A["label"], 1e-15)), A)
            fns.append(scalar(g_fn))
        elif name == "gamma_deviance":
            def gd_fn(p, raw, A):
                pm = jnp.maximum(p[0], 1e-15)
                x = A["label"] / pm
                return avg(2.0 * (jnp.log(jnp.maximum(
                    1.0 / jnp.maximum(x, 1e-15), 1e-15)) + x - 1.0), A)
            fns.append(scalar(gd_fn))
        elif name == "tweedie":
            rho = float(cfg.tweedie_variance_power)
            def tw_fn(p, raw, A, rho=rho):
                pm = jnp.maximum(p[0], 1e-15)
                a = A["label"] * jnp.power(pm, 1.0 - rho) / (1.0 - rho)
                b = jnp.power(pm, 2.0 - rho) / (2.0 - rho)
                return avg(-a + b, A)
            fns.append(scalar(tw_fn))
        elif name in ("binary_logloss", "cross_entropy"):
            def bl_fn(p, raw, A):
                pc = jnp.clip(p[0], _EPS, 1.0 - _EPS)
                y = (A["label"] > 0).astype(jnp.float32)
                return avg(-(y * jnp.log(pc)
                             + (1.0 - y) * jnp.log(1.0 - pc)), A)
            fns.append(scalar(bl_fn))
        elif name == "binary_error":
            def be_fn(p, raw, A):
                pred = (p[0] > 0.5)
                y = (A["label"] > 0)
                return avg((pred != y).astype(jnp.float32), A)
            fns.append(scalar(be_fn))
        elif name == "kullback_leibler":
            def kl_fn(p, raw, A):
                pc = jnp.clip(p[0], _EPS, 1.0 - _EPS)
                y = jnp.clip(A["label"], _EPS, 1.0 - _EPS)
                return avg(y * jnp.log(y / pc)
                           + (1.0 - y) * jnp.log((1.0 - y) / (1.0 - pc)),
                           A)
            fns.append(scalar(kl_fn))
        elif name == "auc":
            def auc_fn(p, raw, A):
                # weighted rank-sum AUC with half credit inside tied-score
                # groups (metric/__init__.py AUCMetric, via segment_sum
                # over cumsum-derived group ids instead of np.reduceat)
                s = raw[0]
                order = jnp.argsort(s, stable=True)
                ss = s[order]
                ys = A["label"][order] > 0
                ws = (A["w"][order] if has_w
                      else jnp.ones_like(ss))
                pos_w = jnp.sum(ws * ys)
                neg_w = jnp.sum(ws * ~ys)
                new_grp = jnp.concatenate(
                    [jnp.zeros(1, dtype=jnp.int32),
                     (ss[1:] != ss[:-1]).astype(jnp.int32)])
                gid = jnp.cumsum(new_grp)
                grp_neg = jax.ops.segment_sum(
                    ws * ~ys, gid, num_segments=N,
                    indices_are_sorted=True)
                cum_before = jnp.cumsum(grp_neg) - grp_neg
                auc_sum = jnp.sum((cum_before[gid]
                                   + 0.5 * grp_neg[gid]) * ws * ys)
                ok = (pos_w > 0) & (neg_w > 0)
                return jnp.where(
                    ok, auc_sum / jnp.maximum(pos_w * neg_w, 1e-20), 1.0)
            fns.append(scalar(auc_fn))
        elif name == "multi_logloss":
            arrays.setdefault("label_i", jnp.asarray(
                np.asarray(metadata.label, dtype=np.int32)))
            def ml_fn(p, raw, A):
                pc = jnp.clip(p, _EPS, 1.0 - _EPS)
                picked = jnp.take_along_axis(
                    pc, A["label_i"][None, :], axis=0)[0]
                return avg(-jnp.log(picked), A)
            fns.append(scalar(ml_fn))
        elif name == "multi_error":
            arrays.setdefault("label_i", jnp.asarray(
                np.asarray(metadata.label, dtype=np.int32)))
            k = max(1, int(cfg.multi_error_top_k))
            def me_fn(p, raw, A, k=k):
                lab = A["label_i"]
                if k == 1:
                    err = (jnp.argmax(raw, axis=0).astype(jnp.int32)
                           != lab)
                else:
                    target = jnp.take_along_axis(
                        raw, lab[None, :], axis=0)[0]
                    rank = jnp.sum(raw > target[None, :], axis=0)
                    err = rank >= k
                return avg(err.astype(jnp.float32), A)
            fns.append(scalar(me_fn))
        elif name == "ndcg":
            nd_arrays, ks = _build_ndcg_tables(m)
            arrays.update(nd_arrays)
            sum_qw = float(np.asarray(nd_arrays["ndcg_qw"]).sum())
            def nd_fn(p, raw, A, sum_qw=sum_qw):
                s = raw[0]
                sq = jnp.where(A["ndcg_mask"], s[A["ndcg_idx"]],
                               -jnp.inf)                       # [Q, P]
                order = jnp.argsort(-sq, axis=1, stable=True)
                g_sorted = jnp.take_along_axis(A["ndcg_gain"], order,
                                               axis=1)
                dcg = jnp.einsum("qp,kp->kq", g_sorted,
                                 A["ndcg_disc"])               # [K, Q]
                nd = jnp.where(A["ndcg_perfect"].T, 1.0,
                               dcg * A["ndcg_inv"].T)
                return (jnp.sum(nd * A["ndcg_qw"][None, :], axis=1)
                        / max(sum_qw, 1e-20))
            fns.append(nd_fn)
            for k in ks:
                columns.append((f"{m.name}@{k}", m.higher_better))
            continue
        columns.append((name, m.higher_better))

    link = _link_for(objective)

    def set_fn(raw, A):
        p = link(raw)
        return jnp.concatenate([fn(p, raw, A) for fn in fns])

    return columns, arrays, set_fn


def build_device_eval(gbdt, include_train: bool):
    """Build the in-scan evaluation program for a GBDT with metrics set
    up.  Returns ``(DeviceEval, None)`` or ``(None, blocker)`` where the
    blocker string names the first non-device-computable piece (metric
    canonical name, ``objective:<name>`` or ``no_metrics``) — the caller
    surfaces it in a telemetry gauge and falls back to per-iteration
    eval."""
    if _link_for(gbdt.objective) is None:
        return None, f"objective:{getattr(gbdt.objective, 'name', '?')}"
    specs = []
    if include_train:
        specs.append(("training", gbdt.metrics, gbdt.train_set, -1))
    for i, (vname, vset) in enumerate(gbdt.valid_sets):
        specs.append((vname, gbdt.valid_metrics[i], vset, i))
    columns: List[Tuple[str, str, bool]] = []
    progs = []
    arrays = []
    try:
        for set_name, metrics, dset, src in specs:
            cols, arrs, set_fn = _build_set_program(
                metrics, dset.metadata, dset.num_data, gbdt.objective)
            columns.extend((set_name, mn, hb) for mn, hb in cols)
            progs.append((src, set_fn))
            arrays.append(arrs)
    except _Blocked as e:
        return None, e.what
    if not columns:
        return None, "no_metrics"
    # valid-set bin matrices, row-major [Nv, G] (binned against the train
    # set's reference mappers, so fmeta's group/offset remap applies)
    vbins = tuple(vset.device_binned() for _, vset in gbdt.valid_sets)

    def eval_fn(train_score, vscores, arrs_tuple):
        outs = []
        for (src, set_fn), A in zip(progs, arrs_tuple):
            s = train_score if src < 0 else vscores[src]
            outs.append(set_fn(s, A))
        return jnp.concatenate(outs)

    return DeviceEval(tuple(columns), eval_fn, tuple(arrays), vbins), None
