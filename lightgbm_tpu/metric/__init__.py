"""Metric battery + factory.

Reference: src/metric/metric.cpp:16-60 (factory) and the per-family headers:
regression_metric.hpp (l2/rmse/l1/quantile/huber/fair/poisson/mape/gamma/
gamma_deviance/tweedie), binary_metric.hpp (binary_logloss:115,
binary_error:139, AUC:159), multiclass_metric.hpp (multi_logloss,
multi_error with top-k), rank_metric.hpp (NDCG@k) + map_metric.hpp (MAP@k),
xentropy_metric.hpp (cross_entropy, cross_entropy_lambda, kullback_leibler).

All metrics are host-side numpy over the raw score matrix; ``eval`` applies
the objective's link where the reference does (Metric::Eval's ConvertOutput
hook, metric.h:44).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils.dcg import DCGCalculator
from ..utils.log import log_fatal, log_warning


class Metric:
    name: str = ""
    higher_better = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weights = (np.asarray(metadata.weights, dtype=np.float64)
                        if metadata.weights is not None else None)
        self.sum_weights = (float(self.weights.sum())
                            if self.weights is not None else float(num_data))
        self.metadata = metadata

    def eval(self, score: np.ndarray, objective=None) -> float:
        raise NotImplementedError

    def _avg(self, losses: np.ndarray) -> float:
        if self.weights is None:
            return float(np.mean(losses))
        return float(np.sum(losses * self.weights) / self.sum_weights)


def _convert(score, objective):
    if objective is not None:
        return objective.convert_output(score)
    return score


# ------------------------------------------------------------------ regression
class L2Metric(Metric):
    name = "l2"

    def eval(self, score, objective=None):
        p = _convert(score, objective)
        return self._avg((self.label - p) ** 2)


class RMSEMetric(L2Metric):
    name = "rmse"

    def eval(self, score, objective=None):
        return float(np.sqrt(super().eval(score, objective)))


class L1Metric(Metric):
    name = "l1"

    def eval(self, score, objective=None):
        p = _convert(score, objective)
        return self._avg(np.abs(self.label - p))


class QuantileMetric(Metric):
    name = "quantile"

    def eval(self, score, objective=None):
        a = float(self.config.alpha)
        p = _convert(score, objective)
        d = self.label - p
        return self._avg(np.where(d >= 0, a * d, (a - 1) * d))


class HuberMetric(Metric):
    name = "huber"

    def eval(self, score, objective=None):
        a = float(self.config.alpha)
        p = _convert(score, objective)
        d = np.abs(self.label - p)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return self._avg(loss)


class FairMetric(Metric):
    name = "fair"

    def eval(self, score, objective=None):
        c = float(self.config.fair_c)
        p = _convert(score, objective)
        x = np.abs(self.label - p)
        return self._avg(c * c * (x / c - np.log1p(x / c)))


class PoissonMetric(Metric):
    name = "poisson"

    def eval(self, score, objective=None):
        p = np.maximum(_convert(score, objective), 1e-15)
        return self._avg(p - self.label * np.log(p))


class MAPEMetric(Metric):
    name = "mape"

    def eval(self, score, objective=None):
        p = _convert(score, objective)
        return self._avg(np.abs((self.label - p))
                         / np.maximum(1.0, np.abs(self.label)))


class GammaMetric(Metric):
    name = "gamma"

    def eval(self, score, objective=None):
        """Negative log-likelihood of Gamma with shape=1
        (regression_metric.hpp GammaMetric)."""
        p = np.maximum(_convert(score, objective), 1e-15)
        x = self.label / p
        return self._avg(x + np.log(p) - np.log(np.maximum(self.label, 1e-15)))


class GammaDevianceMetric(Metric):
    name = "gamma_deviance"

    def eval(self, score, objective=None):
        p = np.maximum(_convert(score, objective), 1e-15)
        x = self.label / p
        return self._avg(2.0 * (np.log(np.maximum(1.0 / np.maximum(x, 1e-15),
                                                  1e-15)) + x - 1.0))


class TweedieMetric(Metric):
    name = "tweedie"

    def eval(self, score, objective=None):
        rho = float(self.config.tweedie_variance_power)
        p = np.maximum(_convert(score, objective), 1e-15)
        a = self.label * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return self._avg(-a + b)


# -------------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective=None):
        p = np.clip(_convert(score, objective), 1e-15, 1 - 1e-15)
        # positive <=> label > 0, the reference's is_pos rule
        # (binary objective/metric accept any labels)
        y = (self.label > 0).astype(np.float64)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return self._avg(loss)


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective=None):
        p = _convert(score, objective)
        pred = (p > 0.5).astype(np.float64)
        y = (self.label > 0).astype(np.float64)
        return self._avg((pred != y).astype(np.float64))


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, score, objective=None):
        """Weighted rank-sum AUC (binary_metric.hpp:159-240)."""
        order = np.argsort(score, kind="stable")
        y = self.label[order]
        w = (self.weights[order] if self.weights is not None
             else np.ones_like(y))
        # average rank for tied scores
        s = score[order]
        pos_w = np.sum(w * (y > 0))
        neg_w = np.sum(w * (y <= 0))
        if pos_w <= 0 or neg_w <= 0:
            log_warning("AUC is undefined with a single class")
            return 1.0
        # handle ties: group by unique score, use half credit within a group
        _, first_idx, inv = np.unique(s, return_index=True, return_inverse=True)
        grp_neg = np.add.reduceat(w * (y <= 0), first_idx)
        cum_before = np.concatenate([[0], np.cumsum(grp_neg)[:-1]])
        auc_sum = np.sum((cum_before[inv] + 0.5 * grp_neg[inv])
                         * w * (y > 0))
        return float(auc_sum / (pos_w * neg_w))


# ----------------------------------------------------------------- multiclass
class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective=None):
        """score [C, N]; softmax via objective convert."""
        p = _convert(score, objective)
        p = np.clip(p, 1e-15, 1 - 1e-15)
        lab = self.label.astype(np.int64)
        ll = -np.log(p[lab, np.arange(self.num_data)])
        return self._avg(ll)


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective=None):
        lab = self.label.astype(np.int64)
        k = max(1, int(self.config.multi_error_top_k))
        if k == 1:
            pred = np.argmax(score, axis=0)
            err = (pred != lab).astype(np.float64)
        else:
            # top-k correctness (multiclass_metric.hpp MultiErrorMetric)
            target = score[lab, np.arange(self.num_data)]
            rank = np.sum(score > target[None, :], axis=0)
            err = (rank >= k).astype(np.float64)
        return self._avg(err)


# ----------------------------------------------------------------- xentropy
class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score, objective=None):
        p = np.clip(_convert(score, objective), 1e-15, 1 - 1e-15)
        # positive <=> label > 0, the reference's is_pos rule
        # (binary objective/metric accept any labels)
        y = (self.label > 0).astype(np.float64)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return self._avg(loss)


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        # score -> lambda parameter; prob = 1 - exp(-w*log1p(exp(score)))
        hhat = np.log1p(np.exp(np.asarray(score, dtype=np.float64)))
        w = self.weights if self.weights is not None else 1.0
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        loss = -(self.label * np.log(z) + (1 - self.label) * np.log(1 - z))
        return float(np.mean(loss))


class KLDivMetric(Metric):
    name = "kullback_leibler"

    def eval(self, score, objective=None):
        p = np.clip(_convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        kl = (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))
        return self._avg(kl)


# ----------------------------------------------------------------------- rank
class NDCGMetric(Metric):
    name = "ndcg"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log_fatal("The NDCG metric requires query information")
        self.boundaries = np.asarray(metadata.query_boundaries)
        self.calc = DCGCalculator(self.config.label_gain)
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        self.query_weights = metadata.query_weights

    def eval_multi(self, score, objective=None) -> List[float]:
        nq = len(self.boundaries) - 1
        out = np.zeros(len(self.eval_at))
        sumw = 0.0
        for q in range(nq):
            s, e = self.boundaries[q], self.boundaries[q + 1]
            lab = self.label[s:e]
            sc = score[s:e]
            qw = (self.query_weights[q] if self.query_weights is not None
                  else 1.0)
            sumw += qw
            for i, k in enumerate(self.eval_at):
                maxdcg = self.calc.cal_maxdcg_at_k(k, lab)
                if maxdcg <= 0:
                    out[i] += qw  # no relevant docs counts as perfect
                else:
                    out[i] += qw * self.calc.cal_dcg_at_k(k, lab, sc) / maxdcg
        return list(out / max(sumw, 1e-20))

    def eval(self, score, objective=None):
        return self.eval_multi(score, objective)[0]


class MAPMetric(Metric):
    name = "map"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log_fatal("The MAP metric requires query information")
        self.boundaries = np.asarray(metadata.query_boundaries)
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        self.query_weights = metadata.query_weights

    def eval_multi(self, score, objective=None) -> List[float]:
        nq = len(self.boundaries) - 1
        out = np.zeros(len(self.eval_at))
        sumw = 0.0
        for q in range(nq):
            s, e = self.boundaries[q], self.boundaries[q + 1]
            lab = (self.label[s:e] > 0).astype(np.float64)
            order = np.argsort(-score[s:e], kind="stable")
            rel = lab[order]
            hits = np.cumsum(rel)
            prec = hits / np.arange(1, len(rel) + 1)
            qw = (self.query_weights[q] if self.query_weights is not None
                  else 1.0)
            sumw += qw
            for i, k in enumerate(self.eval_at):
                topk = slice(0, min(k, len(rel)))
                denom = max(min(k, int(lab.sum())), 1)
                ap = np.sum(prec[topk] * rel[topk]) / denom
                out[i] += qw * ap
        return list(out / max(sumw, 1e-20))

    def eval(self, score, objective=None):
        return self.eval_multi(score, objective)[0]


# -------------------------------------------------------------------- factory
_ALIASES = {
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1",
    "regression_l1": "l1",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler", "kldiv": "kullback_leibler",
}

_REGISTRY = {
    "l2": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric, "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivMetric, "ndcg": NDCGMetric, "map": MAPMetric,
}


def metric_canonical_name(name: str) -> Optional[str]:
    return _ALIASES.get(str(name).strip().lower())


def create_metric(name: str, config) -> Optional[Metric]:
    canon = metric_canonical_name(name)
    if canon is None:
        if name not in ("", "none", "null", "na", "custom"):
            log_warning(f"Unknown metric {name}")
        return None
    return _REGISTRY[canon](config)


def default_metric_for_objective(objective_name: str) -> str:
    m = {
        "regression": "l2", "regression_l1": "l1", "huber": "huber",
        "fair": "fair", "poisson": "poisson", "quantile": "quantile",
        "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
        "binary": "binary_logloss", "multiclass": "multi_logloss",
        "multiclassova": "multi_logloss", "cross_entropy": "cross_entropy",
        "cross_entropy_lambda": "cross_entropy_lambda",
        "lambdarank": "ndcg",
    }
    return m.get(objective_name, "")
