"""Refit: re-estimate leaf outputs of an existing model on new data.

Reference: GBDT::RefitTree (src/boosting/gbdt.cpp) via the CLI refit task
(application.cpp) and Booster.refit (python basic.py): walk trees in order,
compute objective gradients at the progressively-updated score, and blend
each leaf's output with the gradient-optimal value using refit_decay_rate:
new = decay * old + (1 - decay) * (-sum_g / (sum_h + lambda_l2)).
"""

from __future__ import annotations

import numpy as np

from ..objective import create_objective
from ..ops.split import K_EPSILON


def snapshot_leaf_values(gbdt):
    """Per-tree float64 copies of every leaf value — taken before a
    speculative refit so a rejected refit→swap can be undone exactly
    (refit mutates ``tree.leaf_value`` in place)."""
    return [np.array(t.leaf_value, dtype=np.float64) for t in gbdt.models]


def restore_leaf_values(gbdt, snapshot) -> None:
    """Undo an in-place refit: restore the leaf values captured by
    :func:`snapshot_leaf_values` (bit-exact; structure untouched)."""
    if len(snapshot) != len(gbdt.models):
        raise ValueError(
            f"leaf-value snapshot holds {len(snapshot)} trees but the "
            f"model has {len(gbdt.models)}")
    for tree, vals in zip(gbdt.models, snapshot):
        tree.leaf_value = np.array(vals, dtype=np.float64)


def refit_model(gbdt, metadata, leaf_preds: np.ndarray, config) -> None:
    """``metadata`` carries label/weights/query boundaries — pass the full
    training Metadata where available so weighted and ranking objectives
    refit correctly."""
    objective = create_objective(config)
    if objective is None:
        objective = gbdt.objective
    label = np.asarray(metadata.label)
    objective.init(metadata, len(label))

    C = gbdt.num_tree_per_iteration
    decay = float(config.refit_decay_rate)
    lam = float(config.lambda_l2)
    n_trees = leaf_preds.shape[1]
    score = np.zeros((C, len(label)), dtype=np.float64)
    for k in range(C):
        score[k] += gbdt.init_scores[k]

    import jax.numpy as jnp
    for t in range(n_trees):
        k = t % C
        g, h = objective.get_gradients(
            jnp.asarray(score if C > 1 else score[k], dtype=jnp.float32))
        g = np.asarray(g if C == 1 else g[k], dtype=np.float64)
        h = np.asarray(h if C == 1 else h[k], dtype=np.float64)
        tree = gbdt.models[t]
        leaves = leaf_preds[:, t]
        new_values = np.array(tree.leaf_value, dtype=np.float64)
        for leaf in range(tree.num_leaves):
            sel = leaves == leaf
            if not sel.any():
                continue
            sum_g, sum_h = g[sel].sum(), h[sel].sum()
            opt = -sum_g / (sum_h + lam + K_EPSILON) * tree.shrinkage
            new_values[leaf] = decay * new_values[leaf] + (1 - decay) * opt
        tree.leaf_value = new_values
        # leaf assignments are given, so the tree's contribution is a
        # direct gather — no feature matrix needed (matches GBDT::RefitTree
        # updating scores from leaf outputs)
        score[k] += new_values[leaves]
