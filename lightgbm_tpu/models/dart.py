"""DART boosting: per-iteration random tree dropout with re-normalization.

Reference: src/boosting/dart.hpp:23-211 — DroppingTrees (uniform or
tree-weighted selection capped by max_drop, skip_drop chance), shrinkage
lr/(1+k) (or lr/(lr+k) in xgboost_dart_mode), and Normalize's three-step
shrinkage dance whose NET effect per dropped tree with k drops is:

  * train/valid score -= 1/(k+1) x tree's current prediction
  * stored leaf values scale by k/(k+1)

(xgboost mode: lr/(k+lr) and k/(k+lr) respectively).  This implementation
applies the net effect directly instead of replaying the sign-flip steps.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT


class DART(GBDT):

    # mutates freshly-grown trees right after each iteration
    _async_trees = False
    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0

    def _select_drop(self) -> List[int]:
        cfg = self.config
        if self._drop_rng.rand() < cfg.skip_drop:
            return []
        drop: List[int] = []
        if not cfg.uniform_drop and self.sum_weight > 0:
            inv_avg = len(self.tree_weight) / self.sum_weight
            rate = cfg.drop_rate
            if cfg.max_drop > 0:
                rate = min(rate, cfg.max_drop * inv_avg / self.sum_weight)
            for i in range(self.iter_):
                if self._drop_rng.rand() < rate * self.tree_weight[i] * inv_avg:
                    drop.append(i)
                    if len(drop) >= cfg.max_drop > 0:
                        break
        else:
            rate = cfg.drop_rate
            if cfg.max_drop > 0 and self.iter_ > 0:
                rate = min(rate, cfg.max_drop / self.iter_)
            for i in range(self.iter_):
                if self._drop_rng.rand() < rate:
                    drop.append(i)
                    if len(drop) >= cfg.max_drop > 0:
                        break
        return drop

    def _tree_predictions(self, it: int):
        """Current train/valid predictions of iteration ``it``'s trees."""
        C = self.num_tree_per_iteration
        infos = self.train_set.feature_infos()
        train_preds, valid_preds = [], []
        for k in range(C):
            tree = self.models[it * C + k]
            train_preds.append(tree.predict_binned(self.train_set.binned,
                                                   infos))
            valid_preds.append([tree.predict_binned(vset.binned, infos)
                                for (_, vset) in self.valid_sets])
        return train_preds, valid_preds

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.config
        self._boost_from_average()
        C = self.num_tree_per_iteration
        drop = self._select_drop()
        k = float(len(drop))

        # drop: remove the dropped trees' full contribution before gradients
        dropped_preds = []
        for it in drop:
            tp, vp = self._tree_predictions(it)
            dropped_preds.append((it, tp, vp))
            for ki in range(C):
                self.train_score = self.train_score.at[ki].add(
                    -jnp.asarray(tp[ki], dtype=jnp.float32))
                for vi, vscore in enumerate(self.valid_scores):
                    vscore[ki] -= vp[ki][vi]

        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k)
            scale = k / (k + 1.0)
            sub = 1.0 / (k + 1.0)
        else:
            self.shrinkage_rate = (cfg.learning_rate if not drop else
                                   cfg.learning_rate / (cfg.learning_rate + k))
            scale = k / (k + cfg.learning_rate)
            sub = cfg.learning_rate / (k + cfg.learning_rate)

        ret = super().train_one_iter(grad, hess)
        if ret:
            # training stopped: restore the dropped trees' contribution
            for it, tp, vp in dropped_preds:
                for ki in range(C):
                    self.train_score = self.train_score.at[ki].add(
                        jnp.asarray(tp[ki], dtype=jnp.float32))
                    for vi, vscore in enumerate(self.valid_scores):
                        vscore[ki] += vp[ki][vi]
            return ret

        # normalize: add back scale x prediction, shrink stored trees
        for it, tp, vp in dropped_preds:
            for ki in range(C):
                tree = self.models[it * C + ki]
                tree.apply_shrinkage(scale)
                self.train_score = self.train_score.at[ki].add(
                    jnp.asarray(np.asarray(tp[ki]) * scale,
                                dtype=jnp.float32))
                for vi, vscore in enumerate(self.valid_scores):
                    vscore[ki] += vp[ki][vi] * scale
            if not cfg.uniform_drop:
                self.sum_weight -= self.tree_weight[it] * sub
                self.tree_weight[it] *= scale

        if not cfg.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
