"""GOSS: Gradient-based One-Side Sampling.

Reference: src/boosting/goss.hpp:30-220 — keep the top ``top_rate`` fraction
of rows by sum-over-classes |grad x hess|, sample ``other_rate`` of the rest
uniformly and amplify their grad AND hess by (cnt - top_k) / other_k; no
subsampling for the first 1/learning_rate iterations (goss.hpp:142-145).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import check, log_fatal
from .gbdt import GBDT


class GOSS(GBDT):
    # _bagging inspects gradients on the host; the fused iteration computes
    # them in-jit, so GOSS keeps the eager path (device-side GOSS sampling
    # replaces this)
    _fused_ok = False

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        check(config.top_rate + config.other_rate <= 1.0,
              "top_rate + other_rate cannot be larger than 1.0")
        check(config.top_rate > 0 and config.other_rate > 0,
              "top_rate and other_rate must be positive for GOSS")

    def _bagging(self, iter_idx, grads, hesss):
        cfg = self.config
        n = self.num_data
        # warm-up: use all data for the first 1/lr iterations
        if iter_idx < int(1.0 / cfg.learning_rate):
            self.bag_weight = jnp.ones(n, dtype=jnp.float32)
            return grads, hesss
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))

        score = np.abs(np.asarray(grads) * np.asarray(hesss)).sum(axis=0)
        top_idx = np.argpartition(-score, top_k - 1)[:top_k]
        rest = np.setdiff1d(np.arange(n), top_idx, assume_unique=False)
        sampled = self._bag_rng.choice(rest, min(other_k, len(rest)),
                                       replace=False)
        multiply = (n - top_k) / other_k

        mask = np.zeros(n, dtype=np.float32)
        mask[top_idx] = 1.0
        mask[sampled] = 1.0
        amp = np.ones(n, dtype=np.float32)
        amp[sampled] = multiply
        amp_d = jnp.asarray(amp)[None, :]
        self.bag_weight = jnp.asarray(mask)
        return grads * amp_d, hesss * amp_d
