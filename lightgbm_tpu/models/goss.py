"""GOSS: Gradient-based One-Side Sampling.

Reference: src/boosting/goss.hpp:30-220 — keep the top ``top_rate`` fraction
of rows by sum-over-classes |grad x hess|, sample ``other_rate`` of the rest
uniformly and amplify their grad AND hess by (cnt - top_k) / other_k; no
subsampling for the first 1/learning_rate iterations (goss.hpp:142-145).

The selection runs ON DEVICE (jnp sort/argsort + threshold masks): the
reference's OpenMP top-k + per-thread random pick (goss.hpp:91-140) would
force a gradient round-trip to the host every iteration, breaking the
transfer-free training loop.  Sorts are bandwidth-shaped on TPU and cost a
few ms at 10M rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.log import check
from .gbdt import GBDT


@jax.jit
def _goss_select(grads, hesss, key, top_k, other_k):
    """Exact top-k + uniform other_k sampling, all on device, in ONE
    dispatch: the amplified gradients come back alongside the mask so
    the eager multiplies never leave the jit.

    Returns (grads' [C, n], hesss' [C, n], mask [n] f32): mask is the
    bagging weight; sampled small-gradient rows are amplified by
    (n - top_k) / other_k in both grad and hess (goss.hpp:91-140).
    """
    n = grads.shape[1]
    score = jnp.sum(jnp.abs(grads * hesss), axis=0)
    order = jnp.argsort(-score)
    rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    top_mask = rank < top_k
    # exactly other_k of the rest: smallest other_k uniform keys
    u = jax.random.uniform(key, (n,))
    u = jnp.where(top_mask, jnp.inf, u)
    kth = jnp.sort(u)[jnp.maximum(other_k - 1, 0)]
    rest_sel = (u <= kth) & ~top_mask
    multiply = (n - top_k).astype(jnp.float32) / \
        jnp.maximum(other_k, 1).astype(jnp.float32)
    mask = (top_mask | rest_sel).astype(jnp.float32)
    amp = jnp.where(rest_sel, multiply, 1.0)
    return grads * amp[None, :], hesss * amp[None, :], mask


class GOSS(GBDT):
    # GOSS's sampling is a pure device-side transform of the gradients,
    # so it rides the fused pipeline: gradient dispatch, one sampling
    # dispatch (skipped in warm-up), then the per-class fused grow+score

    # the sampling dispatch runs between gradients and grow each
    # iteration (with an iter_idx-dependent warm-up switch), which the
    # single-program chunked loop does not replicate
    _chunk_capable = False

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        check(config.top_rate + config.other_rate <= 1.0,
              "top_rate + other_rate cannot be larger than 1.0")
        check(config.top_rate > 0 and config.other_rate > 0,
              "top_rate and other_rate must be positive for GOSS")

    def _bagging(self, iter_idx, grads, hesss):
        cfg = self.config
        n = self.num_data
        # warm-up: use all data for the first 1/lr iterations
        if iter_idx < int(1.0 / cfg.learning_rate):
            self.bag_weight = jnp.ones(n, dtype=jnp.float32)
            return grads, hesss
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        key = jax.random.fold_in(self._key, 0x60550000 + iter_idx)
        grads, hesss, mask = _goss_select(grads, hesss, key,
                                          jnp.int32(top_k),
                                          jnp.int32(other_k))
        self.bag_weight = mask
        return grads, hesss
