"""Leaf-wise tree growth, fused on-device.

The TPU re-design of SerialTreeLearner::Train (serial_tree_learner.cpp:174-239).
The reference's per-split sequence — BeforeFindBestSplit / ConstructHistograms
/ FindBestSplitsFromHistograms / Split over index-list leaf partitions — is
re-expressed as ONE jitted ``lax.fori_loop`` whose state lives entirely in
HBM:

  * leaf membership is a dense ``leaf_id[N]`` vector (scatter-free splits by
    masked where) instead of DataPartition's index lists
    (data_partition.hpp:111);
  * per-leaf histograms are retained in a ``[num_leaves, F, B, 3]`` tensor —
    the HistogramPool (feature_histogram.hpp:654) without eviction since HBM
    comfortably holds all leaves;
  * only the smaller child is histogrammed from data; the larger child is
    parent - smaller (the subtraction trick, serial_tree_learner.cpp:494-497,
    596-597);
  * the leaf to split is the argmax of per-leaf best gains
    (serial_tree_learner.cpp:219), and tree topology is built with LightGBM's
    node numbering (Tree::Split, tree.h:407-445: new internal node =
    num_leaves-1, right child leaf = num_leaves, leaf refs stored as ~leaf).

Everything is traced once per (N, F, B, num_leaves, params) signature; no
host round-trips during growth.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.binning import MISSING_NAN, MISSING_ZERO
from ..ops.histogram import histogram_chunked
from ..ops.split import (NEG_INF, FeatureMeta, SplitParams, best_split,
                         expand_group_hist, leaf_gain, leaf_output,
                         reconstruct_feature_column)


class GrowerParams(NamedTuple):
    """Static growth hyper-parameters (folded into the jit signature)."""
    num_leaves: int = 31
    max_depth: int = -1
    feature_fraction_bynode: float = 1.0
    row_chunk: int = 0
    # "onehot": row-major [N, F] bins, XLA one-hot einsum (works anywhere);
    # "pallas": feature-major [F, Npad] bins, TPU pallas kernel
    # (ops/pallas_histogram.py)
    hist_backend: str = "onehot"
    # pallas-only: bins packed two <=16-bin columns per byte
    # (ops/pallas_histogram.pack_bins_4bit; reference Dense4bitsBin,
    # dense_nbits_bin.hpp:42) — halves bin-stream DMA and sort payload
    packed4: bool = False
    # logical bin-matrix columns (EFB groups); 0 = same as the physical
    # row count of the bins array (needed when packed4 obscures it)
    num_columns: int = 0
    # static: any feature carries a monotone constraint — enables per-leaf
    # [min, max] output-bound propagation (LeafSplits::SetValueConstraint,
    # src/treelearner/leaf_splits.hpp:50-53 + the mid-split handoff in
    # serial_tree_learner.cpp:892-903)
    use_monotone: bool = False
    # CEGB penalties (serial_tree_learner.cpp:527-618); split/coupled are
    # in-grower gain adjustments, lazy is handled by the fused grower only
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    use_cegb_coupled: bool = False
    use_cegb_lazy: bool = False
    # forced splits (ForceSplits, serial_tree_learner.cpp:642): static
    # BFS-ordered plan of (leaf, inner_feature, threshold_bin) applied to
    # the leading growth steps before best-gain growth
    forced_plan: tuple = ()
    split: SplitParams = SplitParams()

    @property
    def feature_major(self) -> bool:
        return self.hist_backend == "pallas"


class TreeArrays(NamedTuple):
    """Flat-array tree, device-resident; mirrors reference Tree storage
    (include/LightGBM/tree.h:330-404)."""
    num_leaves: jax.Array          # i32 scalar: leaves actually produced
    # internal nodes [num_leaves-1]
    split_feature: jax.Array       # i32 (index into used features)
    threshold_bin: jax.Array       # i32
    default_left: jax.Array        # bool
    is_cat: jax.Array              # bool
    cat_bitset: jax.Array          # u32 [num_leaves-1, 8]
    left_child: jax.Array          # i32 (>=0 internal, ~leaf for leaves)
    right_child: jax.Array         # i32
    split_gain: jax.Array          # f32
    internal_value: jax.Array      # f32
    internal_weight: jax.Array     # f32
    internal_count: jax.Array      # f32
    # leaves [num_leaves]
    leaf_value: jax.Array          # f32
    leaf_weight: jax.Array         # f32
    leaf_count: jax.Array          # f32
    leaf_parent: jax.Array         # i32
    leaf_depth: jax.Array          # i32


@jax.jit
def _pack_tree_device(t: TreeArrays):
    """Concatenate all tree fields into one i32 + one f32 buffer so the
    host fetch is two transfers instead of ~17 (each pays a full device
    round-trip)."""
    ints = jnp.concatenate([
        jnp.atleast_1d(t.num_leaves),
        t.split_feature, t.threshold_bin,
        t.default_left.astype(jnp.int32), t.is_cat.astype(jnp.int32),
        t.cat_bitset.astype(jnp.int32).ravel(),
        t.left_child, t.right_child,
        t.leaf_parent, t.leaf_depth,
    ])
    floats = jnp.concatenate([
        t.split_gain, t.internal_value, t.internal_weight,
        t.internal_count, t.leaf_value, t.leaf_weight, t.leaf_count,
    ])
    return ints, floats


def _count_fetch(*bufs) -> None:
    """Telemetry: one device->host tree fetch (however many transfers it
    batches) with its total payload bytes."""
    from ..utils.telemetry import TELEMETRY
    TELEMETRY.counter_add("transfer/fetch_calls")
    TELEMETRY.counter_add("transfer/fetch_bytes",
                          sum(int(b.nbytes) for b in bufs))


def fetch_tree_arrays(t: TreeArrays) -> TreeArrays:
    """Device TreeArrays -> host (numpy) TreeArrays via two transfers."""
    import numpy as np
    ints_d, floats_d = _pack_tree_device(t)
    ints_np, floats_np = np.asarray(ints_d), np.asarray(floats_d)
    _count_fetch(ints_np, floats_np)
    return unpack_tree_buffers(ints_np, floats_np, t.leaf_value.shape[0])


def fetch_tree_chunk(ints_all, floats_all, L: int) -> list:
    """Batched inverse of _pack_tree_device over a whole boosting chunk:
    stacked [T, C, len] device buffers -> [[TreeArrays] * C] * T host
    pytrees.  The entire chunk crosses the device boundary in TWO
    transfers; fetching tree-by-tree would pay 2*T*C round-trips."""
    import numpy as np
    ints_np = np.asarray(ints_all)
    floats_np = np.asarray(floats_all)
    _count_fetch(ints_np, floats_np)
    return [[unpack_tree_buffers(ints_np[t, k], floats_np[t, k], L)
             for k in range(ints_np.shape[1])]
            for t in range(ints_np.shape[0])]


def unpack_tree_buffers(ints, floats, L: int) -> TreeArrays:
    """Host-side inverse of _pack_tree_device."""
    import numpy as np
    n = L - 1

    def take(buf, pos, count, shape=None):
        out = buf[pos:pos + count]
        return (out.reshape(shape) if shape else out), pos + count

    p = 0
    num_leaves, p = take(ints, p, 1)
    split_feature, p = take(ints, p, n)
    threshold_bin, p = take(ints, p, n)
    default_left, p = take(ints, p, n)
    is_cat, p = take(ints, p, n)
    cat_bitset, p = take(ints, p, n * 8, (n, 8))
    left_child, p = take(ints, p, n)
    right_child, p = take(ints, p, n)
    leaf_parent, p = take(ints, p, L)
    leaf_depth, p = take(ints, p, L)
    q = 0
    split_gain, q = take(floats, q, n)
    internal_value, q = take(floats, q, n)
    internal_weight, q = take(floats, q, n)
    internal_count, q = take(floats, q, n)
    leaf_value, q = take(floats, q, L)
    leaf_weight, q = take(floats, q, L)
    leaf_count, q = take(floats, q, L)
    return TreeArrays(
        num_leaves=int(num_leaves[0]),
        split_feature=split_feature, threshold_bin=threshold_bin,
        default_left=default_left.astype(bool),
        is_cat=is_cat.astype(bool),
        cat_bitset=cat_bitset.astype(np.uint32),
        left_child=left_child, right_child=right_child,
        split_gain=split_gain, internal_value=internal_value,
        internal_weight=internal_weight, internal_count=internal_count,
        leaf_value=leaf_value, leaf_weight=leaf_weight,
        leaf_count=leaf_count, leaf_parent=leaf_parent,
        leaf_depth=leaf_depth,
    )


class _GrowState(NamedTuple):
    leaf_id: jax.Array
    num_leaves: jax.Array
    leaf_hist: jax.Array           # [L, F, B, 3]
    leaf_g: jax.Array              # [L]
    leaf_h: jax.Array
    leaf_c: jax.Array
    # per-leaf monotone output bounds (LeafSplits min_val_/max_val_)
    leaf_mono_lo: jax.Array        # [L]
    leaf_mono_hi: jax.Array        # [L]
    # CEGB bookkeeping: features used by any split so far ([F] 0/1), and
    # per-(feature, row) "row has paid for feature" marks ([F, N] i8 when
    # cegb_penalty_feature_lazy is active, else [1, 1])
    feat_used: jax.Array
    seen: jax.Array
    # per-leaf best-split cache (best_split_per_leaf_,
    # serial_tree_learner.h:153)
    # best-split cache PACKED into 3 tensors so each scan writes 3 rows
    # instead of 11 scalar scatters: f32 [L, 6] = (gain, left_g, left_h,
    # left_c, left_out, right_out); i32 [L, 4] = (feature, threshold,
    # default_left, is_cat); cat bitset [L, 8] u32
    best_f32: jax.Array
    best_i32: jax.Array
    best_cat_bitset: jax.Array
    tree: TreeArrays


def _bit_test(bitset_row: jax.Array, idx: jax.Array) -> jax.Array:
    """bitset_row u32[8], idx i32 -> bool."""
    word = bitset_row[idx // 32]
    return ((word >> (idx % 32).astype(jnp.uint32)) & 1).astype(bool)


def routed_left(fcol, threshold, default_left, is_cat, cat_bitset,
                missing_type, default_bin, num_bin):
    """Which side each row goes (numerical <=threshold with missing routing,
    categorical bitset membership)."""
    fcol = fcol.astype(jnp.int32)
    is_missing = (((missing_type == MISSING_ZERO) & (fcol == default_bin))
                  | ((missing_type == MISSING_NAN) & (fcol == num_bin - 1)))
    num_left = jnp.where(is_missing, default_left, fcol <= threshold)
    cat_left = _bit_test(cat_bitset, jnp.clip(fcol, 0, 255))
    return jnp.where(is_cat, cat_left, num_left)


def _node_feature_mask(base_mask, key, step, p: GrowerParams):
    if p.feature_fraction_bynode >= 1.0:
        return base_mask
    sub = jax.random.fold_in(key, step)
    m = jax.random.bernoulli(sub, p.feature_fraction_bynode,
                             base_mask.shape).astype(base_mask.dtype)
    m = m * base_mask
    # guarantee at least one usable feature
    return jnp.where(m.sum() > 0, m, base_mask)


def _cegb_split_coupled_adjust(feat_used, c, fmeta, p: GrowerParams):
    """[F] additive CEGB penalty: per-row split cost + coupled feature cost
    for not-yet-used features (serial_tree_learner.cpp:582-607)."""
    F = feat_used.shape[0]
    adjust = jnp.full(F, p.cegb_tradeoff * p.cegb_penalty_split,
                      jnp.float32) * c
    if p.use_cegb_coupled:
        adjust = adjust + p.cegb_tradeoff * fmeta.cegb_coupled * \
            (1.0 - feat_used)
    return adjust


def _cegb_gain_adjust(st: "_GrowState", leaf, c, in_leaf, fmeta,
                      p: GrowerParams):
    """Full CEGB penalty incl. the lazy per-(feature,row) cost for rows
    that have not yet paid for the feature (CalculateOndemandCosts,
    serial_tree_learner.cpp:527-547)."""
    if not (p.cegb_penalty_split > 0.0 or p.use_cegb_coupled
            or p.use_cegb_lazy):
        return None
    adjust = _cegb_split_coupled_adjust(st.feat_used, c, fmeta, p)
    if p.use_cegb_lazy:
        unseen = jnp.sum((1 - st.seen) * in_leaf[None, :].astype(jnp.int8),
                         axis=1).astype(jnp.float32)          # [F]
        adjust = adjust + p.cegb_tradeoff * fmeta.cegb_lazy * unseen
    return adjust


def mono_handoff(lo_p, hi_p, out_l, out_r, mono_f, cat):
    """Children's [lo, hi] output bounds after a split at
    mid=(left+right)/2 (serial_tree_learner.cpp:892-903).  Returns
    (lo_l, hi_l, lo_r, hi_r)."""
    mid = (out_l + out_r) / 2.0
    pos = ~cat & (mono_f > 0)
    neg = ~cat & (mono_f < 0)
    lo_l = jnp.where(neg, mid, lo_p)
    hi_l = jnp.where(pos, mid, hi_p)
    lo_r = jnp.where(pos, mid, lo_p)
    hi_r = jnp.where(neg, mid, hi_p)
    return lo_l, hi_l, lo_r, hi_r


def _leaf_scan(hist, g, h, c, depth, fmeta, fmask, p: GrowerParams,
               lo=None, hi=None, gain_adjust=None):
    """best_split for one leaf + depth gating."""
    info = best_split(hist, g, h, c, fmeta, p.split, fmask,
                      mono_lo=lo, mono_hi=hi, gain_adjust=gain_adjust)
    gain = info.gain
    if p.max_depth > 0:
        gain = jnp.where(depth >= p.max_depth, NEG_INF, gain)
    return info, gain


class CommHooks(NamedTuple):
    """Collective hooks injected by the parallel tree learners
    (SURVEY.md §2.5: the TPU equivalent of the Network reducers).

    ``reduce_hist(hist, G, H, C, fmeta)`` runs after every histogram build
    (data-parallel: psum / voting: vote + masked psum); ``reduce_stats(x)``
    reduces root scalar stats; ``merge_split(info)`` merges per-shard
    SplitInfos by max gain (feature-parallel: SyncUpGlobalBestSplit,
    parallel_tree_learner.h:356-397).  All default to identity (serial).

    ``no_subtract=True`` disables the parent-minus-smaller histogram trick
    and builds BOTH children's histograms from data.  Required whenever
    ``reduce_hist`` is not a plain linear reduction over a fixed feature
    set (voting-parallel: each call's vote elects a different feature
    subset, so parent and child histograms are masked inconsistently and
    their difference is meaningless).

    ``column_block`` (feature-parallel) returns this shard's
    ``(start_col, block_cols)`` so histogram CONSTRUCTION itself only
    touches the shard's column stripe — the reference histograms only the
    rank's own features (feature_parallel_tree_learner.cpp:36-75).  The
    stripe result is scattered back into a zero [F, B, 3] tensor at its
    offset; out-of-stripe features are masked by ``shard_feature_mask``.
    ``block_cols`` must be static (the same on every shard).
    """
    reduce_hist: object = None
    reduce_stats: object = None
    merge_split: object = None
    shard_feature_mask: object = None
    no_subtract: bool = False
    column_block: object = None
    # frontier-batched grower (grower_frontier.py) variants: the same
    # reductions over a whole K-leaf batch in one collective —
    # ``reduce_hist_batch([K, G, B, 3])`` and ``merge_split_batch(infos,
    # gains)`` with a leading batch axis on every SplitInfo field
    reduce_hist_batch: object = None
    merge_split_batch: object = None
    # ``uniform_scan(blocks)`` maps a per-shard scanned-block count to a
    # shard-UNIFORM value (data-parallel: pmax).  The strict segment
    # grower's epoch-while predicates gate on the scan counter, and a
    # while_loop whose body runs collectives must have shard-uniform trip
    # counts — per-shard confinement intervals differ, so the raw count
    # does not qualify.  None (serial) = identity.
    uniform_scan: object = None


def make_grow_tree(num_bins: int, params: GrowerParams,
                   comm: CommHooks = CommHooks(), wrap=None):
    """Build the jitted tree-growing function for a static (B, params).

    The returned ``grow(bins, grad, hess, member, fmeta, feature_mask, key)``
    takes the [N, F] bin matrix, per-row gradients/hessians (already weighted
    by metadata weights / GOSS amplification), a [N] inclusion weight vector
    (bagging mask), per-feature metadata arrays, a [F] per-tree feature mask,
    and a PRNG key; it returns ``(TreeArrays, leaf_id[N])`` where leaf ids
    follow LightGBM leaf numbering so ``leaf_value[leaf_id]`` is this tree's
    per-row raw prediction.
    """
    p = params
    L = p.num_leaves
    B = num_bins
    sp = p.split
    # packed int16 accumulator stream: resolved ONCE at build time (env
    # inside the jitted grow would poison the jit cache) — self-check
    # gated with automatic fallback to the f32 channel path.  The plain
    # grower quantizes per LEAF inside leaf_histogram_pallas, so the
    # rescale scales are naturally per-leaf.
    packed_acc = False
    qbits = 8
    if p.feature_major:
        from ..ops.pallas_histogram import (packed_acc_bits,
                                            packed_acc_decisions,
                                            packed_acc_enabled)
        packed_acc = packed_acc_enabled()
        qbits = packed_acc_bits()
        packed_acc_decisions["plain"] = packed_acc

    def hist_of(bins, grad, hess, member, G, H, C, fmeta):
        hist_bins = bins
        start = None
        if comm.column_block is not None:
            # feature-parallel: construct only this shard's column stripe
            start, ncols = comm.column_block(bins)
            if p.feature_major:
                hist_bins = lax.dynamic_slice_in_dim(bins, start, ncols,
                                                     axis=0)
            else:
                hist_bins = lax.dynamic_slice_in_dim(bins, start, ncols,
                                                     axis=1)
        if p.feature_major:
            from ..ops.pallas_histogram import leaf_histogram_pallas
            out = leaf_histogram_pallas(hist_bins, grad, hess, member, B,
                                        p.row_chunk, packed4=p.packed4,
                                        packed_acc=packed_acc, bits=qbits)
            if p.num_columns:
                out = out[: p.num_columns]
        else:
            w = jnp.stack([grad * member, hess * member, member])
            out = histogram_chunked(hist_bins, w, B, p.row_chunk)
        if start is not None:
            ncols_total = bins.shape[0] if p.feature_major else bins.shape[1]
            full = jnp.zeros((ncols_total,) + out.shape[1:], out.dtype)
            out = lax.dynamic_update_slice_in_dim(full, out, start, axis=0)
        if comm.reduce_hist is not None:
            out = comm.reduce_hist(out, G, H, C, fmeta)
        return out

    def scan_leaf(st: _GrowState, leaf_idx, hist, g, h, c, depth, fmeta,
                  fmask):
        lo = hi = None
        if p.use_monotone:
            lo = st.leaf_mono_lo[leaf_idx]
            hi = st.leaf_mono_hi[leaf_idx]
        adjust = _cegb_gain_adjust(st, leaf_idx, c, st.leaf_id == leaf_idx,
                                   fmeta, p)
        # EFB: group-space histogram -> per-feature view (identity when
        # the dataset is unbundled)
        hist = expand_group_hist(hist, fmeta, g, h, c)
        info, gain = _leaf_scan(hist, g, h, c, depth, fmeta, fmask, p,
                                lo=lo, hi=hi, gain_adjust=adjust)
        if comm.merge_split is not None:
            info, gain = comm.merge_split(info, gain)
        f32 = jnp.stack([gain, info.left_g, info.left_h, info.left_c,
                         info.left_out, info.right_out]).astype(jnp.float32)
        i32 = jnp.stack([info.feature, info.threshold,
                         info.default_left.astype(jnp.int32),
                         info.is_cat.astype(jnp.int32)])
        return st._replace(
            best_f32=st.best_f32.at[leaf_idx].set(f32),
            best_i32=st.best_i32.at[leaf_idx].set(i32),
            best_cat_bitset=st.best_cat_bitset.at[leaf_idx].set(info.cat_bitset),
        )

    def grow(bins, grad, hess, member, fmeta: FeatureMeta, feature_mask, key):
        # G = physical bin-matrix columns (EFB groups); F = logical
        # features the scans see.  Equal when unbundled.
        if p.feature_major:
            G_cols, n = bins.shape
        else:
            n, G_cols = bins.shape
        F = fmeta.num_bin.shape[0]
        if comm.shard_feature_mask is not None:
            feature_mask = comm.shard_feature_mask(feature_mask)

        def do_split(st: _GrowState, step, forced=None):
            new_leaf = st.num_leaves
            node = st.num_leaves - 1

            if forced is None:
                leaf = jnp.argmax(st.best_f32[:, 0]).astype(jnp.int32)
                bf = st.best_f32[leaf]
                bi = st.best_i32[leaf]
                f = bi[0]
                t = bi[1]
                dl = bi[2].astype(bool)
                cat = bi[3].astype(bool)
                bitset = st.best_cat_bitset[leaf]
                Gl, Hl, Cl = bf[1], bf[2], bf[3]
                Gp, Hp, Cp = st.leaf_g[leaf], st.leaf_h[leaf], st.leaf_c[leaf]
                Gr, Hr, Cr = Gp - Gl, Hp - Hl, Cp - Cl
                out_l = bf[4]
                out_r = bf[5]
                gain = bf[0]
            else:
                # forced numerical split (ForceSplits,
                # serial_tree_learner.cpp:642): stats from the leaf's
                # retained histogram at the given threshold bin
                leaf = jnp.int32(forced[0])
                f = jnp.int32(forced[1])
                t = jnp.int32(forced[2])
                dl = jnp.asarray(False)
                cat = jnp.asarray(False)
                bitset = jnp.zeros(8, dtype=jnp.uint32)
                hist_row = expand_group_hist(
                    st.leaf_hist[forced[0]], fmeta, st.leaf_g[leaf],
                    st.leaf_h[leaf], st.leaf_c[leaf])[forced[1]]
                cum = jnp.cumsum(hist_row, axis=0)
                Gl, Hl, Cl = cum[forced[2], 0], cum[forced[2], 1], \
                    cum[forced[2], 2]
                # keep stats consistent with routed_left(dl=False): zero-
                # missing default-bin rows route RIGHT, so drop them from
                # the left sums when the default bin falls under the
                # threshold
                db = fmeta.default_bin[forced[1]]
                drop = ((fmeta.missing_type[forced[1]] == MISSING_ZERO)
                        & (db <= t))
                dbh = hist_row[db]
                Gl = jnp.where(drop, Gl - dbh[0], Gl)
                Hl = jnp.where(drop, Hl - dbh[1], Hl)
                Cl = jnp.where(drop, Cl - dbh[2], Cl)
                Gp, Hp, Cp = st.leaf_g[leaf], st.leaf_h[leaf], st.leaf_c[leaf]
                Gr, Hr, Cr = Gp - Gl, Hp - Hl, Cp - Cl
                lo_f, hi_f = -jnp.inf, jnp.inf
                if p.use_monotone:
                    lo_f = st.leaf_mono_lo[leaf]
                    hi_f = st.leaf_mono_hi[leaf]
                out_l = jnp.clip(leaf_output(Gl, Hl, sp.lambda_l1,
                                             sp.lambda_l2,
                                             sp.max_delta_step), lo_f, hi_f)
                out_r = jnp.clip(leaf_output(Gr, Hr, sp.lambda_l1,
                                             sp.lambda_l2,
                                             sp.max_delta_step), lo_f, hi_f)
                gain = (leaf_gain(Gl, Hl, sp.lambda_l1, sp.lambda_l2,
                                  sp.max_delta_step)
                        + leaf_gain(Gr, Hr, sp.lambda_l1, sp.lambda_l2,
                                    sp.max_delta_step)
                        - leaf_gain(Gp, Hp, sp.lambda_l1, sp.lambda_l2,
                                    sp.max_delta_step))

            col = f if fmeta.feat_group is None else fmeta.feat_group[f]
            if p.feature_major:
                # contiguous [1, N] stream — far cheaper than the strided
                # row-major column gather
                if p.packed4:
                    from ..ops.pallas_histogram import slice_packed_column
                    fcol = slice_packed_column(bins, col)
                else:
                    fcol = lax.dynamic_slice_in_dim(bins, col, 1,
                                                    axis=0)[0, :]
            else:
                fcol = lax.dynamic_slice_in_dim(bins, col, 1, axis=1)[:, 0]
            fcol = reconstruct_feature_column(fcol, f, fmeta)
            go_left = routed_left(fcol, t, dl, cat, bitset,
                                  fmeta.missing_type[f], fmeta.default_bin[f],
                                  fmeta.num_bin[f])
            in_leaf = st.leaf_id == leaf
            leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, st.leaf_id)

            # monotone constraint handoff (serial_tree_learner.cpp:892-903)
            if p.use_monotone:
                lo_l, hi_l, lo_r, hi_r = mono_handoff(
                    st.leaf_mono_lo[leaf], st.leaf_mono_hi[leaf],
                    out_l, out_r, fmeta.monotone[f], cat)
                st = st._replace(
                    leaf_mono_lo=st.leaf_mono_lo
                    .at[leaf].set(lo_l).at[new_leaf].set(lo_r),
                    leaf_mono_hi=st.leaf_mono_hi
                    .at[leaf].set(hi_l).at[new_leaf].set(hi_r),
                )
            if p.use_cegb_coupled:
                st = st._replace(feat_used=st.feat_used.at[f].set(1.0))
            if p.use_cegb_lazy:
                st = st._replace(seen=st.seen.at[f].set(
                    jnp.maximum(st.seen[f],
                                in_leaf.astype(st.seen.dtype))))

            if comm.no_subtract:
                mem_l = (leaf_id == leaf).astype(grad.dtype) * member
                mem_r = (leaf_id == new_leaf).astype(grad.dtype) * member
                hist_left = hist_of(bins, grad, hess, mem_l, Gl, Hl, Cl,
                                    fmeta)
                hist_right = hist_of(bins, grad, hess, mem_r, Gr, Hr, Cr,
                                     fmeta)
            else:
                smaller_is_left = Cl <= Cr
                smaller = jnp.where(smaller_is_left, leaf, new_leaf)
                mem_small = (leaf_id == smaller).astype(grad.dtype) * member
                Gs = jnp.where(smaller_is_left, Gl, Gr)
                Hs = jnp.where(smaller_is_left, Hl, Hr)
                Cs = jnp.where(smaller_is_left, Cl, Cr)
                hist_small = hist_of(bins, grad, hess, mem_small, Gs, Hs, Cs,
                                     fmeta)
                hist_parent = st.leaf_hist[leaf]
                hist_large = hist_parent - hist_small
                hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
                hist_right = jnp.where(smaller_is_left, hist_large,
                                       hist_small)
            leaf_hist = (st.leaf_hist.at[leaf].set(hist_left)
                         .at[new_leaf].set(hist_right))

            depth_child = st.tree.leaf_depth[leaf] + 1
            tree = st.tree
            parent = tree.leaf_parent[leaf]
            # re-point the parent's child slot from ~leaf to the new node
            # (Tree::Split's parent fixup, tree.h:411-419)
            pl = jnp.where((parent >= 0)
                           & (tree.left_child[jnp.maximum(parent, 0)] == ~leaf),
                           node, tree.left_child[jnp.maximum(parent, 0)])
            pr = jnp.where((parent >= 0)
                           & (tree.right_child[jnp.maximum(parent, 0)] == ~leaf),
                           node, tree.right_child[jnp.maximum(parent, 0)])
            left_child = tree.left_child.at[jnp.maximum(parent, 0)].set(pl)
            right_child = tree.right_child.at[jnp.maximum(parent, 0)].set(pr)
            left_child = left_child.at[node].set(~leaf)
            right_child = right_child.at[node].set(~new_leaf)

            tree = tree._replace(
                num_leaves=st.num_leaves + 1,
                split_feature=tree.split_feature.at[node].set(f),
                threshold_bin=tree.threshold_bin.at[node].set(t),
                default_left=tree.default_left.at[node].set(dl),
                is_cat=tree.is_cat.at[node].set(cat),
                cat_bitset=tree.cat_bitset.at[node].set(bitset),
                left_child=left_child,
                right_child=right_child,
                split_gain=tree.split_gain.at[node].set(gain),
                internal_value=tree.internal_value.at[node].set(
                    tree.leaf_value[leaf]),
                internal_weight=tree.internal_weight.at[node].set(Hp),
                internal_count=tree.internal_count.at[node].set(Cp),
                leaf_value=(tree.leaf_value.at[leaf].set(out_l)
                            .at[new_leaf].set(out_r)),
                leaf_weight=(tree.leaf_weight.at[leaf].set(Hl)
                             .at[new_leaf].set(Hr)),
                leaf_count=(tree.leaf_count.at[leaf].set(Cl)
                            .at[new_leaf].set(Cr)),
                leaf_parent=(tree.leaf_parent.at[leaf].set(node)
                             .at[new_leaf].set(node)),
                leaf_depth=(tree.leaf_depth.at[leaf].set(depth_child)
                            .at[new_leaf].set(depth_child)),
            )

            st = st._replace(
                leaf_id=leaf_id,
                num_leaves=st.num_leaves + 1,
                leaf_hist=leaf_hist,
                leaf_g=st.leaf_g.at[leaf].set(Gl).at[new_leaf].set(Gr),
                leaf_h=st.leaf_h.at[leaf].set(Hl).at[new_leaf].set(Hr),
                leaf_c=st.leaf_c.at[leaf].set(Cl).at[new_leaf].set(Cr),
                tree=tree,
            )
            fmask_l = _node_feature_mask(feature_mask, key, 2 * step, p)
            fmask_r = _node_feature_mask(feature_mask, key, 2 * step + 1, p)
            st = scan_leaf(st, leaf, hist_left, Gl, Hl, Cl, depth_child,
                           fmeta, fmask_l)
            st = scan_leaf(st, new_leaf, hist_right, Gr, Hr, Cr, depth_child,
                           fmeta, fmask_r)
            return st

        def body(step, st: _GrowState):
            can_split = jnp.max(st.best_f32[:, 0]) > 0.0
            return lax.cond(can_split,
                            lambda s: do_split(s, step),
                            lambda s: s, st)

        # ---- init root ----
        G0 = jnp.sum(grad * member)
        H0 = jnp.sum(hess * member)
        C0 = jnp.sum(member)
        if comm.reduce_stats is not None:
            # allreduce of the root (cnt, sum_g, sum_h) tuple
            # (data_parallel_tree_learner.cpp:311-357)
            G0, H0, C0 = (comm.reduce_stats(G0), comm.reduce_stats(H0),
                          comm.reduce_stats(C0))
        root_hist = hist_of(bins, grad, hess, member, G0, H0, C0, fmeta)
        neg = jnp.full(L, NEG_INF, dtype=jnp.float32)
        zeros_l = jnp.zeros(L, dtype=jnp.float32)
        tree0 = TreeArrays(
            num_leaves=jnp.int32(1),
            split_feature=jnp.zeros(L - 1, dtype=jnp.int32),
            threshold_bin=jnp.zeros(L - 1, dtype=jnp.int32),
            default_left=jnp.zeros(L - 1, dtype=bool),
            is_cat=jnp.zeros(L - 1, dtype=bool),
            cat_bitset=jnp.zeros((L - 1, 8), dtype=jnp.uint32),
            left_child=jnp.full(L - 1, -1, dtype=jnp.int32),
            right_child=jnp.full(L - 1, -1, dtype=jnp.int32),
            split_gain=jnp.zeros(L - 1, dtype=jnp.float32),
            internal_value=jnp.zeros(L - 1, dtype=jnp.float32),
            internal_weight=jnp.zeros(L - 1, dtype=jnp.float32),
            internal_count=jnp.zeros(L - 1, dtype=jnp.float32),
            leaf_value=zeros_l,
            leaf_weight=zeros_l.at[0].set(H0),
            leaf_count=zeros_l.at[0].set(C0),
            leaf_parent=jnp.full(L, -1, dtype=jnp.int32),
            leaf_depth=jnp.zeros(L, dtype=jnp.int32),
        )
        used0 = (fmeta.cegb_used0 if (p.use_cegb_coupled
                                      and fmeta.cegb_used0 is not None)
                 else jnp.zeros(F, dtype=jnp.float32))
        st = _GrowState(
            leaf_id=jnp.zeros(n, dtype=jnp.int32),
            num_leaves=jnp.int32(1),
            leaf_hist=jnp.zeros((L,) + root_hist.shape, dtype=jnp.float32)
                         .at[0].set(root_hist),
            leaf_g=zeros_l.at[0].set(G0),
            leaf_h=zeros_l.at[0].set(H0),
            leaf_c=zeros_l.at[0].set(C0),
            leaf_mono_lo=jnp.full(L, -jnp.inf, dtype=jnp.float32),
            leaf_mono_hi=jnp.full(L, jnp.inf, dtype=jnp.float32),
            feat_used=used0,
            seen=jnp.zeros((F, n) if p.use_cegb_lazy else (1, 1),
                           dtype=jnp.int8),
            best_f32=jnp.zeros((L, 6), dtype=jnp.float32)
                        .at[:, 0].set(neg),
            best_i32=jnp.zeros((L, 4), dtype=jnp.int32)
                        .at[:, 0].set(-1),
            best_cat_bitset=jnp.zeros((L, 8), dtype=jnp.uint32),
            tree=tree0,
        )
        fmask_root = _node_feature_mask(feature_mask, key, 2 * L, p)
        st = scan_leaf(st, 0, root_hist, G0, H0, C0, jnp.int32(0), fmeta,
                       fmask_root)
        # forced splits first (static plan, unrolled), then best-gain growth
        for s, fp in enumerate(p.forced_plan[: L - 1]):
            st = do_split(st, s, forced=fp)
        st = lax.fori_loop(min(len(p.forced_plan), L - 1), L - 1, body, st)
        return st.tree, st.leaf_id

    if wrap is not None:
        return wrap(grow)
    from ..utils.jitcost import cost_jit
    return cost_jit("grow/fused", jax.jit(grow))
