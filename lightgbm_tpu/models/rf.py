"""Random Forest mode.

Reference: src/boosting/rf.hpp:25-218 — bagging is mandatory, shrinkage is
1.0, every tree fits gradients computed ONCE from a constant boost-from-
average score, each tree absorbs that init score as a bias (AddBias), and
predictions are the average over iterations (average_output).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import check, log_fatal
from .gbdt import GBDT


class RF(GBDT):

    # mutates freshly-grown trees right after each iteration
    _async_trees = False
    average_output = True

    def __init__(self, config, train_set, objective=None):
        check(config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0,
              "RF mode requires bagging "
              "(bagging_freq > 0 and bagging_fraction in (0, 1))")
        if objective is None:
            log_fatal("RF mode does not support custom objective functions")
        super().__init__(config, train_set, objective)
        self.shrinkage_rate = 1.0
        self._fixed_grads = None

    def _boost_from_average(self):
        # RF keeps scores as sums of per-tree predictions; the init score is
        # baked into each tree (AddBias), never into the score buffer.
        self._boosted_from_average = True

    def _rf_gradients(self):
        if self._fixed_grads is None:
            C = self.num_tree_per_iteration
            self._rf_init = [self.objective.boost_from_score(k)
                             for k in range(C)]
            const = jnp.stack([
                jnp.full(self.num_data, v, dtype=jnp.float32)
                for v in self._rf_init])
            g, h = self.objective.get_gradients(
                const if C > 1 else const[0])
            if C == 1:
                g, h = g[None, :], h[None, :]
            self._fixed_grads = (g, h)
        return self._fixed_grads

    def _gradients(self):
        return self._rf_gradients()

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is not None or hess is not None:
            log_fatal("RF mode does not support custom gradients")
        ret = super().train_one_iter()
        if ret:
            return ret
        # fold the init score into the new trees' leaf values
        # (rf.hpp:140-146 AddBias) so averaged predictions are calibrated
        C = self.num_tree_per_iteration
        infos = self.train_set.feature_infos()
        for k in range(C):
            bias = self._rf_init[k]
            if abs(bias) < 1e-15:
                continue
            tree = self.models[(self.iter_ - 1) * C + k]
            if tree.num_leaves > 1:
                tree.leaf_value = tree.leaf_value + bias
                # score buffers must include the bias too
                self.train_score = self.train_score.at[k].add(bias)
                for vscore in self.valid_scores:
                    vscore[k] += bias
        return False

    # eval uses the AVERAGED score (train_score holds the running sum)
    def _eval_score(self, score, metrics):
        denom = max(self.iter_, 1)
        return super()._eval_score(score / denom, metrics)
