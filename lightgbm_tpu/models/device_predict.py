"""Device-side (jittable) ensemble prediction over binned features.

The training-time score update never needs this (the grower returns leaf
assignments directly), but batch prediction of a trained ensemble is itself
a TPU-friendly computation: stack every tree's flat arrays into [T, ...]
tensors and route all rows through all trees with a bounded fori_loop.
Replaces the reference's per-row OpenMP tree walk
(GBDT::PredictRaw, src/boosting/gbdt_prediction.cpp + tree.h:243-288
NumericalDecisionInner) with vectorized gathers.
"""

from __future__ import annotations

from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.binning import MISSING_NAN, MISSING_ZERO
from ..utils.log import LightGBMError


class TreeStack(NamedTuple):
    """Ensemble as stacked arrays; max_nodes = max(num_leaves) - 1."""
    split_feature: jax.Array   # [T, M] i32 (inner/used-feature index)
    threshold_bin: jax.Array   # [T, M] i32
    decision_type: jax.Array   # [T, M] i8-ish i32 bits
    left_child: jax.Array      # [T, M] i32
    right_child: jax.Array     # [T, M] i32
    cat_bitset: jax.Array      # [T, M, 8] u32 (inner bins)
    leaf_value: jax.Array      # [T, L] f32
    num_leaves: jax.Array      # [T] i32
    max_depth: int             # static bound on routing steps


def stack_trees_host(trees: List, num_features: int = -1):
    """Numpy side of :func:`stack_trees`: (fields..., max_depth) without
    the device upload — serve/registry.py packs several models' host
    stacks into shared [M, ...] buffers before a single upload."""
    T = len(trees)
    for i, t in enumerate(trees):
        if not getattr(t, "bins_aligned", True):
            raise LightGBMError(
                f"tree {i} was loaded from a model file and its bin "
                f"thresholds are not aligned with any dataset; remap "
                f"before binned prediction")
    M = max(max(t.num_leaves - 1, 1) for t in trees)
    L = max(max(t.num_leaves, 1) for t in trees)
    sf = np.zeros((T, M), dtype=np.int32)
    tb = np.zeros((T, M), dtype=np.int32)
    dt = np.zeros((T, M), dtype=np.int32)
    lc = np.full((T, M), -1, dtype=np.int32)
    rc = np.full((T, M), -1, dtype=np.int32)
    cb = np.zeros((T, M, 8), dtype=np.uint32)
    lv = np.zeros((T, L), dtype=np.float32)
    nl = np.ones(T, dtype=np.int32)
    depth = 1
    for i, t in enumerate(trees):
        n = t.num_leaves - 1
        nl[i] = t.num_leaves
        lv[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
        if n <= 0:
            continue
        if num_features >= 0 and n > 0 and \
                int(np.max(t.split_feature_inner[:n])) >= num_features:
            raise LightGBMError(
                f"tree {i} splits on feature "
                f"{int(np.max(t.split_feature_inner[:n]))} but the bin "
                f"matrix has only {num_features} features")
        sf[i, :n] = t.split_feature_inner[:n]
        tb[i, :n] = t.threshold_in_bin[:n]
        dt[i, :n] = t.decision_type[:n].astype(np.int32)
        lc[i, :n] = t.left_child[:n]
        rc[i, :n] = t.right_child[:n]
        for node in range(n):
            if dt[i, node] & 1:
                cat_idx = int(t.threshold_in_bin[node])
                words = t.cat_threshold_inner[cat_idx]
                cb[i, node, : min(len(words), 8)] = words[:8]
                tb[i, node] = 0
        depth = max(depth, t.max_depth)
    return sf, tb, dt, lc, rc, cb, lv, nl, int(depth)


def stack_trees(trees: List, num_features: int = -1) -> TreeStack:
    """Stack host Tree objects (with inner thresholds) into a TreeStack.

    ``num_features``, when given, validates that every split references a
    feature inside the bin matrix (out-of-range splits would otherwise
    become silent clipped gathers inside the jitted predict).
    """
    sf, tb, dt, lc, rc, cb, lv, nl, depth = stack_trees_host(trees,
                                                             num_features)
    return TreeStack(jnp.asarray(sf), jnp.asarray(tb), jnp.asarray(dt),
                     jnp.asarray(lc), jnp.asarray(rc), jnp.asarray(cb),
                     jnp.asarray(lv), jnp.asarray(nl), int(depth))


def _tree_leaves(stack: TreeStack, tree_idx, bins: jax.Array,
                 fmeta_num_bin: jax.Array, fmeta_default_bin: jax.Array,
                 feat_group, feat_offset) -> jax.Array:
    """Leaf index of every row under tree ``tree_idx``: [N] i32."""
    n = bins.shape[0]
    sf = stack.split_feature[tree_idx]
    tb = stack.threshold_bin[tree_idx]
    dt = stack.decision_type[tree_idx]
    lc = stack.left_child[tree_idx]
    rc = stack.right_child[tree_idx]
    cb = stack.cat_bitset[tree_idx]

    def step(_, node):
        internal = node >= 0
        safe = jnp.maximum(node, 0)
        f = sf[safe]
        col = f if feat_group is None else feat_group[f]
        fv = jnp.take_along_axis(
            bins, col[:, None].astype(jnp.int32), axis=1)[:, 0] \
            .astype(jnp.int32)
        if feat_group is not None:
            off = feat_offset[f]
            in_range = (fv >= off) & (fv < off + fmeta_num_bin[f])
            fv = jnp.where(in_range, fv - off, fmeta_default_bin[f])
        d = dt[safe]
        is_cat = (d & 1) > 0
        mt = (d >> 2) & 3
        dl = (d & 2) > 0
        is_missing = (((mt == MISSING_ZERO)
                       & (fv == fmeta_default_bin[f]))
                      | ((mt == MISSING_NAN)
                         & (fv == fmeta_num_bin[f] - 1)))
        num_left = jnp.where(is_missing, dl, fv <= tb[safe])
        # negative bin = "category never seen in training" sentinel from
        # predict-time binning (training bins are always >= 0): the host
        # float walk sends unseen/negative/NaN categories right
        word = cb[safe, jnp.clip(fv // 32, 0, 7)]
        cat_left = (((word >> (fv % 32).astype(jnp.uint32)) & 1) > 0) \
            & (fv >= 0)
        go_left = jnp.where(is_cat, cat_left, num_left)
        nxt = jnp.where(go_left, lc[safe], rc[safe])
        return jnp.where(internal, nxt, node)

    # single-leaf trees start terminal at node -1 (= leaf ~(-1) = 0)
    start = jnp.where(stack.num_leaves[tree_idx] <= 1,
                      jnp.full(n, -1, dtype=jnp.int32),
                      jnp.zeros(n, dtype=jnp.int32))
    node = lax.fori_loop(0, stack.max_depth + 1, step, start)
    return jnp.maximum(~node, 0)


def predict_binned_ensemble(stack: TreeStack, bins: jax.Array,
                            fmeta_num_bin: jax.Array,
                            fmeta_default_bin: jax.Array,
                            feat_group: jax.Array = None,
                            feat_offset: jax.Array = None) -> jax.Array:
    """Sum of per-tree raw outputs for binned rows: [N] f32.

    For EFB-bundled datasets (core/bundle.py) pass ``feat_group`` /
    ``feat_offset`` ([F] i32): feature f's bin lives in column
    ``feat_group[f]`` at ``feat_offset[f] + bin``, with out-of-range column
    values meaning "f at its default bin"."""
    n = bins.shape[0]

    def route_one_tree(total, tree_idx):
        leaf = _tree_leaves(stack, tree_idx, bins, fmeta_num_bin,
                            fmeta_default_bin, feat_group, feat_offset)
        return total + stack.leaf_value[tree_idx][leaf], None

    init = jnp.zeros(n, dtype=jnp.float32)
    total, _ = lax.scan(route_one_tree, init,
                        jnp.arange(stack.split_feature.shape[0]))
    return total


def predict_binned_leaves(stack: TreeStack, bins: jax.Array,
                          fmeta_num_bin: jax.Array,
                          fmeta_default_bin: jax.Array,
                          feat_group: jax.Array = None,
                          feat_offset: jax.Array = None) -> jax.Array:
    """Per-tree leaf assignment for binned rows: [T, N] i32.

    Routing is identical to :func:`predict_binned_ensemble`; returning
    the leaf INDEX instead of the f32 leaf-value sum lets callers gather
    the float64 leaf values on the host and accumulate tree-by-tree in
    the exact order (and precision) of the host walk
    (``GBDT._raw_predict``) — device-routed predictions become
    bit-identical to the host fallback instead of merely close."""

    def route_one_tree(_, tree_idx):
        leaf = _tree_leaves(stack, tree_idx, bins, fmeta_num_bin,
                            fmeta_default_bin, feat_group, feat_offset)
        return 0, leaf

    _, leaves = lax.scan(route_one_tree, 0,
                         jnp.arange(stack.split_feature.shape[0]))
    return leaves
