"""GBDT: the boosting driver.

Reference: src/boosting/gbdt.{h,cpp} — Init (gbdt.cpp:49), TrainOneIter
(:450: boost-from-average -> GetGradients -> Bagging -> per-class tree train
-> RenewTreeOutput -> shrinkage -> score update -> constant-tree handling),
Bagging (:182-334), RollbackOneIter (:553), train/valid metric evaluation
(:578-660), feature importances.

TPU orchestration: the per-iteration hot path stays on device — gradients
(objective jnp fn), tree growth (fused grower), and the training-score update
(``score += leaf_value[leaf_id]`` gather).  Host work per iteration is O(1)
scalars plus optional leaf renewal / validation-set prediction.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..core.binning import MISSING_NAN, MISSING_ZERO
from ..core.dataset import TpuDataset
from ..ops.split import FeatureMeta, SplitParams
from ..utils.faults import FAULTS, InjectedFault, oom_error
from ..utils.jitcost import cost_jit
from ..utils.log import (LightGBMError, check, log_fatal, log_info,
                         log_warning)
from ..utils.phase import GLOBAL_TIMER as _PHASES, step_annotation
from ..utils.telemetry import HEALTH, TELEMETRY
from .grower import (GrowerParams, _pack_tree_device, fetch_tree_arrays,
                     fetch_tree_chunk, make_grow_tree, unpack_tree_buffers)
from .grower_seg import print_seg_stats, seg_stats_enabled
from .tree import Tree


class _PendingChunk(NamedTuple):
    """A chunk of ``length`` dispatched-but-unfetched iterations: the
    scan's stacked [T, C, len_ints]/[T, C, len_floats] device buffers,
    materialized host-side in two transfers at the chunk boundary.
    ``mvals`` is the in-scan evaluation's stacked [T, n_cols] metric
    rows (None when no eval program rides the chunk); ``wall_s`` is the
    chunk dispatch's host wall window (wall-to-ready under
    device_timing), carried into the health stream's iter records."""
    ints_all: jax.Array
    floats_all: jax.Array
    shrinkage: float
    length: int
    mvals: Optional[jax.Array] = None
    wall_s: Optional[float] = None


# batch-predict routing seams, one per static routing depth: the
# TreeStack's max_depth is the fori_loop bound and must stay a python
# int for AOT compilation, so it cannot ride through the jit arguments
_ROUTE_SEAMS: Dict[int, Any] = {}


def _route_seam(max_depth: int):
    fn = _ROUTE_SEAMS.get(max_depth)
    if fn is None:
        from .device_predict import predict_binned_leaves

        def leaves_fn(stack, bins, num_bin, default_bin):
            return predict_binned_leaves(
                stack._replace(max_depth=max_depth), bins, num_bin,
                default_bin)

        fn = cost_jit(f"predict/route[d{max_depth}]", jax.jit(leaves_fn))
        _ROUTE_SEAMS[max_depth] = fn
    return fn


def _maybe_print_seg_stats(stats) -> None:
    """Render a grower's counter output when LIGHTGBM_TPU_SEG_STATS asks
    for it (stats is () for growers that emit none, e.g. the fused one).
    The rows also feed the telemetry counters; fetching the stats vector
    blocks on the device, so recording stays gated on the same env knob
    that opts into per-iteration synchronization."""
    if stats and seg_stats_enabled():
        from .grower_seg import SEG_STATS_SLOTS
        rows = np.asarray(stats[0]).reshape(-1, SEG_STATS_SLOTS)
        TELEMETRY.counter_add("seg/scanned_blocks",
                              int(rows[:, 0].sum()))
        TELEMETRY.counter_add("seg/compactions", int(rows[:, 1].sum()))
        TELEMETRY.counter_add("seg/grid_steps", int(rows[:, 2].sum()))
        # quantization / staging counters stay 0 on paths that never
        # quantize or stage — record only live events so trace_report's
        # hist section renders n/a instead of misleading zero rates
        if rows[:, 5].sum():
            TELEMETRY.counter_add("hist/fused_k_rounds",
                                  int(rows[:, 5].sum()))
        if rows[:, 6].sum():
            TELEMETRY.counter_add("hist/quant_rescales", len(rows))
            TELEMETRY.counter_add("hist/quant_clips",
                                  int(rows[:, 6].sum()))
        if rows[:, 8].sum():
            TELEMETRY.counter_add("hist/stage_hits",
                                  int(rows[:, 7].sum()))
            TELEMETRY.counter_add("hist/stage_lookups",
                                  int(rows[:, 8].sum()))
        print_seg_stats(stats[0])


def _auto_frontier_k(cfg, num_columns: int, num_bins: int) -> int:
    """Frontier batch width: explicit tpu_frontier_width wins; the auto
    width caps the batch at ~num_leaves/16 (rounded up) so small trees
    stay near strict best-first (K=16 on a 31-leaf tree is level-wise
    growth and measurably hurts fit) while 255-leaf benchmark trees get
    the full 16-leaf / 128-channel MXU tile.  Shared by the serial and
    data-parallel frontier learners so they always grow the same-width
    frontier."""
    if cfg.tpu_frontier_width > 0:
        TELEMETRY.gauge_set("grow/frontier_k", int(cfg.tpu_frontier_width))
        return cfg.tpu_frontier_width
    from ..ops.pallas_histogram import frontier_width
    k = min(frontier_width(num_columns, num_bins),
            max(1, -(-max(2, cfg.num_leaves) // 16)))
    TELEMETRY.gauge_set("grow/frontier_k", int(k))
    return k


def _round_up_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _build_forced_plan(train_set: TpuDataset, filename: str,
                       num_leaves: int) -> tuple:
    """forcedsplits_filename JSON -> static BFS plan of
    (leaf, inner_feature, threshold_bin) triples (ForceSplits,
    serial_tree_learner.cpp:642: breadth-first, left child keeps the
    parent's leaf id, right child takes the new one)."""
    import json
    from collections import deque
    with open(filename) as fh:
        root = json.load(fh)
    plan = []
    q = deque([(root, 0)])
    while q and len(plan) < num_leaves - 1:
        node, leaf = q.popleft()
        if not isinstance(node, dict) or "feature" not in node:
            continue
        real_f = int(node["feature"])
        inner = train_set.inner_feature_index(real_f)
        if inner < 0:
            log_warning(f"forced split on unused feature {real_f}; skipped")
            continue
        thr = float(node["threshold"])
        t_bin = int(np.asarray(train_set.bin_mappers[real_f].value_to_bin(
            np.asarray([thr], dtype=np.float64)))[0])
        step = len(plan)
        plan.append((int(leaf), int(inner), t_bin))
        if isinstance(node.get("left"), dict):
            q.append((node["left"], leaf))
        if isinstance(node.get("right"), dict):
            q.append((node["right"], step + 1))
    return tuple(plan)


def build_feature_meta(dataset: TpuDataset, config=None,
                       used_in_split=None) -> FeatureMeta:
    infos = dataset.feature_infos()
    F = len(infos)

    def per_feature(vals, default):
        """Real-feature-indexed config list -> [F] inner-feature array."""
        out = np.full(F, default, dtype=np.float64)
        if vals:
            for j, real in enumerate(dataset.used_feature_indices):
                if int(real) < len(vals):
                    out[j] = float(vals[int(real)])
        return jnp.asarray(out, dtype=jnp.float32)

    cegb_coupled = cegb_lazy = used0 = None
    if config is not None and (config.cegb_penalty_feature_coupled
                               or config.cegb_penalty_feature_lazy):
        cegb_coupled = per_feature(config.cegb_penalty_feature_coupled, 0.0)
        cegb_lazy = per_feature(config.cegb_penalty_feature_lazy, 0.0)
        used0 = jnp.asarray(used_in_split if used_in_split is not None
                            else np.zeros(F), dtype=jnp.float32)
    feat_group = feat_offset = gather_idx = None
    if dataset.bundle is not None:
        # static [F, Bf] gather map from the flattened [G * Bg] group
        # histogram; Bg/Bf are the pow2-padded histogram axes the grower
        # actually allocates (GBDT.reset_train_data uses the same rounding)
        Bg = _round_up_pow2(max(dataset.max_column_bin, 2))
        Bf = _round_up_pow2(max(dataset.max_num_bin, 2))
        gi = np.full((F, Bf), -1, dtype=np.int32)
        for j, info in enumerate(infos):
            gi[j, : info.num_bin] = (info.group * Bg + info.offset
                                     + np.arange(info.num_bin))
        feat_group = jnp.asarray([i.group for i in infos], dtype=jnp.int32)
        feat_offset = jnp.asarray([i.offset for i in infos],
                                  dtype=jnp.int32)
        gather_idx = jnp.asarray(gi)
    return FeatureMeta(
        num_bin=jnp.asarray([i.num_bin for i in infos], dtype=jnp.int32),
        missing_type=jnp.asarray([i.missing_type for i in infos],
                                 dtype=jnp.int32),
        default_bin=jnp.asarray([i.default_bin for i in infos],
                                dtype=jnp.int32),
        is_cat=jnp.asarray([i.is_categorical for i in infos], dtype=bool),
        monotone=jnp.asarray([i.monotone for i in infos], dtype=jnp.int32),
        penalty=jnp.asarray([i.penalty for i in infos], dtype=jnp.float32),
        cegb_coupled=cegb_coupled,
        cegb_lazy=cegb_lazy,
        cegb_used0=used0,
        feat_group=feat_group,
        feat_offset=feat_offset,
        gather_idx=gather_idx,
    )


def _add_tree_score_core(score, leaf_values, leaf_id):
    return score + leaf_values[leaf_id]


def _apply_tree_score_core(score, leaf_values, leaf_id, shrinkage):
    """Device-side score update straight from the grower's output — no host
    round-trip in the training loop (shrinkage folded in here; the stored
    model applies it at materialization)."""
    return score + shrinkage * leaf_values[leaf_id]


_add_tree_score = cost_jit("score/add", jax.jit(_add_tree_score_core))
_apply_tree_score = cost_jit("score/apply", jax.jit(_apply_tree_score_core))

# one-scalar finiteness reduce over the boosted scores (check_nonfinite
# guardrail): the device does the whole reduction, the host fetches one
# bool — run OUTSIDE any transfer guard wrapping the chunk dispatch
_all_finite = jax.jit(lambda x: jnp.isfinite(x).all())


def _grad_stats_core(grads, hesss):
    """Per-class gradient/hessian diagnostics for the health stream:
    [C, 8] f32 columns [gmin, gmax, g_l2, g_nonfinite, hmin, hmax,
    h_l2, h_nonfinite].  Pure jnp so the chunk scan body can inline it
    (one extra stacked scan output, zero extra dispatches) while the
    per-iteration paths call the jitted wrapper below — the reductions
    lower identically either way, keeping the records bit-identical at
    any chunk size (the same property the chunked trees rely on)."""
    def one(x):
        nonfinite = jnp.sum(~jnp.isfinite(x), axis=1).astype(jnp.float32)
        safe = jnp.where(jnp.isfinite(x), x, 0.0)
        return (jnp.min(safe, axis=1), jnp.max(safe, axis=1),
                jnp.sqrt(jnp.sum(safe * safe, axis=1)), nonfinite)
    return jnp.stack(one(grads) + one(hesss), axis=1)


_grad_stats = cost_jit("health/grad_stats", jax.jit(_grad_stats_core))


def _route_tree_rows(arrays, vbins, fmeta, depth_bound: int):
    """Per-row leaf values of one freshly-grown device tree over a
    row-major [Nv, G] binned matrix: the in-scan evaluation's valid-set
    score update (pure jnp, traced inside the chunk scan body).

    Same routing semantics as models/device_predict.route_one_tree, but
    over the grower's TreeArrays fields (pre-packing, per-feature
    missing types from fmeta instead of per-node decision bits) so the
    scan never needs host Tree objects.  Single-leaf trees start
    terminal at node -1 (= leaf ~(-1) = 0, whose value is 0), matching
    the train-score update's unconditional add."""
    sf, tb = arrays.split_feature, arrays.threshold_bin
    dl, ic, cb = arrays.default_left, arrays.is_cat, arrays.cat_bitset
    lc, rc = arrays.left_child, arrays.right_child
    n = vbins.shape[0]

    def step(_, node):
        internal = node >= 0
        safe = jnp.maximum(node, 0)
        f = sf[safe]
        col = f if fmeta.feat_group is None else fmeta.feat_group[f]
        fv = jnp.take_along_axis(
            vbins, col[:, None].astype(jnp.int32), axis=1)[:, 0] \
            .astype(jnp.int32)
        if fmeta.feat_group is not None:
            off = fmeta.feat_offset[f]
            in_range = (fv >= off) & (fv < off + fmeta.num_bin[f])
            fv = jnp.where(in_range, fv - off, fmeta.default_bin[f])
        mt = fmeta.missing_type[f]
        is_missing = (((mt == MISSING_ZERO)
                       & (fv == fmeta.default_bin[f]))
                      | ((mt == MISSING_NAN)
                         & (fv == fmeta.num_bin[f] - 1)))
        num_left = jnp.where(is_missing, dl[safe], fv <= tb[safe])
        word = cb[safe, jnp.clip(fv // 32, 0, 7)]
        cat_left = ((word >> (fv % 32).astype(jnp.uint32)) & 1) > 0
        go_left = jnp.where(ic[safe], cat_left, num_left)
        nxt = jnp.where(go_left, lc[safe], rc[safe])
        return jnp.where(internal, nxt, node)

    start = jnp.where(arrays.num_leaves <= 1,
                      jnp.full(n, -1, dtype=jnp.int32),
                      jnp.zeros(n, dtype=jnp.int32))
    node = jax.lax.fori_loop(0, depth_bound, step, start)
    return arrays.leaf_value[jnp.maximum(~node, 0)]


def _is_oom_error(e: BaseException) -> bool:
    """RESOURCE_EXHAUSTED-shaped device failures (real XlaRuntimeError
    allocation failures and injected chunk/oom faults) that the chunked
    loop may retry at a smaller chunk size."""
    msg = str(e)
    if ("RESOURCE_EXHAUSTED" not in msg
            and "out of memory" not in msg.lower()):
        return False
    return (isinstance(e, InjectedFault)
            or type(e).__name__ in ("XlaRuntimeError", "InternalError"))


# proactive-admission headroom: start in the host-spill tier when the
# estimated working set exceeds this fraction of the reported HBM
_ADMIT_FRACTION = 0.9


def working_set_bytes(num_data: int, num_columns: int, *,
                      num_tree_per_iteration: int = 1,
                      layout: Tuple[str, int, bool] = ("rows", 0, False),
                      itemsize: int = 1) -> int:
    """The working-set arithmetic shared by the internal pre-dispatch
    admission check (``GBDT._estimate_working_set``) and the public
    :func:`estimate_working_set`: the bin matrix in its device layout
    (``("T", row_multiple, packed4)`` pads rows to whole blocks and
    packs two sub-16-bin columns per byte; ``("rows", 0, False)`` is the
    plain row-major matrix), the f32 boosting state (scores, grads,
    hessians per class, bag weights, leaf ids), plus the largest CostJit
    ``memory_analysis`` working set already on record."""
    num_data, f = int(num_data), int(num_columns)
    kind, rm, packed4 = layout
    if kind == "T":
        npad_rows = num_data + ((-num_data) % max(1, int(rm)))
        mat_bytes = (-(-f // 2) * npad_rows if packed4
                     else f * npad_rows * int(itemsize))
    else:
        mat_bytes = num_data * f * int(itemsize)
    state_bytes = 4 * num_data * (3 * int(num_tree_per_iteration) + 2)
    return mat_bytes + state_bytes + TELEMETRY.cost_working_set()


def estimate_working_set(config, data_shape, *,
                         num_bins: Optional[int] = None) -> int:
    """Estimated training working set in bytes for ``config`` over a
    ``(num_data, num_columns)`` dataset — BEFORE constructing a dataset
    or booster, so admission control (serve registry, the sched plane's
    HBM gate, ``data_in_hbm=auto``) and users share one number.

    ``config`` is a :class:`~lightgbm_tpu.config.Config` or a params
    dict.  ``num_bins`` defaults to ``max_bin`` (the post-binning upper
    bound; a constructed dataset may resolve fewer bins and a slightly
    smaller matrix).  The single-device bin layout is resolved the same
    way training resolves it: the pallas kernel's feature-major padded/
    packed layout when the shape supports it, the row-major matrix
    otherwise.  A warm process adds its compiled programs' recorded
    temp+argument+output bytes; a cold one contributes 0.  See
    docs/TUNING.md (working-set budgeting)."""
    if not isinstance(config, Config):
        config = Config.from_params(dict(config))
    num_data, num_columns = (int(x) for x in tuple(data_shape))
    if num_data < 1 or num_columns < 1:
        raise LightGBMError(
            f"estimate_working_set needs a (num_data, num_columns) "
            f"shape with both >= 1, got {data_shape!r}")
    from ..objective import create_objective
    objective = create_objective(config)
    C = int(getattr(objective, "num_tree_per_iteration", 1) or 1)
    bins = int(num_bins) if num_bins else max(2, int(config.max_bin))
    layout: Tuple[str, int, bool] = ("rows", 0, False)
    choice = str(config.tpu_histogram_backend).strip().lower()
    if (choice != "onehot" and not config.gpu_use_dp
            and not config.tpu_double_precision):
        from ..ops.pallas_histogram import pick_block_rows, supported
        nb2 = _round_up_pow2(max(bins, 2))
        if supported(num_columns, nb2, np.dtype(np.uint8)):
            rb = (int(config.tpu_row_chunk) if config.tpu_row_chunk > 0
                  else pick_block_rows(num_columns, bins, num_data))
            layout = ("T", rb, bins <= 16)
    return working_set_bytes(num_data, num_columns,
                             num_tree_per_iteration=C, layout=layout)


class GBDT:
    """Gradient Boosted Decision Trees (boosting='gbdt')."""

    def __init__(self, config: Config, train_set: Optional[TpuDataset],
                 objective=None):
        self.config = config
        self.objective = objective
        # bind the config's telemetry level (env wins; see
        # utils/telemetry.py) and hook jax compile/retrace/cache events
        # before any tracing happens
        TELEMETRY.set_config_level(getattr(config, "telemetry_level", 1))
        TELEMETRY.set_config_timing(getattr(config, "device_timing",
                                            False))
        if TELEMETRY.level >= 1:
            TELEMETRY.install_jax_listeners()
        # arm fault injection for this run (env spec wins per-site) with
        # fresh occurrence counters — same lifecycle as the telemetry
        # level binding above; the collective retry policy binds at the
        # same point so every entry path (engine/sklearn/CLI) gets it
        FAULTS.configure(getattr(config, "fault_injection", ""))
        from ..parallel import network as _network
        _network.configure(config)
        self.train_set: Optional[TpuDataset] = None
        self._models: List[Tree] = []           # flat: iter-major, class-minor
        # finished trees whose device->host transfer is still in flight,
        # in iteration order: (first_iter, payload, grad_stats) where
        # payload is [(ints_dev, floats_dev, shrinkage)] * C or a
        # _PendingChunk, and grad_stats is the device-side health
        # diagnostics ([C, 8] / [T, C, 8]) or None when no stream runs
        self._pending: List[tuple] = []
        self._stop_flag = False
        self.num_tree_per_iteration = (
            objective.num_tree_per_iteration if objective is not None
            else max(1, config.num_class))
        self.shrinkage_rate = config.learning_rate
        self.iter_ = 0
        self.init_scores: List[float] = [0.0] * self.num_tree_per_iteration
        self.valid_sets: List[Tuple[str, TpuDataset]] = []
        self.valid_scores: List[np.ndarray] = []
        self.metrics = []
        self.valid_metrics: List[list] = []
        self.best_iter = -1
        self.feature_names: List[str] = []
        self._grow_fn = None
        self.max_feature_idx = 0
        self._inscan_evals: List[tuple] = []
        if train_set is not None:
            self.reset_train_data(train_set)

    # ----------------------------------------------------------------- setup
    def _resolve_hist_backend(self, parallel: bool) -> str:
        """auto -> pallas on TPU when the kernel supports the shape
        (ops/pallas_histogram.supported); parallel learners and explicit
        double-precision requests stay on the XLA one-hot path."""
        cfg = self.config
        choice = str(cfg.tpu_histogram_backend).strip().lower()
        if choice == "onehot":
            return "onehot"
        if choice == "pallas" or choice == "auto":
            import jax
            from ..ops.pallas_histogram import supported
            ok = (not parallel
                  and not cfg.gpu_use_dp and not cfg.tpu_double_precision
                  and supported(self.train_set.num_columns,
                                _round_up_pow2(
                                    max(self.train_set.max_column_bin, 2)),
                                self.train_set.binned.dtype))
            if choice == "pallas":
                if not ok:
                    from ..utils.log import log_warning as _warn
                    _warn("tpu_histogram_backend=pallas unsupported for "
                          "this dataset/learner; falling back to onehot")
                    return "onehot"
                return "pallas"
            return "pallas" if (ok and jax.default_backend() == "tpu") \
                else "onehot"
        return "onehot"

    def reset_train_data(self, train_set: TpuDataset) -> None:
        if self.train_set is not None and self.train_set is not train_set:
            # the reference CheckAligns on training-data reset too
            # (gbdt.cpp:827); existing trees' bin-space thresholds would
            # silently mis-route on differently-binned data
            self.train_set.check_align(train_set)
            # settle async-pipeline trees against the OLD score buffers
            # before they are replaced (the flush may rollback a stopped
            # iteration, which must not touch the new buffers)
            self._flush_pending()
        self.train_set = train_set
        self.num_data = train_set.num_data
        self.feature_names = list(train_set.feature_names)
        self.max_feature_idx = train_set.num_total_features - 1
        self._cegb_used = np.zeros(train_set.num_used_features,
                                   dtype=np.float64)
        self.fmeta = build_feature_meta(train_set, self.config,
                                        self._cegb_used)
        self._row_pad = 0
        # histogram bin axis is over physical COLUMNS (EFB groups); the
        # per-feature scan axis comes from fmeta.gather_idx when bundled
        self.num_bins = _round_up_pow2(max(train_set.max_column_bin, 2))
        cfg = self.config
        # Resolve the parallel layout FIRST so the histogram backend is
        # chosen for the learner that actually runs: a parallel request on
        # a single-device mesh falls back to the serial learner and must
        # keep the pallas/segment fast path (ADVICE.md round 1).
        tl = str(cfg.tree_learner).strip().lower()
        parallel = tl in ("data", "data_parallel", "feature",
                          "feature_parallel", "voting", "voting_parallel")
        mesh = None
        if parallel:
            from ..parallel import network
            # num_machines=1 (the default) means "use every device on the
            # mesh" — the TPU runtime already knows the slice topology
            mesh = network.init(cfg.num_machines if cfg.num_machines > 1
                                else 0)
            if mesh.devices.size <= 1:
                log_warning("Only one device available; using the serial "
                            "tree learner")
                parallel = False
                mesh = None
        # data-parallel keeps the segment fast path: rows shard cleanly and
        # histograms reduce linearly; feature/voting (and an explicit
        # fused-impl request) stay on the fused onehot grower, whose
        # row-major sharded layout is incompatible with the feature-major
        # pallas bins
        impl = str(cfg.tpu_tree_impl).strip().lower()
        # forced splits are a fused-grower feature: resolve them BEFORE the
        # layout choice, because a forced data-parallel run must fall back
        # to the fused grower's ROW-major sharded layout (a feature-major
        # pallas matrix sharded on axis 0 would split features, not rows)
        forced_plan = ()
        if cfg.forcedsplits_filename:
            if parallel and tl not in ("data", "data_parallel"):
                # the forced path reads this shard's leaf histogram without
                # a merge; feature/voting shards hold incomplete histograms
                # (column stripes / elected subsets), so forced stats would
                # diverge across devices.  Data-parallel psums full
                # histograms and is safe.
                log_warning("forcedsplits_filename is not supported by the "
                            "feature/voting-parallel learners; ignoring it")
            else:
                forced_plan = _build_forced_plan(train_set,
                                                 cfg.forcedsplits_filename,
                                                 max(2, cfg.num_leaves))
        data_mode = (tl in ("data", "data_parallel") and impl != "fused"
                     and not forced_plan)
        # feature-/voting-parallel on the O(leaf) growers are OPT-IN via
        # an explicit tpu_tree_impl (the auto default keeps the fused
        # grower those modes always had); every reference parallel
        # learner inherits the serial O(leaf) machinery
        # (feature_parallel_tree_learner.cpp:74-75)
        feature_mode = (tl in ("feature", "feature_parallel")
                        and impl in ("segment", "frontier")
                        and not forced_plan)
        voting_mode = (tl in ("voting", "voting_parallel")
                       and impl in ("segment", "frontier")
                       and not forced_plan)
        oleaf_mode = data_mode or feature_mode or voting_mode
        D = int(mesh.devices.size) if parallel else 1
        backend = self._resolve_hist_backend(parallel and not oleaf_mode)
        rb = 0
        self._packed4 = False
        if backend == "pallas":
            from ..ops.pallas_histogram import pick_block_rows
            # feature-parallel replicates rows (only split FINDING is
            # sharded); rows-sharded modes pad to whole blocks per shard
            rows_D = 1 if (parallel and feature_mode) else D
            rb = (cfg.tpu_row_chunk if cfg.tpu_row_chunk > 0 else
                  pick_block_rows(train_set.num_columns,
                                  self.num_bins,
                                  -(-self.num_data // rows_D)))
            # each shard's row count must be a whole number of blocks
            # 4-bit packing (Dense4bitsBin equivalent) for <=16-bin
            # datasets: two columns per byte halves the bin-stream DMA
            # and the compaction sort payload.  Feature-parallel column
            # stripes slice physical rows, so they keep unpacked bins
            # (a stripe boundary inside a packed byte would split it).
            self._packed4 = self.num_bins <= 16 and not (
                parallel and feature_mode)
            self._bins_layout = ("T", rb * rows_D, self._packed4)
        else:
            self._bins_layout = ("rows", 0, False)
        # memory-tier resolution (docs/ROBUSTNESS.md, rung 4 of the
        # recovery ladder) BEFORE any upload: a run whose working set
        # never fit starts out-of-core instead of crash-and-retrying
        self._spill_store = None
        self._bins_window = None
        self._bins_hold = 0
        self._spill_unavail = None
        self._data_tier = self._resolve_data_tier(parallel)
        if self._data_tier == "spill":
            self._activate_spill(train_set)
        else:
            try:
                # the resident upload is itself a bin-matrix h2d
                # transfer, so it hosts the oocore/h2d injection site:
                # "the matrix never fit" becomes deterministically
                # reproducible
                if FAULTS.enabled:
                    FAULTS.maybe_raise("oocore/h2d", oom_error)
                self._upload_resident_bins(train_set)
            except Exception as e:
                if (not _is_oom_error(e)
                        or self._spill_blocked_reason(parallel)):
                    raise
                TELEMETRY.fault_event(
                    "oom_spill", site="oocore/h2d", iteration=self.iter_,
                    detail="resident bin-matrix upload hit "
                           "RESOURCE_EXHAUSTED; spilling to host")
                log_warning("uploading the bin matrix to HBM failed with "
                            "RESOURCE_EXHAUSTED; continuing in the "
                            "host-spill (out-of-core) tier")
                self._data_tier = "spill"
                TELEMETRY.set_data_tier("spill")
                self._activate_spill(train_set)
        # rb threads through as the single block size for BOTH the bin
        # matrix padding and every kernel launch (grower + segment grower);
        # re-picking it at a kernel call site could desync from the padding
        infos = train_set.feature_infos()
        use_monotone = any(i.monotone != 0 for i in infos)
        use_cegb_coupled = bool(cfg.cegb_penalty_feature_coupled)
        use_cegb_lazy = bool(cfg.cegb_penalty_feature_lazy)
        if use_cegb_lazy and parallel:
            log_warning("cegb_penalty_feature_lazy is not supported by the "
                        "distributed learners; ignoring it")
            use_cegb_lazy = False
        self.grower_params = GrowerParams(
            num_leaves=max(2, cfg.num_leaves),
            max_depth=cfg.max_depth,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            row_chunk=rb,
            hist_backend=backend,
            packed4=self._packed4,
            num_columns=train_set.num_columns,
            use_monotone=use_monotone,
            cegb_tradeoff=float(cfg.cegb_tradeoff),
            cegb_penalty_split=float(cfg.cegb_penalty_split),
            use_cegb_coupled=use_cegb_coupled,
            use_cegb_lazy=use_cegb_lazy,
            forced_plan=forced_plan,
            split=SplitParams(
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                max_delta_step=cfg.max_delta_step,
                min_data_in_leaf=float(cfg.min_data_in_leaf),
                min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
                min_gain_to_split=cfg.min_gain_to_split,
                cat_smooth=cfg.cat_smooth, cat_l2=cfg.cat_l2,
                max_cat_threshold=cfg.max_cat_threshold,
                max_cat_to_onehot=cfg.max_cat_to_onehot,
                min_data_per_group=cfg.min_data_per_group,
                has_cat=any(i.is_categorical for i in infos)))
        # forced splits and CEGB-lazy are fused-grower features
        self._use_segment = (backend == "pallas" and impl != "fused"
                             and not forced_plan and not use_cegb_lazy)
        if impl in ("segment", "frontier") and not self._use_segment:
            if parallel:
                log_warning(f"tpu_tree_impl={impl} needs the pallas "
                            "backend under this parallel layout; using "
                            "the fused grower")
            else:
                log_warning(f"tpu_tree_impl={impl} requires the pallas "
                            "histogram backend (and no forced splits / "
                            "CEGB-lazy); using the fused grower")
        bundle_fg = (train_set.bundle.feat_group
                     if train_set.bundle is not None else None)
        if parallel and self._use_segment and (feature_mode or voting_mode):
            from ..parallel.learners import (
                make_feature_parallel_oleaf_grower,
                make_voting_parallel_oleaf_grower)
            kw = dict(
                feat_group=bundle_fg, impl=impl,
                batch_k=(_auto_frontier_k(cfg, train_set.num_columns,
                                          self.num_bins)
                         if impl == "frontier" else 0),
                gain_ratio=float(cfg.tpu_frontier_gain_ratio))
            if feature_mode:
                self._grow_fn = make_feature_parallel_oleaf_grower(
                    self.num_bins, self.grower_params, mesh, rb,
                    train_set.num_columns,
                    column_bins=train_set.column_bins, **kw)
            else:
                self._grow_fn = make_voting_parallel_oleaf_grower(
                    self.num_bins, self.grower_params, mesh, rb,
                    train_set.num_columns, top_k=cfg.top_k, **kw)
            self._mesh = mesh
        elif parallel and self._use_segment and impl == "frontier":
            from ..parallel.learners import (
                make_data_parallel_frontier_grower)
            k = _auto_frontier_k(cfg, train_set.num_columns, self.num_bins)
            self._grow_fn = make_data_parallel_frontier_grower(
                self.num_bins, self.grower_params, mesh, rb,
                train_set.num_columns, feat_group=bundle_fg, batch_k=k,
                gain_ratio=float(cfg.tpu_frontier_gain_ratio))
            self._mesh = mesh
        elif parallel and self._use_segment:
            from ..parallel.learners import make_data_parallel_segment_grower
            self._grow_fn = make_data_parallel_segment_grower(
                self.num_bins, self.grower_params, mesh, rb,
                train_set.num_columns, feat_group=bundle_fg)
            self._mesh = mesh
        elif parallel:
            from ..parallel.learners import make_parallel_grower
            # pad rows to a multiple of the mesh size; pad rows carry
            # zero membership weight so they never contribute
            pad = (-self.num_data) % D
            if pad:
                self.bins = jnp.pad(self.bins, ((0, pad), (0, 0)))
                self._row_pad = pad
            self._grow_fn = make_parallel_grower(
                self.num_bins, self.grower_params, mesh, tl,
                top_k=cfg.top_k, num_columns=train_set.num_columns,
                feat_group=bundle_fg,
                column_bins=train_set.column_bins)
            self._mesh = mesh
        elif self._use_segment and impl == "frontier":
            # batched best-first: K splits per round, one K-leaf batched
            # histogram kernel whose matmul output fills the 128-wide MXU
            # tile (grower_frontier.py); opt-in — trees can differ
            # slightly from strict best-first when K > 1
            from .grower_frontier import make_grow_tree_frontier
            self._grow_fn = make_grow_tree_frontier(
                self.num_bins, self.grower_params, rb,
                batch_k=_auto_frontier_k(cfg, train_set.num_columns,
                                         self.num_bins),
                gain_ratio=float(cfg.tpu_frontier_gain_ratio))
        elif self._use_segment and impl in ("auto", "segment"):
            from .grower_seg import make_grow_tree_segment
            self._grow_fn = make_grow_tree_segment(
                self.num_bins, self.grower_params, rb)
        else:
            self._grow_fn = make_grow_tree(self.num_bins, self.grower_params)
        C = self.num_tree_per_iteration
        if self.iter_ > 0:
            # mid-boosting swap (GBDT::ResetTrainingData): the score buffer
            # must equal the existing model's raw prediction on the NEW
            # rows (per-row init scores folded in by the replay), or the
            # next iteration boosts against a zero model
            self.train_score = jnp.asarray(
                self._replay_model_scores(train_set), dtype=jnp.float32)
        elif train_set.metadata.init_score is not None:
            init = np.asarray(train_set.metadata.init_score, dtype=np.float32)
            self.train_score = jnp.asarray(
                init.reshape(C, self.num_data))
        else:
            self.train_score = jnp.zeros((C, self.num_data),
                                         dtype=jnp.float32)
        self._bag_rng = np.random.RandomState(cfg.bagging_seed)
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        self._key = jax.random.PRNGKey(cfg.seed)
        self.bag_weight = jnp.ones(self.num_data, dtype=jnp.float32)
        # a stopped model may find splits again on fresh data
        self._stop_flag = False
        # init scores are already folded into a replayed buffer; re-running
        # boost-from-average would shift every valid score a second time
        self._boosted_from_average = self.iter_ > 0
        self._full_fmask = jnp.ones(train_set.num_used_features,
                                    dtype=jnp.float32)
        self._fused_fns = None
        self._fused_core = None
        self._obj_arrs = None
        self._chunk_fns: Dict[object, object] = {}
        self._shr_dev: Dict[float, jax.Array] = {}
        # a data swap invalidates the in-scan eval program (labels, bin
        # layout and metric bindings may all change); the engine/CLI
        # attach a fresh one via setup_inscan_eval when eligible
        self._inscan = None
        self._vscores_dev = None
        self._inscan_evals = []
        # OOM-degraded chunk-size ceiling (None = no ceiling): once a
        # chunk dispatch hits RESOURCE_EXHAUSTED the cap halves and
        # STICKS, so later chunks of the run skip the doomed sizes
        self._chunk_cap: Optional[int] = None

    # ------------------------------------------------------- memory tiers
    def _spill_blocked_reason(self, parallel: bool) -> Optional[str]:
        """Why the host-spill tier is off the table for this run, or
        None when it is available."""
        if parallel or getattr(self, "_mesh", None) is not None:
            return ("distributed learners keep the bin matrix sharded "
                    "in HBM")
        if str(getattr(self.config, "data_in_hbm", "auto")).strip() \
                .lower() == "resident":
            return "data_in_hbm=resident pins the bin matrix in HBM"
        return None

    def _estimate_working_set(self) -> int:
        """Pre-dispatch estimate of the training working set in bytes:
        the bin matrix in its resolved device layout, the f32 boosting
        state (scores/grads/hessians per class, bag weights, leaf ids),
        plus the largest CostJit ``memory_analysis`` working set already
        on record (a resumed/warm process knows its compiled programs'
        temp+argument+output bytes; a cold one contributes 0)."""
        ts = self.train_set
        return working_set_bytes(
            self.num_data, ts.num_columns,
            num_tree_per_iteration=self.num_tree_per_iteration,
            layout=self._bins_layout,
            itemsize=ts.binned.dtype.itemsize)

    def _resolve_data_tier(self, parallel: bool) -> str:
        """data_in_hbm=auto|resident|spill -> this run's starting tier.

        ``auto`` is the proactive admission check: estimated working
        set vs the device's reported HBM capacity
        (``TELEMETRY.device_memory_budget()``); backends without
        allocator stats (CPU) stay resident.  The ``oocore/admit``
        fault site forces the spill decision deterministically.  Every
        spill decision lands in the telemetry faults section as an
        ``oocore_admit`` event.  The tier is runtime-only state — it is
        never serialized into models or snapshots."""
        choice = str(getattr(self.config, "data_in_hbm", "auto")).strip() \
            .lower()
        blocked = self._spill_blocked_reason(parallel)
        if blocked is not None:
            if choice == "spill":
                log_warning(f"data_in_hbm=spill ignored: {blocked}")
            self._spill_unavail = blocked
            TELEMETRY.set_data_tier("resident")
            return "resident"
        if choice == "spill":
            TELEMETRY.fault_event("oocore_admit", site="oocore/admit",
                                  iteration=self.iter_,
                                  detail="forced by data_in_hbm=spill")
            TELEMETRY.set_data_tier("spill")
            return "spill"
        tier, detail = "resident", ""
        if FAULTS.enabled and FAULTS.check("oocore/admit"):
            tier, detail = "spill", "injected admission failure"
        else:
            budget = TELEMETRY.device_memory_budget()
            if budget:
                need = self._estimate_working_set()
                if need > _ADMIT_FRACTION * budget:
                    tier = "spill"
                    detail = (f"estimated working set ~{need} B vs "
                              f"{budget} B reported HBM")
        if tier == "spill":
            TELEMETRY.fault_event("oocore_admit", site="oocore/admit",
                                  iteration=self.iter_, detail=detail)
            log_warning(f"admission check: {detail}; starting in the "
                        "host-spill (out-of-core) tier")
        TELEMETRY.set_data_tier(tier)
        return tier

    def _upload_resident_bins(self, train_set: TpuDataset) -> None:
        """Resident tier: the cached whole-matrix device upload."""
        kind, rm, packed4 = self._bins_layout
        if kind == "T":
            self.bins = train_set.device_binned_T(rm, packed4=packed4)
            self._row_pad = int(self.bins.shape[1]) - self.num_data
        else:
            self.bins = train_set.device_binned()

    def _activate_spill(self, train_set: TpuDataset) -> None:
        """Move the bin matrix to the host-spill tier: build the
        fixed-order row-block store over the exact bytes the resident
        path would upload (bit-identity by construction), and drop
        every resident device copy so its HBM is reclaimable."""
        from ..data.hostspill import HostSpillStore
        kind, rm, packed4 = self._bins_layout
        if kind == "T":
            mat = train_set.host_binned_T(rm, packed4=packed4)
            self._row_pad = int(mat.shape[1]) - self.num_data
            axis = 1
        else:
            mat = train_set.host_binned()
            axis = 0
        self._spill_store = HostSpillStore.from_matrix(mat, row_axis=axis)
        self.bins = None
        self._bins_window = None
        train_set.drop_device_cache()
        TELEMETRY.gauge_set("oocore/spill_bytes", self._spill_store.nbytes)
        TELEMETRY.gauge_set("oocore/block_rows",
                            self._spill_store.block_rows)

    def _device_bins(self):
        """The device bin matrix for the next dispatch.  Resident tier:
        the cached upload.  Spill tier: stream the host row-blocks into
        a fresh device matrix (data/hostspill.py) and keep it only for
        the current dispatch window — train_chunk releases it on exit,
        so between windows that HBM is reclaimable (the matrix IS
        resident during a window; the win is between-window headroom
        and allocator fragmentation recovery)."""
        if self.bins is not None:
            return self.bins
        if self._bins_window is None:
            with _PHASES.phase("h2d_stream"):
                self._bins_window = self._spill_store.stream_to_device()
        return self._bins_window

    def _release_bins_window(self) -> None:
        """Drop the spill tier's per-window device matrix (no-op when
        resident: self.bins keeps the only reference there)."""
        self._bins_window = None

    def _donated_carries_deleted(self) -> bool:
        """True when a failed dispatch consumed its donated score/key/
        vscore buffers — there is no device state left to retry from."""
        for buf in ((self.train_score, self._key)
                    + tuple(self._vscores_dev or ())):
            deleted = getattr(buf, "is_deleted", None)
            if deleted is not None and deleted():
                return True
        return False

    def _escalate_spill(self, err: BaseException) -> bool:
        """Reactive rung 3->4 of the recovery ladder: the chunk-size
        ladder bottomed out at 1 and dispatch still RESOURCE_EXHAUSTs —
        move the bin matrix to the host-spill tier and let the caller
        retry, instead of giving up.  Returns False (recording the
        reason for _oom_exhausted) when the tier is unavailable or
        already active."""
        if getattr(self, "_data_tier", "resident") == "spill":
            self._spill_unavail = "already at the host-spill tier"
            return False
        blocked = self._spill_blocked_reason(False)
        if blocked is not None:
            self._spill_unavail = blocked
            return False
        if self._donated_carries_deleted():
            self._spill_unavail = ("the failed dispatch consumed its "
                                   "donated score/key carries; no device "
                                   "state left to retry from")
            return False
        # same recovery pattern as the PR 7 vscores invalidation: drop
        # the device carry, re-upload from the host f64 truth at the
        # next dispatch (outside the transfer guard)
        self._vscores_dev = None
        self._activate_spill(self.train_set)
        self._data_tier = "spill"
        TELEMETRY.set_data_tier("spill")
        TELEMETRY.fault_event(
            "oom_spill", site="chunk/oom", iteration=self.iter_,
            detail="chunk ladder exhausted at size 1; bin matrix spilled "
                   "to host (out-of-core tier)")
        log_warning("dispatch still RESOURCE_EXHAUSTED at chunk size 1; "
                    "spilling the bin matrix to host memory and streaming "
                    "row-blocks per dispatch window (out-of-core tier)")
        return True

    def _replay_model_scores(self, dataset: TpuDataset) -> np.ndarray:
        """[C, N] f64 raw scores of the current model on ``dataset``: the
        dataset's per-row init scores (else zeros), every existing tree
        replayed over its binned rows, plus the scalar boost-from-average
        inits (gbdt.cpp AddValidDataset / ResetTrainingData).  Trees loaded
        from a model file are bin-remapped first."""
        C = self.num_tree_per_iteration
        models = self.models                 # flushes the async pipeline
        n_iter = self.iter_
        score = np.zeros((C, dataset.num_data), dtype=np.float64)
        if dataset.metadata.init_score is not None:
            score = np.asarray(dataset.metadata.init_score,
                               dtype=np.float64).reshape(
                                   C, dataset.num_data).copy()
        infos = dataset.feature_infos()
        for it in range(n_iter):
            for k in range(C):
                tree = models[it * C + k]
                if not tree.bins_aligned:
                    from .serialization import _remap_tree_to_bins
                    tree = _remap_tree_to_bins(tree, dataset)
                    # cache the remap ONLY against the training set (whose
                    # alignment is enforced); persisting a remap against an
                    # arbitrary valid set would silently re-route later
                    # binned passes through that set's bins
                    if dataset is self.train_set:
                        models[it * C + k] = tree
                score[k] += tree.predict_binned(dataset.binned, infos)
        for k in range(C):
            score[k] += self.init_scores[k]
        return score

    def add_valid_data(self, name: str, valid_set: TpuDataset) -> None:
        if self.train_set is not None:
            self.train_set.check_align(valid_set)
        # replay existing trees (continued training, gbdt.cpp
        # AddValidDataset)
        score = self._replay_model_scores(valid_set)
        self.valid_sets.append((name, valid_set))
        self.valid_scores.append(score)
        # the in-scan eval program binds the valid-set tuple at build time
        self._inscan = None
        self._vscores_dev = None

    # --------------------------------------------------------------- bagging
    def _bagging(self, iter_idx: int, grads, hesss):
        """Compute the per-iteration row-inclusion mask; may also rescale
        gradients (GOSS overrides).  Returns (grads, hesss)."""
        cfg = self.config
        need = (cfg.bagging_freq > 0 and
                (cfg.bagging_fraction < 1.0
                 or cfg.pos_bagging_fraction < 1.0
                 or cfg.neg_bagging_fraction < 1.0))
        if not need:
            return grads, hesss
        if iter_idx % cfg.bagging_freq != 0:
            return grads, hesss
        n = self.num_data
        if (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0):
            # balanced bagging over positive/negative labels (gbdt.cpp:186-240)
            lab = np.asarray(self.train_set.metadata.label)
            mask = np.zeros(n, dtype=np.float32)
            pos = np.nonzero(lab > 0)[0]
            neg = np.nonzero(lab <= 0)[0]
            kp = int(len(pos) * cfg.pos_bagging_fraction)
            kn = int(len(neg) * cfg.neg_bagging_fraction)
            if kp > 0:
                mask[self._bag_rng.choice(pos, kp, replace=False)] = 1.0
            if kn > 0:
                mask[self._bag_rng.choice(neg, kn, replace=False)] = 1.0
        else:
            k = int(n * cfg.bagging_fraction)
            idx = self._bag_rng.choice(n, k, replace=False)
            mask = np.zeros(n, dtype=np.float32)
            mask[idx] = 1.0
        self.bag_weight = jnp.asarray(mask)
        return grads, hesss

    def _tree_feature_mask(self) -> jnp.ndarray:
        """Per-tree feature_fraction sampling (GetUsedFeatures,
        serial_tree_learner.cpp:273-321)."""
        F = self.train_set.num_used_features
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return self._full_fmask
        k = max(1, int(F * frac))
        idx = self._feat_rng.choice(F, k, replace=False)
        mask = np.zeros(F, dtype=np.float32)
        mask[idx] = 1.0
        return jnp.asarray(mask)

    # ------------------------------------------------------------- iteration
    def _boost_from_average(self) -> None:
        cfg = self.config
        if (self._boosted_from_average or self.objective is None
                or not cfg.boost_from_average
                or self.train_set.metadata.init_score is not None):
            self._boosted_from_average = True
            return
        C = self.num_tree_per_iteration
        for k in range(C):
            init = self.objective.boost_from_score(k)
            if abs(init) > 1e-15:
                self.init_scores[k] = init
                self.train_score = self.train_score.at[k].add(init)
                for vs in self.valid_scores:
                    vs[k] += init
        self._boosted_from_average = True

    def _gradients(self):
        C = self.num_tree_per_iteration
        if C == 1:
            g, h = self.objective.get_gradients(self.train_score[0])
            return g[None, :], h[None, :]
        return self.objective.get_gradients(self.train_score)

    # trees may be fetched asynchronously (pipeline depth 1) when nothing
    # needs them on the host mid-iteration; DART/RF mutate freshly-grown
    # trees and opt out
    _async_trees = True
    # whole-iteration fusion (gradients + grow + score update in a single
    # jitted dispatch per tree) — subclasses whose bagging cannot run as
    # a device-side transform of the gradients opt out
    _fused_ok = True
    # the chunked loop (train_chunk) additionally requires every
    # per-iteration decision to live on device; subclasses whose _bagging
    # transforms gradients with host-side dispatch each iteration (GOSS)
    # opt out
    _chunk_capable = True
    # test seam: zero-arg context-manager factory wrapped around the chunk
    # dispatch (tests install jax.transfer_guard("disallow") here to prove
    # the chunk body never touches the host)
    _chunk_guard = None
    # in-scan evaluation (metric/device.py): the DeviceEval program the
    # chunk scan body runs per iteration, and the device-resident [C, Nv]
    # f32 valid-score carries it threads between dispatches.  None until
    # setup_inscan_eval attaches one; _vscores_dev is re-uploaded from
    # the host f64 buffers whenever it is invalidated (rollback, undo,
    # OOM degrade, data swap)
    _inscan = None
    _vscores_dev = None

    def _build_fused_step(self):
        """One jitted call per (gradient pass, per-class tree).  Keeping the
        iteration to two dispatches matters on the remote-TPU transport,
        where every eager op pays a round-trip; it is also the natural unit
        for the driver's multichip dryrun."""
        import functools
        obj = self.objective
        pad = self._row_pad
        N = self.num_data
        C = self.num_tree_per_iteration
        grow_fn = self._grow_fn

        # device-array state of the objective (labels, per-class weights,
        # lambdarank bucket tables...) passed as explicit args: embedding
        # them as jit constants would bloat the compiled program (and the
        # remote-compile request) by O(N) bytes.  tree_flatten reaches
        # arrays nested in lists/dicts (e.g. rank.py's bucket structures).
        attr_leaves, attr_treedef = jax.tree_util.tree_flatten(
            dict(vars(obj)),
            is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
        arr_pos = [i for i, x in enumerate(attr_leaves)
                   if isinstance(x, jax.Array)]
        self._obj_arrs = [attr_leaves[i] for i in arr_pos]

        def _with_arrs(fn, arr_vals):
            leaves = list(attr_leaves)
            for i, v in zip(arr_pos, arr_vals):
                leaves[i] = v
            attrs = jax.tree_util.tree_unflatten(attr_treedef, leaves)
            saved = {k: getattr(obj, k) for k in attrs}
            for k, v in attrs.items():
                setattr(obj, k, v)
            try:
                return fn()
            finally:
                for k, v in saved.items():
                    setattr(obj, k, v)

        def grad_core(score, arrs):
            def run():
                if C == 1:
                    g, h = obj.get_gradients(score[0])
                    return g[None], h[None]
                return obj.get_gradients(score)
            return _with_arrs(run, arrs)

        fused_grad = cost_jit("boost/gradients", jax.jit(grad_core))

        # multiclass batched roots: all C class-trees' root histograms in
        # ONE kernel pass (C x fewer full-data scans per iteration; the
        # 8*C output channels also pack the MXU tile better).  Serial
        # segment/frontier growers only — the distributed wrappers own
        # their histogram reduction, and the fused grower's layout is
        # row-major.
        batched_roots = (C > 1 and self._use_segment
                         and getattr(self, "_mesh", None) is None)
        if batched_roots:
            from ..ops.pallas_histogram import (channel_set_capacity,
                                                histogram_all,
                                                pack_channels, unpack_hist)
            G_cols = self.train_set.num_columns
            rb_ = self.grower_params.row_chunk
            packed4 = self.grower_params.packed4
            # the kernel's VMEM working set grows with the channel stack;
            # chunk the classes when num_class exceeds the budget
            cap = channel_set_capacity(G_cols, self.num_bins, rb_)

            def roots_core(grads, hesss, member, bins):
                if pad:
                    grads = jnp.pad(grads, ((0, 0), (0, pad)))
                    hesss = jnp.pad(hesss, ((0, 0), (0, pad)))
                    member = jnp.pad(member, (0, pad))
                outs = []
                for c0 in range(0, C, cap):
                    cs = range(c0, min(c0 + cap, C))
                    w8m = jnp.concatenate(
                        [pack_channels(grads[c], hesss[c], member)
                         for c in cs])                      # [len*8, Npad]
                    out = histogram_all(bins, w8m, self.num_bins, rb_,
                                        packed4=packed4)
                    if len(cs) == 1:
                        out = out[None]
                    outs.append(out)
                out = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
                return jax.vmap(unpack_hist)(out)[:, :G_cols]

            fused_roots = cost_jit("grow/roots", jax.jit(roots_core))
        else:
            fused_roots = roots_core = None

        # Resolve the scorer choice OUTSIDE the trace: the auto mode
        # runs a real on-device self-check (lowering + bit-exactness)
        # and falls back to the gather if the kernel misbehaves.
        if self.grower_params.hist_backend == "pallas":
            from ..ops.pallas_score import scorer_available
            use_score_kernel = scorer_available()
        else:
            use_score_kernel = False

        def step_core_full(score, grads, hesss, member, bins, fmeta, fmask,
                           sub, shrinkage, k, roots=None):
            g_k, h_k = grads[k], hesss[k]
            if pad:
                g_k = jnp.pad(g_k, (0, pad))
                h_k = jnp.pad(h_k, (0, pad))
                member = jnp.pad(member, (0, pad))
            kw = {} if roots is None else {"root_hist": roots[k]}
            arrays, leaf_id, *stats = grow_fn(bins, g_k, h_k, member,
                                              fmeta, fmask, sub, **kw)
            if pad:
                leaf_id = leaf_id[:N]
            if use_score_kernel:
                # one-hot-matmul scorer: the plain table gather lowers
                # to ~1.6 GB/s on this backend (ops/pallas_score)
                from ..ops.pallas_score import score_gather_add
                new_row = score_gather_add(
                    score[k], leaf_id, shrinkage * arrays.leaf_value)
            else:
                new_row = (score[k]
                           + shrinkage * arrays.leaf_value[leaf_id])
            score = score.at[k].set(new_row)
            ints_d, floats_d = _pack_tree_device(arrays)
            # the raw TreeArrays ride along for the in-scan eval variant,
            # which re-routes the valid sets through the freshly grown tree
            return score, ints_d, floats_d, tuple(stats), arrays

        def step_core(*a, **kw):
            return step_core_full(*a, **kw)[:4]

        fused_step = cost_jit(
            "grow/fused_step",
            functools.partial(jax.jit, donate_argnums=(0,))(step_core))

        self._fused_fns = (fused_grad, fused_step, fused_roots)
        # un-jitted building blocks; the chunked loop retraces them inside
        # its scan so a chunk body is op-for-op the per-iteration fused
        # path (bit-identical trees at any chunk size)
        self._fused_core = (grad_core, step_core, roots_core, step_core_full)

    def _get_chunk_fn(self, T: int, with_eval: bool = False):
        """One jitted program running ``T`` boosting iterations as a
        lax.scan over the fused step, stacking each iteration's packed
        tree buffers into [T, C, ...] on-device outputs.  The score and
        PRNG-key carries are donated so no buffer copies accumulate
        across chunks.

        With ``with_eval`` the scan additionally threads the valid-set
        score vectors through the carry, routes every freshly grown tree
        over each valid set's binned matrix, and runs the attached
        DeviceEval program per iteration — stacking a [T, n_cols] metric
        matrix onto the chunk outputs so eval cadence costs zero extra
        dispatches."""
        cache_key = (T, "eval") if with_eval else T
        fn = self._chunk_fns.get(cache_key)
        if fn is not None:
            return fn
        import functools
        if self._fused_core is None:
            self._build_fused_step()
        grad_core, step_core, roots_core, step_core_full = self._fused_core
        C = self.num_tree_per_iteration

        if with_eval:
            inscan = self._inscan
            gp = self.grower_params
            # static routing depth: max_depth when bounded, else the leaf
            # count (a path can't be longer than num_leaves - 1 splits)
            depth_bound = ((gp.max_depth + 1) if gp.max_depth > 0
                           else gp.num_leaves)

            @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
            def chunk_run_eval(score, key, vscores, member, bins, fmeta,
                               fmask, shrinkage, arrs, vbins, earrs):
                def body(carry, _):
                    score, key, vscores = carry
                    grads, hesss = grad_core(score, arrs)
                    gstats = _grad_stats_core(grads, hesss)
                    roots = (roots_core(grads, hesss, member, bins)
                             if roots_core is not None else None)
                    ints_l, floats_l = [], []
                    for k in range(C):
                        key, sub = jax.random.split(key)
                        score, ints_d, floats_d, _, arrays = step_core_full(
                            score, grads, hesss, member, bins, fmeta,
                            fmask, sub, shrinkage, jnp.int32(k), roots)
                        ints_l.append(ints_d)
                        floats_l.append(floats_d)
                        vscores = [
                            vs.at[k].add(shrinkage * _route_tree_rows(
                                arrays, vb, fmeta, depth_bound))
                            for vs, vb in zip(vscores, vbins)]
                    mvals = inscan.eval_fn(score, vscores, earrs)
                    return ((score, key, vscores),
                            (jnp.stack(ints_l), jnp.stack(floats_l),
                             gstats, mvals))

                carry, (ints_all, floats_all, gstats_all, mvals_all) = \
                    jax.lax.scan(body, (score, key, vscores), None,
                                 length=T)
                score, key, vscores = carry
                return (score, key, vscores, ints_all, floats_all,
                        gstats_all, mvals_all)

            chunk_run_eval = cost_jit(f"boost/chunk_eval[{T}]",
                                      chunk_run_eval)
            self._chunk_fns[cache_key] = chunk_run_eval
            return chunk_run_eval

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def chunk_run(score, key, member, bins, fmeta, fmask, shrinkage,
                      arrs):
            def body(carry, _):
                score, key = carry
                grads, hesss = grad_core(score, arrs)
                # health diagnostics ride the scan as one more stacked
                # output ([T, C, 8] total): zero extra dispatches, and
                # the tiny reduce is dwarfed by the histogram build
                gstats = _grad_stats_core(grads, hesss)
                roots = (roots_core(grads, hesss, member, bins)
                         if roots_core is not None else None)
                ints_l, floats_l = [], []
                for k in range(C):
                    # same key stream as the per-iteration paths, so the
                    # same seed grows the same trees at any chunk size
                    key, sub = jax.random.split(key)
                    score, ints_d, floats_d, _ = step_core(
                        score, grads, hesss, member, bins, fmeta, fmask,
                        sub, shrinkage, jnp.int32(k), roots)
                    ints_l.append(ints_d)
                    floats_l.append(floats_d)
                return ((score, key),
                        (jnp.stack(ints_l), jnp.stack(floats_l), gstats))

            (score, key), (ints_all, floats_all, gstats_all) = jax.lax.scan(
                body, (score, key), None, length=T)
            return score, key, ints_all, floats_all, gstats_all

        chunk_run = cost_jit(f"boost/chunk[{T}]", chunk_run)
        self._chunk_fns[cache_key] = chunk_run
        return chunk_run

    @property
    def models(self) -> List[Tree]:
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._models = list(value)
        self._pending = []
        self._vscores_dev = None

    def _entry_iter_arrays(self, entry):
        """Normalize one pending entry into per-iteration host pytrees:
        [(iter_idx, [(TreeArrays, shrinkage)] * C, gstats, chunk_len,
        mvals_row, wall_s)].  A chunk entry fetches its stacked [T, C,
        ...] buffers here — two host transfers for the WHOLE chunk (the
        async copy started at dispatch), then pure numpy slicing.
        ``gstats`` is the [C, 8] grad/hess diagnostics row for the
        health stream (None when no stream is active — the device buffer
        is then never fetched); ``mvals_row`` is the in-scan eval
        program's [n_cols] metric row (None off the eval path), its
        fetch counted under ``transfer/eval_fetch_*``; ``wall_s`` is the
        chunk's dispatch wall window, attributed to the chunk's FIRST
        iteration (None elsewhere)."""
        iter_idx, payload, gstats = entry
        L = self.grower_params.num_leaves
        fetch_stats = gstats is not None and HEALTH.active
        if isinstance(payload, _PendingChunk):
            chunk = fetch_tree_chunk(payload.ints_all, payload.floats_all,
                                     L)
            gnp = np.asarray(gstats) if fetch_stats else None
            mv = None
            if payload.mvals is not None:
                mv = np.asarray(payload.mvals)
                # the in-scan eval row fetch is its own host transfer;
                # counted separately from the tree-buffer fetch_calls
                # (whose exact counts tests pin)
                TELEMETRY.counter_add("transfer/eval_fetch_calls")
                TELEMETRY.counter_add("transfer/eval_fetch_bytes",
                                      int(mv.nbytes))
            return [(iter_idx + t,
                     [(arrays, payload.shrinkage) for arrays in per_class],
                     gnp[t] if gnp is not None else None,
                     payload.length,
                     mv[t] if mv is not None else None,
                     payload.wall_s if t == 0 else None)
                    for t, per_class in enumerate(chunk)]
        pairs = []
        for (ints_d, floats_d, lr) in payload:
            ints_np, floats_np = np.asarray(ints_d), np.asarray(floats_d)
            TELEMETRY.counter_add("transfer/fetch_calls")
            TELEMETRY.counter_add("transfer/fetch_bytes",
                                  int(ints_np.nbytes)
                                  + int(floats_np.nbytes))
            pairs.append((unpack_tree_buffers(ints_np, floats_np, L), lr))
        return [(iter_idx, pairs,
                 np.asarray(gstats) if fetch_stats else None, 1, None,
                 None)]

    def _materialize_iter(self, pairs):
        """One iteration's [(TreeArrays, shrinkage)] -> (trees, all_const);
        constant outputs become Tree(1) placeholders."""
        trees = []
        all_const = True
        for arrays, lr in pairs:
            if int(arrays.num_leaves) <= 1:
                trees.append(Tree(1))
            else:
                all_const = False
                trees.append(Tree.from_grown(arrays, self.train_set, lr))
        return trees, all_const

    def _apply_valid_scores(self, trees) -> None:
        """Fold freshly-materialized trees into the valid-set score
        buffers.  The per-iteration async path never has valid sets
        attached (train_one_iter routes eager then); this feeds the
        chunked path, whose boundary flush must leave eval_valid
        current."""
        if not self.valid_sets:
            return
        infos = self.train_set.feature_infos()
        for (vname, vset), vscore in zip(self.valid_sets,
                                         self.valid_scores):
            for k, tree in enumerate(trees):
                if tree.num_leaves > 1:
                    vscore[k] += tree.predict_binned(vset.binned, infos)

    def _flush_pending(self, keep_latest: int = 0) -> None:
        """Materialize in-flight trees (oldest first) into self._models.

        A fully-constant iteration means training stopped there: its trees
        and every later pending iteration's are discarded (their score
        deltas undone), matching the reference's drop of the all-constant
        iteration (gbdt.cpp:543-551) — just detected one iteration (or
        chunk) late.
        """
        while len(self._pending) > keep_latest:
            per_iter = self._entry_iter_arrays(self._pending.pop(0))
            for j, (iter_idx, pairs, gstats, clen, mrow,
                    wall) in enumerate(per_iter):
                trees, all_const = self._materialize_iter(pairs)
                if all_const:
                    rest = [(ii, self._materialize_iter(pp)[0])
                            for ii, pp, _g, _c, _m, _w in per_iter[j + 1:]]
                    self._undo_pending_scores([(iter_idx, trees)] + rest
                                              + self._materialize_rest())
                    self._pending = []
                    self._stop_flag = True
                    self.iter_ = iter_idx
                    log_warning("Stopped training because there are no "
                                "more leaves that meet the split "
                                "requirements")
                    return
                self._models.extend(trees)
                self._note_trees(trees)
                self._apply_valid_scores(trees)
                self._health_emit(iter_idx, trees, gstats, clen,
                                  wall_s=wall)
                # in-scan eval rows surface only for materialized
                # iterations: tail-of-chunk rows past an all-constant
                # stop are discarded with their trees
                if mrow is not None:
                    self._inscan_evals.append((iter_idx, mrow))

    def _note_trees(self, trees) -> None:
        """Record which features the model has split on, feeding the next
        iteration's CEGB coupled penalty (is_feature_used_in_split_,
        serial_tree_learner.h:169 — persists across trees)."""
        if not self.grower_params.use_cegb_coupled:
            return
        changed = False
        for t in trees:
            if t.num_leaves > 1:
                for f in np.unique(t.split_feature_inner[: t.num_leaves - 1]):
                    if not self._cegb_used[f]:
                        self._cegb_used[f] = 1.0
                        changed = True
        if changed:
            self.fmeta = self.fmeta._replace(
                cegb_used0=jnp.asarray(self._cegb_used, dtype=jnp.float32))

    def _materialize_rest(self):
        out = []
        for entry in self._pending:
            for iter_idx, pairs, _g, _c, _m, _w in self._entry_iter_arrays(
                    entry):
                out.append((iter_idx, self._materialize_iter(pairs)[0]))
        return out

    # ------------------------------------------------------- health stream
    def _health_emit(self, iter_idx: int, trees, gstats,
                     chunk_len: int, wall_s=None) -> None:
        """One ``iter`` health record: dispatched chunk size, per-tree
        shape stats, grad/hess diagnostics ([C, 8] from
        ``_grad_stats_core``), the HBM gauge, and — on the chunk's first
        iteration — the dispatch wall window (``dispatch_wall_s``).
        Emitted at tree materialization, so the async pipeline's records
        land in iteration order."""
        if not HEALTH.active:
            return
        rec: Dict[str, Any] = {"iter": int(iter_idx),
                               "chunk": int(chunk_len)}
        # memory tier of the bin matrix (resident / spill), so a live
        # monitor can see an out-of-core escalation mid-run
        rec["data_tier"] = getattr(self, "_data_tier", None) or "resident"
        if wall_s is not None:
            rec["dispatch_wall_s"] = round(float(wall_s), 6)
        tstats = []
        for t in trees:
            nl = int(t.num_leaves)
            n = max(nl - 1, 0)
            gains = np.asarray(t.split_gain[:n], dtype=np.float64)
            tstats.append({
                "leaves": nl,
                "depth": int(np.max(t.leaf_depth[:nl])) if nl > 1 else 0,
                "gain_sum": float(gains.sum()) if n else 0.0,
                "gain_max": float(gains.max()) if n else 0.0,
            })
        rec["trees"] = tstats
        if gstats is not None:
            g = np.asarray(gstats)
            rec["grad"] = {
                "min": [float(v) for v in g[:, 0]],
                "max": [float(v) for v in g[:, 1]],
                "l2": [float(v) for v in g[:, 2]],
                "nonfinite": [int(v) for v in g[:, 3]],
            }
            rec["hess"] = {
                "min": [float(v) for v in g[:, 4]],
                "max": [float(v) for v in g[:, 5]],
                "l2": [float(v) for v in g[:, 6]],
                "nonfinite": [int(v) for v in g[:, 7]],
            }
        hbm = TELEMETRY.memory_gauges()
        if hbm is not None:
            rec["hbm"] = hbm
        HEALTH.record("iter", rec)

    def _undo_pending_scores(self, iter_trees) -> None:
        """Subtract discarded iterations' contributions from train_score
        (rare: only when stop is detected late under bagging randomness)."""
        # the device valid-score carry already includes the discarded
        # trees; drop it and re-upload from the host f64 truth next chunk
        self._vscores_dev = None
        infos = self.train_set.feature_infos()
        for _, trees in iter_trees:
            for k, tree in enumerate(trees):
                if tree.num_leaves > 1:
                    delta = tree.predict_binned(self.train_set.binned, infos)
                    self.train_score = self.train_score.at[k].add(
                        -jnp.asarray(delta, dtype=jnp.float32))

    # ----------------------------------------------------- fault guardrails
    def _poison_scores(self) -> None:
        """grad/nonfinite injection: NaN the score buffer, so the next
        gradient pass (and everything downstream) goes non-finite the
        same way a diverged objective would."""
        self.train_score = self.train_score * jnp.float32(np.nan)

    def _raise_nonfinite(self, first_iter: int, count: int) -> None:
        obj = getattr(self.config, "objective", "?")
        span = (f"iteration {first_iter}" if count <= 1 else
                f"iterations {first_iter}..{first_iter + count - 1}")
        raise LightGBMError(
            f"Non-finite values in the boosted scores at {span} "
            f"(objective={obj}); the ensemble was rolled back to the "
            f"{first_iter} completed iteration(s) before it — check the "
            f"learning_rate/objective "
            f"for divergence, or set check_nonfinite=false to ship the "
            f"model anyway")

    def _guard_nonfinite(self, it: int) -> None:
        """Per-iteration finiteness guardrail: on NaN/Inf scores, drop
        the just-trained iteration and raise (check_nonfinite)."""
        if not getattr(self.config, "check_nonfinite", True):
            return
        if bool(_all_finite(self.train_score)):
            return
        # settle the async pipeline first: a NaN iteration may grow an
        # all-constant tree, which the flush already discards (lowering
        # iter_ back to ``it``); only a materialized bad iteration needs
        # the explicit rollback
        self._flush_pending()
        if self.iter_ > it:
            self.rollback_one_iter()
        TELEMETRY.fault_event("nonfinite_rollback", site="grad/nonfinite",
                              iteration=it,
                              detail="iteration dropped")
        self._raise_nonfinite(it, 1)

    def _guard_chunk_nonfinite(self, first_iter: int, t: int) -> None:
        """Chunk-boundary guardrail, called BEFORE the chunk's pending
        trees are enqueued: a non-finite score buffer discards the whole
        failing chunk (its buffers never become trees), settles the
        still-good in-flight chunk, and raises."""
        if not getattr(self.config, "check_nonfinite", True):
            return
        if bool(_all_finite(self.train_score)):
            return
        self._flush_pending()        # older chunks are still good
        TELEMETRY.fault_event("nonfinite_rollback", site="grad/nonfinite",
                              iteration=first_iter,
                              detail=f"chunk of {t} iterations dropped")
        self._raise_nonfinite(first_iter, t)

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True if training should stop
        (no further splits possible), matching LGBM_BoosterUpdateOneIter
        semantics.  Wraps the implementation with the check_nonfinite
        guardrail (and its grad/nonfinite injection site)."""
        if self._stop_flag:
            return True
        if FAULTS.check("grad/nonfinite", n=self.iter_):
            self._poison_scores()
        it = self.iter_
        stop = self._train_one_iter_impl(grad, hess)
        # per-iteration dispatch outside a chunk window: the spilled
        # matrix is released per iteration (out-of-core pays one stream
        # per dispatch window, by definition)
        if getattr(self, "_bins_hold", 0) <= 0:
            self._release_bins_window()
        self._guard_nonfinite(it)
        return stop

    def _train_one_iter_impl(self, grad: Optional[np.ndarray] = None,
                             hess: Optional[np.ndarray] = None) -> bool:
        self._boost_from_average()
        C = self.num_tree_per_iteration
        if self.train_set.num_used_features == 0:
            # every feature is trivial (e.g. min_data_in_leaf >= num_data
            # prunes all split points): the reference trains a constant
            # model and stops (gbdt.cpp:543-551) — growing is pointless
            # and the growers assume F >= 1
            self._flush_pending()
            self._models.extend(Tree(1) for _ in range(C))
            self.iter_ += 1
            self._stop_flag = True
            log_warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        use_async = (self._async_trees and not self.valid_sets
                     and (self.objective is None
                          or not self.objective.is_renew_tree_output))
        if (use_async and grad is None and self._fused_ok
                and self.objective is not None):
            return self._train_one_iter_fused()

        with _PHASES.phase("boost") as box:
            if grad is None or hess is None:
                if self.objective is None:
                    log_fatal("No objective and no custom gradients")
                grads, hesss = self._gradients()
            else:
                grads = jnp.asarray(np.asarray(grad, dtype=np.float32)
                                    .reshape(C, self.num_data))
                hesss = jnp.asarray(np.asarray(hess, dtype=np.float32)
                                    .reshape(C, self.num_data))
            grads, hesss = self._bagging(self.iter_, grads, hesss)
            # health diagnostics only when a stream consumes them — the
            # jitted reduce stays off the default hot path
            gstats = (_grad_stats(grads, hesss) if HEALTH.active
                      else None)
            box[0] = grads

        bins = self._device_bins()
        if use_async:
            items = []
            for k in range(C):
                fmask = self._tree_feature_mask()
                self._key, sub = jax.random.split(self._key)
                g_k, h_k, member = grads[k], hesss[k], self.bag_weight
                if self._row_pad:
                    g_k = jnp.pad(g_k, (0, self._row_pad))
                    h_k = jnp.pad(h_k, (0, self._row_pad))
                    member = jnp.pad(member, (0, self._row_pad))
                with _PHASES.phase("grow") as box:
                    arrays, leaf_id, *stats = self._grow_fn(
                        bins, g_k, h_k, member, self.fmeta, fmask, sub)
                    box[0] = leaf_id
                _maybe_print_seg_stats(stats)
                if self._row_pad:
                    leaf_id = leaf_id[: self.num_data]
                with _PHASES.phase("score") as box:
                    self.train_score = self.train_score.at[k].set(
                        _apply_tree_score(self.train_score[k],
                                          arrays.leaf_value, leaf_id,
                                          jnp.float32(self.shrinkage_rate)))
                    box[0] = self.train_score
                ints_d, floats_d = _pack_tree_device(arrays)
                self._start_host_copy(ints_d, floats_d)
                items.append((ints_d, floats_d, self.shrinkage_rate))
            self._pending.append((self.iter_, items, gstats))
            self.iter_ += 1
            # materialize older iterations; the newest stays in flight so
            # its fetch overlaps the next iteration's device work
            with _PHASES.phase("fetch"):
                self._flush_pending(keep_latest=1)
            TELEMETRY.mark_iteration(self.iter_ - 1)
            if self._stop_flag:
                return True
            return False

        should_stop = True
        infos = self.train_set.feature_infos()
        for k in range(C):
            fmask = self._tree_feature_mask()
            self._key, sub = jax.random.split(self._key)
            g_k, h_k, member = grads[k], hesss[k], self.bag_weight
            if self._row_pad:
                g_k = jnp.pad(g_k, (0, self._row_pad))
                h_k = jnp.pad(h_k, (0, self._row_pad))
                member = jnp.pad(member, (0, self._row_pad))
            with _PHASES.phase("grow") as box:
                arrays, leaf_id, *stats = self._grow_fn(
                    bins, g_k, h_k, member, self.fmeta, fmask, sub)
                box[0] = leaf_id
            _maybe_print_seg_stats(stats)
            if self._row_pad:
                leaf_id = leaf_id[: self.num_data]
            with _PHASES.phase("fetch"):
                arrays = fetch_tree_arrays(arrays)
            nl = int(arrays.num_leaves)
            if nl <= 1:
                tree = Tree(1)
                self.models.append(tree)
                continue
            should_stop = False
            tree = Tree.from_arrays(arrays, self.train_set)
            # leaf renewal for percentile-fit objectives (L1/quantile/MAPE)
            if (self.objective is not None
                    and self.objective.is_renew_tree_output):
                leaf_np = np.asarray(leaf_id)
                score_np = np.asarray(self.train_score[k], dtype=np.float64)
                tree.set_leaf_values(self.objective.renew_tree_output(
                    tree.leaf_value, leaf_np, score_np))
            tree.apply_shrinkage(self.shrinkage_rate)
            # device score update via the grower's leaf assignment; pad the
            # leaf values to the static num_leaves so _add_tree_score
            # compiles once, not once per distinct tree size
            lv_np = np.zeros(self.grower_params.num_leaves, dtype=np.float32)
            lv_np[:nl] = tree.leaf_value[:nl]
            self.train_score = self.train_score.at[k].set(
                _add_tree_score(self.train_score[k], jnp.asarray(lv_np),
                                leaf_id))
            for (vname, vset), vscore in zip(self.valid_sets,
                                             self.valid_scores):
                vscore[k] += tree.predict_binned(vset.binned, infos)
            self.models.append(tree)

        if should_stop:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            # drop the all-constant iteration (gbdt.cpp:543-551)
            for _ in range(C):
                self.models.pop()
            return True
        self._note_trees(self._models[-C:])
        self.iter_ += 1
        self._health_emit(self.iter_ - 1, self._models[-C:],
                          np.asarray(gstats) if gstats is not None
                          else None, 1)
        TELEMETRY.mark_iteration(self.iter_ - 1)
        return False

    def _train_one_iter_fused(self) -> bool:
        """Async iteration with the whole device pipeline in two jitted
        dispatches (gradients; per-class grow + score update)."""
        C = self.num_tree_per_iteration
        if self._fused_fns is None:
            self._build_fused_step()
        fused_grad, fused_step, fused_roots = self._fused_fns
        with _PHASES.phase("boost") as box:
            grads, hesss = fused_grad(self.train_score, self._obj_arrs)
            # bagging runs AFTER the gradient dispatch (GOSS's device-side
            # select transforms the gradients; membership-mask baggings
            # ignore them) — same call the eager path makes
            grads, hesss = self._bagging(self.iter_, grads, hesss)
            gstats = (_grad_stats(grads, hesss) if HEALTH.active
                      else None)
            box[0] = grads
        bins = self._device_bins()
        roots = None
        if fused_roots is not None:
            with _PHASES.phase("roots"):
                roots = fused_roots(grads, hesss, self.bag_weight,
                                    bins)
        items = []
        for k in range(C):
            fmask = self._tree_feature_mask()
            # identical key stream to the eager path, so the same seed
            # grows the same trees regardless of which path engages
            self._key, sub = jax.random.split(self._key)
            t0_grow = time.perf_counter()
            # instrumented parallel growers run inside the jitted step,
            # where their own wrapper is trace-time only; the fault
            # probe (collective/reduce_scatter etc.) and the per-tree
            # collective counters both live at this eager dispatch site
            coll_kind = getattr(self._grow_fn, "_collective_kind", None)
            if coll_kind is not None:
                from ..parallel import network
                network.probe_dispatch_collective(coll_kind)
            with _PHASES.phase("grow") as box:
                extra = () if roots is None else (roots,)
                self.train_score, ints_d, floats_d, stats_t = fused_step(
                    self.train_score, grads, hesss, self.bag_weight,
                    bins, self.fmeta, fmask, sub,
                    jnp.float32(self.shrinkage_rate), jnp.int32(k), *extra)
                box[0] = self.train_score
            if coll_kind is not None:
                from ..parallel import network
                network.record_collective(
                    coll_kind, self._grow_fn._collective_bytes,
                    time.perf_counter() - t0_grow)
            _maybe_print_seg_stats(stats_t)
            self._start_host_copy(ints_d, floats_d)
            items.append((ints_d, floats_d, self.shrinkage_rate))
        self._pending.append((self.iter_, items, gstats))
        self.iter_ += 1
        with _PHASES.phase("fetch"):
            # CEGB coupled penalties need this iteration's splits noted
            # before the next grow call, so forgo the one-deep pipeline
            keep = 0 if self.grower_params.use_cegb_coupled else 1
            self._flush_pending(keep_latest=keep)
        TELEMETRY.mark_iteration(self.iter_ - 1)
        return bool(self._stop_flag)

    # ---------------------------------------------------------- chunked loop
    @staticmethod
    def _start_host_copy(*bufs) -> None:
        """Kick off the device->host DMA early so the later blocking
        np.asarray finds the bytes already on their way."""
        for buf in bufs:
            copy_async = getattr(buf, "copy_to_host_async", None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:
                    pass

    def _chunk_ok(self) -> bool:
        """Whether multiple iterations can run without host interaction
        between them — the conditions under which tpu_boost_chunk
        auto-clamps to 1."""
        cfg = self.config
        if not (self._async_trees and self._fused_ok
                and self._chunk_capable and self.objective is not None):
            return False
        if self.objective.is_renew_tree_output:
            return False        # leaf renewal runs host percentile fits
        if getattr(self, "_mesh", None) is not None:
            return False        # distributed learners keep per-iter dispatch
        if cfg.feature_fraction < 1.0:
            return False        # per-tree host RNG (GetUsedFeatures)
        if cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0
                                     or cfg.pos_bagging_fraction < 1.0
                                     or cfg.neg_bagging_fraction < 1.0):
            return False        # per-iteration host bagging re-draw
        if (self.grower_params.use_cegb_coupled
                or self.grower_params.use_cegb_lazy):
            return False        # split bookkeeping feeds the next grow
        if seg_stats_enabled():
            return False        # per-iteration counter printing
        return True

    def boost_chunk_size(self) -> int:
        """Resolved tpu_boost_chunk: an explicit value wins; auto (0)
        chunks on the TPU backend — where every dispatch and fetch pays a
        transport round-trip — and stays at 1 elsewhere.  Always 1 when
        the run needs host interaction between iterations (_chunk_ok)."""
        if self.train_set is None or not self._chunk_ok():
            return 1
        req = int(self.config.tpu_boost_chunk)
        if req != 0:
            return max(1, req)
        return 16 if jax.default_backend() == "tpu" else 1

    def train_chunk(self, chunk: int) -> bool:
        """Run up to ``chunk`` boosting iterations as ONE device program
        (lax.scan over the fused step), deferring every device->host tree
        fetch to the chunk boundary, where it overlaps the next chunk's
        device work.  Falls back to train_one_iter when the configuration
        needs host interaction mid-chunk.  Returns True when training
        stopped.

        Always trains exactly ``chunk`` iterations (the engine/CLI step
        accounting assumes it) unless training stops: a chunk dispatch
        that dies with RESOURCE_EXHAUSTED is retried at half the size,
        down to per-iteration dispatch, and the degraded ceiling sticks
        for the rest of the run (_chunk_cap).  Sub-chunk splitting is
        bit-exact — the chunk body consumes the same PRNG key stream at
        any chunk size."""
        T = int(chunk)
        if self._stop_flag:
            return True
        if ((T <= 1 and self._inscan is None) or not self._chunk_ok()
                or self.train_set.num_used_features == 0):
            return self.train_one_iter()
        self._boost_from_average()
        done = 0
        # one spill window per train_chunk call: the streamed matrix is
        # held across the dispatch loop and released on every exit path
        self._bins_hold = getattr(self, "_bins_hold", 0) + 1
        try:
            while done < T:
                if self._stop_flag:
                    return True
                cap = self._chunk_cap
                t = T - done if cap is None else min(T - done, cap)
                if t <= 1 and self._inscan is None:
                    try:
                        # per-iteration fallback still probes the OOM
                        # site: a persistent allocator failure must
                        # reach the next rung (spill) or the actionable
                        # give-up error, not silently complete
                        if FAULTS.enabled:
                            FAULTS.maybe_raise("chunk/oom", oom_error)
                        stop = self.train_one_iter()
                    except Exception as e:
                        if not _is_oom_error(e):
                            raise
                        if self._escalate_spill(e):
                            continue               # retry out-of-core
                        raise self._oom_exhausted(e)  # out of headroom
                    if stop:
                        return True
                    done += 1
                    continue
                try:
                    self._dispatch_chunk(t)
                except Exception as e:
                    if not _is_oom_error(e):
                        raise
                    if t <= 1:
                        # in-scan runs keep the scan path even at chunk
                        # 1; the chunk ladder has no smaller dispatch —
                        # the spill tier is the only rung left
                        if self._escalate_spill(e):
                            continue
                        raise self._oom_exhausted(e)
                    self._degrade_chunk(t, e)
                    continue                       # retry at the new cap
                done += t
            return bool(self._stop_flag)
        finally:
            self._bins_hold -= 1
            if self._bins_hold <= 0:
                self._release_bins_window()

    def _dispatch_chunk(self, t: int) -> None:
        """Dispatch one fused chunk of ``t`` iterations and enqueue its
        tree buffers.  Hosts the grad/nonfinite and chunk/oom injection
        sites and the chunk-boundary finiteness guardrail."""
        if FAULTS.enabled:
            for i in range(self.iter_, self.iter_ + t):
                if FAULTS.check("grad/nonfinite", n=i):
                    self._poison_scores()
                    break
            FAULTS.maybe_raise("chunk/oom", oom_error)
        inscan = self._inscan
        fn = self._get_chunk_fn(t, with_eval=inscan is not None)
        shr = self._shr_dev.get(self.shrinkage_rate)
        if shr is None:
            # device-resident constant: materialized OUTSIDE the guarded
            # dispatch so the chunk body itself stays transfer-free
            shr = jnp.float32(self.shrinkage_rate)
            self._shr_dev[self.shrinkage_rate] = shr
        if inscan is not None and self._vscores_dev is None:
            # (re-)upload the valid-score carry from the host f64 truth;
            # OUTSIDE the guarded region — this is a legitimate h2d copy
            self._vscores_dev = [
                jnp.asarray(np.asarray(vs, dtype=np.float32))
                for vs in self.valid_scores]
        first_iter = self.iter_
        # spill tier: reassemble the device matrix here, OUTSIDE the
        # transfer-guarded region below (streaming is a legitimate h2d
        # copy, like the vscores re-upload above)
        bins = self._device_bins()
        if inscan is not None:
            args = (self.train_score, self._key, self._vscores_dev,
                    self.bag_weight, bins, self.fmeta,
                    self._full_fmask, shr, self._obj_arrs,
                    inscan.vbins, inscan.arrays)
        else:
            args = (self.train_score, self._key, self.bag_weight,
                    bins, self.fmeta, self._full_fmask, shr,
                    self._obj_arrs)
        mvals_all = None
        # the chunk's dispatch wall window: host dispatch time by
        # default, wall-to-ready when device_timing syncs inside the
        # CostJit seam — carried into the health stream's iter records
        t0_wall = time.perf_counter()
        with step_annotation("chunk", first_iter), \
                _PHASES.phase("chunk") as box:
            if self._chunk_guard is not None:
                with self._chunk_guard():
                    out = fn(*args)
            else:
                out = fn(*args)
            if inscan is not None:
                (self.train_score, self._key, self._vscores_dev, ints_all,
                 floats_all, gstats_all, mvals_all) = out
            else:
                (self.train_score, self._key, ints_all, floats_all,
                 gstats_all) = out
            box[0] = self.train_score
        wall_s = time.perf_counter() - t0_wall
        # before the chunk's buffers can become trees: a non-finite score
        # discards them and raises (older pending chunks stay good)
        self._guard_chunk_nonfinite(first_iter, t)
        self._start_host_copy(ints_all, floats_all, gstats_all, mvals_all)
        self._pending.append((self.iter_, _PendingChunk(
            ints_all, floats_all, self.shrinkage_rate, t, mvals_all,
            wall_s), gstats_all))
        self.iter_ += t
        with _PHASES.phase("fetch"):
            # valid-set scores update at materialization, and eval at the
            # chunk boundary needs the chunk just dispatched — so forgo
            # the one-chunk-deep pipeline when valid sets are attached
            keep = 0 if (self.valid_sets or inscan is not None) else 1
            self._flush_pending(keep_latest=keep)
        TELEMETRY.gauge_set("boost/chunk_size", t)
        TELEMETRY.mark_iteration(self.iter_ - 1, count=t)

    def _degrade_chunk(self, t: int, err: BaseException) -> None:
        """Halve the chunk-size ceiling after an OOM-shaped dispatch
        failure, or give up (with the HBM picture) when retry is
        impossible because the dispatch consumed its donated carries."""
        if self._donated_carries_deleted():
            # donate_argnums handed the score/key/vscore buffers to
            # the failed execution; there is no state left to retry
            self._spill_unavail = ("the failed dispatch consumed its "
                                   "donated score/key carries; no device "
                                   "state left to retry from")
            raise self._oom_exhausted(err)
        # conservatively re-upload the valid-score carry: partial
        # execution may have touched it even when not deleted
        self._vscores_dev = None
        self._chunk_cap = max(1, t // 2)
        log_warning(f"chunk dispatch of {t} iterations failed with "
                    f"RESOURCE_EXHAUSTED; retrying at chunk size "
                    f"{self._chunk_cap} (ceiling sticks for this run)")
        TELEMETRY.fault_event("oom_degrade", site="chunk/oom",
                              iteration=self.iter_,
                              detail=f"chunk {t} -> {self._chunk_cap}")

    def _oom_exhausted(self, err: BaseException) -> LightGBMError:
        """The actionable give-up error once every rung of the recovery
        ladder is spent: names the iteration, the NEXT rung that could
        not be taken (so failures at the true ceiling are diagnosable),
        and the peak-HBM figure from the telemetry memory section
        (PR 3) when the backend reports one."""
        mem = TELEMETRY.stats().get("memory") or {}
        peak, limit = mem.get("peak_bytes_in_use"), mem.get("bytes_limit")
        if peak:
            hbm = f"; peak HBM {peak / 1e9:.2f} GB"
            if limit:
                hbm += f" of {limit / 1e9:.2f} GB limit"
        else:
            hbm = "; peak HBM unavailable (backend reports no memory stats)"
        if getattr(self, "_data_tier", "resident") == "spill":
            rung = ("; next rung: none — the bin matrix is already "
                    "streaming from host memory (out-of-core tier)")
        else:
            reason = (getattr(self, "_spill_unavail", None)
                      or "escalation was not attempted")
            rung = f"; next rung: spill unavailable: {reason}"
        return LightGBMError(
            f"device out of memory at iteration {self.iter_} even at "
            f"chunk size 1{rung}{hbm} — reduce num_leaves/max_bin or "
            f"shard the data across more devices ({err})")

    def refit(self, leaf_preds: np.ndarray) -> None:
        """Refit leaf outputs on the current training data given per-row
        leaf assignments [N, num_trees] (GBDT::RefitTree via
        LGBM_BoosterRefit, reference c_api.cpp)."""
        self._flush_pending()
        from .refit import refit_model
        refit_model(self, self.train_set.metadata, np.asarray(leaf_preds),
                    self.config)

    def rollback_one_iter(self) -> None:
        """Remove the last iteration's trees and scores (gbdt.cpp:553-576)."""
        self._flush_pending()
        if self.iter_ <= 0:
            return
        C = self.num_tree_per_iteration
        infos = self.train_set.feature_infos()
        for k in reversed(range(C)):
            tree = self.models.pop()
            if tree.num_leaves > 1:
                delta = tree.predict_binned(self.train_set.binned, infos)
                self.train_score = self.train_score.at[k].add(
                    -jnp.asarray(delta, dtype=jnp.float32))
                for (vname, vset), vscore in zip(self.valid_sets,
                                                 self.valid_scores):
                    vscore[k] -= tree.predict_binned(vset.binned, infos)
        self.iter_ -= 1
        # host f64 buffers are now the truth; the device carry is stale
        self._vscores_dev = None

    # ------------------------------------------------------------ prediction
    def current_iteration(self) -> int:
        # flush in-flight trees first: a trailing all-constant iteration is
        # detected (and iter_ lowered) only at materialization time
        self._flush_pending()
        return self.iter_

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def _raw_predict(self, X: np.ndarray, num_iteration: int = -1,
                     start_iteration: int = 0) -> np.ndarray:
        self._flush_pending()
        C = self.num_tree_per_iteration
        n_iter = self.iter_ if num_iteration <= 0 else min(num_iteration,
                                                           self.iter_)
        out = np.zeros((C, X.shape[0]), dtype=np.float64)
        for k in range(C):
            out[k] += self.init_scores[k]
        cfg = self.config
        freq = int(cfg.pred_early_stop_freq)
        # the reference only instantiates early stop for binary/multiclass
        # predictors; regression and ranking need every tree
        es_type_ok = (C > 1 or (self.objective is not None
                                and getattr(self.objective, "name", "")
                                in ("binary", "cross_entropy", "xentropy")))
        if bool(cfg.pred_early_stop) and freq > 0 and es_type_ok:
            # margin-based per-row early stop every `freq` trees
            # (prediction_early_stop.cpp:54-73 binary margin = 2|raw|,
            # :30-49 multiclass margin = top1 - top2)
            thr = float(cfg.pred_early_stop_margin)
            active = np.ones(X.shape[0], dtype=bool)
            for it in range(start_iteration, n_iter):
                if not active.any():
                    break
                Xa = X[active]
                for k in range(C):
                    out[k, active] += self.models[it * C + k].predict_raw(Xa)
                if (it + 1 - start_iteration) % freq == 0:
                    sub = out[:, active]
                    if C == 1:
                        margin = 2.0 * np.abs(sub[0])
                    else:
                        top2 = np.partition(sub, C - 2, axis=0)
                        margin = top2[-1] - top2[-2]
                    idx = np.nonzero(active)[0]
                    active[idx[margin > thr]] = False
            return out
        for it in range(start_iteration, n_iter):
            for k in range(C):
                out[k] += self.models[it * C + k].predict_raw(X)
        return out

    def _device_route_ok(self) -> bool:
        """Whether batch prediction may use the compiled stacked-tensor
        route (models/device_predict.py) instead of the host tree walk.
        Gated by the ``predict_device`` knob ("auto" = accelerator only —
        on CPU the jit round-trip would cost more than the walk), and
        requires the training BinMappers (file-loaded boosters without a
        bound dataset fall back) plus bin-aligned trees.  Per-row early
        stopping (pred_early_stop) is host-only by design."""
        pd = str(getattr(self.config, "predict_device", "off"))
        if pd == "off":
            return False
        if pd == "auto":
            try:
                if jax.default_backend() == "cpu":
                    return False
            except Exception:
                return False
        ds = getattr(self, "train_set", None)
        if ds is None or not getattr(ds, "bin_mappers", None) \
                or len(getattr(ds, "used_feature_indices", ())) == 0:
            return False
        cfg = self.config
        C = self.num_tree_per_iteration
        es_type_ok = (C > 1 or (self.objective is not None
                                and getattr(self.objective, "name", "")
                                in ("binary", "cross_entropy", "xentropy")))
        if (bool(cfg.pred_early_stop) and int(cfg.pred_early_stop_freq) > 0
                and es_type_ok):
            return False
        return all(getattr(t, "bins_aligned", True) for t in self.models)

    def _device_raw_predict(self, X: np.ndarray,
                            num_iteration: int = -1) -> np.ndarray:
        """[C, N] f64 raw scores via device routing, bit-identical to
        ``_raw_predict``: bins come from the exact host ``value_to_bin``,
        the device returns per-tree leaf INDICES, and the float64 leaf
        values are gathered host-side in the host walk's accumulation
        order.  Rows are padded to a power-of-two bucket so repeated
        predict calls reuse a handful of executables."""
        from .device_predict import stack_trees
        ds = self.train_set
        used = np.asarray(ds.used_feature_indices)
        C = self.num_tree_per_iteration
        n_iter = self.iter_ if num_iteration <= 0 else min(num_iteration,
                                                           self.iter_)
        trees = self.models[: n_iter * C]
        N = X.shape[0]
        bins = np.empty((N, len(used)), dtype=np.int32)
        for j, f in enumerate(used):
            m = ds.bin_mappers[int(f)]
            col = X[:, int(f)]
            b = m.value_to_bin(col)
            if m.is_categorical:
                # unseen categories -> -1 sentinel (value_to_bin's
                # num_bin-1 aliases a real bin); the router sends
                # negative categorical bins right like the float walk
                iv = np.where(np.isfinite(col), col, -1).astype(np.int64)
                if m.categorical_2_bin:
                    cats = np.fromiter(m.categorical_2_bin.keys(),
                                       dtype=np.int64)
                    seen = np.isin(iv, cats) & (iv >= 0)
                else:
                    seen = np.zeros(len(iv), dtype=bool)
                b = np.where(seen, b, -1)
            bins[:, j] = b
        bucket = 8
        while bucket < N:
            bucket <<= 1
        if bucket > N:
            bins = np.concatenate(
                [bins, np.zeros((bucket - N, bins.shape[1]),
                                dtype=np.int32)])
        stack = stack_trees(trees, len(used))
        num_bin = jnp.asarray([ds.bin_mappers[int(f)].num_bin
                               for f in used], dtype=jnp.int32)
        default_bin = jnp.asarray([ds.bin_mappers[int(f)].default_bin
                                   for f in used], dtype=jnp.int32)
        fn = _route_seam(stack.max_depth)
        leaves = np.asarray(fn(stack._replace(max_depth=None),
                               jnp.asarray(bins), num_bin,
                               default_bin))[:, :N]
        out = np.zeros((C, N), dtype=np.float64)
        for k in range(C):
            out[k] += self.init_scores[k]
        for t, tree in enumerate(trees):
            out[t % C] += tree.leaf_value[leaves[t]]
        return out

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False) -> np.ndarray:
        self._flush_pending()
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        C = self.num_tree_per_iteration
        if pred_leaf:
            n_iter = self.iter_ if num_iteration <= 0 else min(num_iteration,
                                                               self.iter_)
            leaves = np.zeros((X.shape[0], n_iter * C), dtype=np.int32)
            for i in range(n_iter * C):
                leaves[:, i] = self.models[i].apply_raw(X)
            return leaves
        if self._device_route_ok():
            raw = self._device_raw_predict(X, num_iteration)
        else:
            raw = self._raw_predict(X, num_iteration)
        if getattr(self, "average_output", False):
            n_iter = self.iter_ if num_iteration <= 0 else min(num_iteration,
                                                               self.iter_)
            raw = raw / max(n_iter, 1)
        if raw_score or self.objective is None:
            res = raw
        else:
            res = self.objective.convert_output(raw)
        if C == 1:
            return res[0]
        return res.T  # [N, C]

    # ------------------------------------------------------------------ eval
    def setup_metrics(self, metric_names: Sequence[str]) -> None:
        """Instantiate metrics for train + each valid set
        (GBDT::AddValidDataset / Init metric wiring, gbdt.cpp:49-130)."""
        from ..metric import create_metric
        self.metrics = []
        for name in metric_names:
            m = create_metric(name, self.config)
            if m is not None and self.train_set is not None:
                m.init(self.train_set.metadata, self.train_set.num_data)
                self.metrics.append(m)
        self.valid_metrics = []
        for (vname, vset) in self.valid_sets:
            ms = []
            for name in metric_names:
                m = create_metric(name, self.config)
                if m is not None:
                    m.init(vset.metadata, vset.num_data)
                    ms.append(m)
            self.valid_metrics.append(ms)

    def _eval_score(self, score: np.ndarray, metrics) -> List[Tuple]:
        out = []
        s = score[0] if (score.ndim > 1 and score.shape[0] == 1) else score
        for m in metrics:
            if hasattr(m, "eval_multi"):
                for k, v in zip(m.eval_at, m.eval_multi(s, self.objective)):
                    out.append((f"{m.name}@{k}", float(v), m.higher_better))
            else:
                out.append((m.name, float(m.eval(s, self.objective)),
                            m.higher_better))
        return out

    def eval_train(self) -> List[Tuple]:
        score = np.asarray(self.train_score, dtype=np.float64)
        return self._eval_score(score, self.metrics)

    def eval_valid(self, i: int) -> List[Tuple]:
        return self._eval_score(np.asarray(self.valid_scores[i]),
                                self.valid_metrics[i])

    # ------------------------------------------------------- in-scan eval
    def setup_inscan_eval(self, include_train: bool = False):
        """Try to attach a device-side eval program (metric/device.py) so
        the chunked scan computes the attached metrics per iteration.
        Returns None on success, or a short blocker string ("feval",
        "metric:<name>", "objective:<name>", "not_chunk_capable", ...)
        when the run must fall back to per-iteration host eval."""
        self._inscan = None
        self._vscores_dev = None
        self._inscan_evals = []
        # drop any stale eval-variant compilations (they close over the
        # previous DeviceEval program)
        self._chunk_fns = {k: v for k, v in self._chunk_fns.items()
                           if not isinstance(k, tuple)}
        if not self._chunk_ok():
            return "not_chunk_capable"
        from ..metric.device import build_device_eval
        prog, blocker = build_device_eval(self, include_train)
        if prog is None:
            return blocker
        self._inscan = prog
        return None

    def inscan_result_list(self, vals) -> List[Tuple]:
        """One in-scan metric row -> the eval_train/eval_valid result
        shape: [(set_name, metric_name, value, higher_better)]."""
        return [(sname, mname, float(v), hb)
                for (sname, mname, hb), v in zip(self._inscan.columns,
                                                 vals)]

    def take_inscan_evals(self) -> List[Tuple]:
        """Pop the per-iteration metric rows materialized so far:
        [(iter_idx, np.ndarray[n_cols])], oldest first."""
        out = self._inscan_evals
        self._inscan_evals = []
        return out

    # ----------------------------------------------------------- importances
    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        """split counts or total gains per original feature
        (gbdt.h FeatureImportance)."""
        self._flush_pending()
        n_feat = self.max_feature_idx + 1
        out = np.zeros(n_feat, dtype=np.float64)
        C = self.num_tree_per_iteration
        n_iter = self.iter_ if iteration <= 0 else min(iteration, self.iter_)
        for tree in self.models[: n_iter * C]:
            n = tree.num_leaves - 1
            for i in range(n):
                f = int(tree.split_feature[i])
                if importance_type == "split":
                    out[f] += 1
                else:
                    out[f] += max(float(tree.split_gain[i]), 0.0)
        return out
