"""Model text/JSON serialization, LightGBM-format compatible.

Reference: src/boosting/gbdt_model_text.cpp — SaveModelToString (:250:
header key=values, per-tree blocks, "end of trees", feature importances),
LoadModelFromString, DumpModel (:19, JSON); src/io/tree.cpp Tree::ToString
(:209).  Models saved here load in stock LightGBM and vice versa for the
shared feature set.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import LightGBMError, log_warning
from .tree import Tree

MODEL_VERSION = "v2"


def _fmt(x: float) -> str:
    """Shortest round-trip float formatting (Common::ArrayToString)."""
    return np.format_float_positional(
        float(x), unique=True, trim="0") if np.isfinite(x) else str(x)


def _join(arr, fmt=str) -> str:
    return " ".join(fmt(x) for x in arr)


def _objective_to_string(config: Config, objective) -> str:
    name = config.objective
    if name == "binary":
        return f"binary sigmoid:{_fmt(config.sigmoid)}"
    if name == "multiclass":
        return f"multiclass num_class:{config.num_class}"
    if name == "multiclassova":
        return (f"multiclassova num_class:{config.num_class} "
                f"sigmoid:{_fmt(config.sigmoid)}")
    if name == "lambdarank":
        return "lambdarank"
    return name


def tree_to_string(tree: Tree) -> str:
    nl = tree.num_leaves
    n = nl - 1
    lines = [f"num_leaves={nl}", f"num_cat={tree.num_cat}"]
    if n > 0:
        lines += [
            "split_feature=" + _join(tree.split_feature),
            "split_gain=" + _join(tree.split_gain, _fmt),
            "threshold=" + _join(tree.threshold, _fmt),
            "decision_type=" + _join(tree.decision_type.astype(np.int64)),
            "left_child=" + _join(tree.left_child),
            "right_child=" + _join(tree.right_child),
            "leaf_value=" + _join(tree.leaf_value, _fmt),
            "leaf_weight=" + _join(tree.leaf_weight, _fmt),
            "leaf_count=" + _join(tree.leaf_count),
            "internal_value=" + _join(tree.internal_value, _fmt),
            "internal_weight=" + _join(tree.internal_weight, _fmt),
            "internal_count=" + _join(tree.internal_count),
        ]
        if tree.num_cat > 0:
            flat = np.concatenate(tree.cat_threshold) if tree.cat_threshold \
                else np.zeros(0, dtype=np.uint32)
            flat_inner = (np.concatenate(tree.cat_threshold_inner)
                          if tree.cat_threshold_inner
                          else np.zeros(0, dtype=np.uint32))
            lines += [
                "cat_boundaries=" + _join(tree.cat_boundaries),
                "cat_threshold=" + _join(flat.astype(np.int64)),
                # extension block so binned prediction survives a round-trip
                "cat_boundaries_inner=" + _join(tree.cat_boundaries_inner),
                "cat_threshold_inner=" + _join(flat_inner.astype(np.int64)),
            ]
    else:
        lines += ["leaf_value=" + _join(tree.leaf_value, _fmt)]
    lines.append(f"shrinkage={_fmt(tree.shrinkage)}")
    return "\n".join(lines) + "\n"


def _feature_infos_strings(gbdt) -> List[str]:
    ds = gbdt.train_set
    out = []
    if ds is None:
        return ["none"] * (gbdt.max_feature_idx + 1)
    for f, m in enumerate(ds.bin_mappers):
        if m.is_trivial:
            out.append("none")
        elif m.is_categorical:
            out.append(":".join(str(c) for c in sorted(m.bin_2_categorical)))
        else:
            out.append(f"[{_fmt(m.min_val)}:{_fmt(m.max_val)}]")
    return out


def save_model_to_string(gbdt, config: Config, num_iteration: int = -1,
                         start_iteration: int = 0) -> str:
    C = gbdt.num_tree_per_iteration
    total_iter = len(gbdt.models) // max(C, 1)
    start_iteration = min(max(start_iteration, 0), total_iter)
    if num_iteration > 0:
        end_iter = min(start_iteration + num_iteration, total_iter)
    else:
        end_iter = total_iter
    lines = ["tree", f"version={MODEL_VERSION}",
             f"num_class={config.num_class}",
             f"num_tree_per_iteration={C}",
             "label_index=0",
             f"max_feature_idx={gbdt.max_feature_idx}",
             f"objective={_objective_to_string(config, gbdt.objective)}"]
    if getattr(gbdt, "average_output", False):
        lines.append("average_output")
    lines.append("feature_names=" + " ".join(gbdt.feature_names))
    lines.append("feature_infos=" + " ".join(_feature_infos_strings(gbdt)))

    def _tree_for_save(i: int):
        """Boost-from-average is a bias folded into the FIRST SAVED
        iteration's leaves (gbdt.cpp:503 AddBias, shrinkage forced to
        1.0), so the model file is self-contained and the reference CLI
        reads it back bit-identically; in memory the bias stays separate
        (GBDT.init_scores) and is added at predict time.  Sliced saves
        (start_iteration > 0) fold into their own first iteration too:
        every file reproduces "its trees + the init score", matching
        what predicting with the in-memory booster over those iterations
        returns."""
        t = gbdt.models[i]
        first_saved = (i - start_iteration * C) < C
        init = (gbdt.init_scores[i % C] if first_saved
                and (i % C) < len(gbdt.init_scores) else 0.0)
        if abs(init) < 1e-35:
            return t
        import copy
        biased = copy.copy(t)
        biased.leaf_value = np.asarray(t.leaf_value, dtype=np.float64) + init
        biased.shrinkage = 1.0
        return biased

    tree_strs = []
    for i in range(start_iteration * C, end_iter * C):
        s = f"Tree={i - start_iteration * C}\n" + tree_to_string(
            _tree_for_save(i)) + "\n"
        tree_strs.append(s)
    lines.append("tree_sizes=" + _join(len(s) for s in tree_strs))
    lines.append("")
    body = "\n".join(lines) + "\n" + "".join(tree_strs) + "end of trees\n"

    imps = gbdt.feature_importance("split")
    pairs = sorted(
        [(int(v), gbdt.feature_names[i]) for i, v in enumerate(imps) if v > 0],
        key=lambda p: -p[0])
    body += "\nfeature importances:\n"
    for v, name in pairs:
        body += f"{name}={v}\n"
    body += "\nparameters:\n"
    from ..config import RUNTIME_ONLY_PARAMS, resolve_alias
    for k, v in (config.raw or {}).items():
        # runtime-only knobs (resume, fault_injection) describe this
        # process, not the model: a resume=true rerun must save a file
        # byte-identical to the uninterrupted run's
        if resolve_alias(k) in RUNTIME_ONLY_PARAMS:
            continue
        body += f"[{k}: {v}]\n"
    body += "end of parameters\n"
    return body


def tree_from_block(block: str) -> Tree:
    kv: Dict[str, str] = {}
    for line in block.strip().splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k.strip()] = v.strip()
    nl = int(kv["num_leaves"])
    t = Tree(nl)
    t.shrinkage = float(kv.get("shrinkage", 1.0))
    t.num_cat = int(kv.get("num_cat", 0))

    def arr(key, dtype, size):
        if key not in kv or not kv[key]:
            return np.zeros(size, dtype=dtype)
        return np.asarray(kv[key].split(), dtype=np.float64).astype(dtype)

    t.leaf_value = arr("leaf_value", np.float64, nl)

    n = nl - 1
    if n > 0:
        t.split_feature = arr("split_feature", np.int32, n)
        t.split_feature_inner = t.split_feature.copy()
        t.split_gain = arr("split_gain", np.float32, n)
        t.threshold = arr("threshold", np.float64, n)
        t.decision_type = arr("decision_type", np.int8, n)
        t.left_child = arr("left_child", np.int32, n)
        t.right_child = arr("right_child", np.int32, n)
        t.leaf_weight = arr("leaf_weight", np.float64, nl)
        t.leaf_count = arr("leaf_count", np.int64, nl)
        t.internal_value = arr("internal_value", np.float64, n)
        t.internal_weight = arr("internal_weight", np.float64, n)
        t.internal_count = arr("internal_count", np.int64, n)
        # real thresholds only until a dataset remap (_remap_tree_to_bins);
        # flag keeps binned prediction from routing on these placeholders
        t.threshold_in_bin = np.zeros(n, dtype=np.int32)
        t.bins_aligned = False
        if t.num_cat > 0:
            bounds = arr("cat_boundaries", np.int64, t.num_cat + 1)
            words = arr("cat_threshold", np.int64, 0).astype(np.uint32)
            t.cat_boundaries = [int(b) for b in bounds]
            t.cat_threshold = [words[bounds[i]:bounds[i + 1]]
                               for i in range(t.num_cat)]
            if "cat_boundaries_inner" in kv:
                bi = arr("cat_boundaries_inner", np.int64, t.num_cat + 1)
                wi = arr("cat_threshold_inner", np.int64, 0).astype(np.uint32)
                t.cat_boundaries_inner = [int(b) for b in bi]
                t.cat_threshold_inner = [wi[bi[i]:bi[i + 1]]
                                         for i in range(t.num_cat)]
            # categorical nodes store the cat index in threshold
            for i in range(n):
                if t.decision_type[i] & 1:
                    t.threshold_in_bin[i] = int(t.threshold[i])
    return t


def _parse_objective_string(s: str) -> Tuple[str, Dict[str, str]]:
    parts = s.split()
    args = {}
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            args[k] = v
    return parts[0], args


class LoadedBoosting:
    """Prediction-only boosting reconstructed from a model string; reuses
    GBDT's prediction/importance methods over the same attribute surface."""

    def __init__(self):
        self.models: List[Tree] = []
        self.num_tree_per_iteration = 1
        self.init_scores: List[float] = []
        self.feature_names: List[str] = []
        self.max_feature_idx = 0
        self.objective = None
        self.iter_ = 0
        self.average_output = False
        self.train_set = None
        self.config: Optional[Config] = None

    def current_iteration(self) -> int:
        return self.iter_

    def _flush_pending(self, keep_latest: int = 0) -> None:
        """No async tree pipeline on a loaded model (GBDT API compat)."""

    def _raw_predict(self, X, num_iteration=-1, start_iteration=0):
        from .gbdt import GBDT
        return GBDT._raw_predict(self, X, num_iteration, start_iteration)

    def _device_route_ok(self):
        # always False here (no train_set -> no bin mappers to bin
        # predict inputs with), but routed through the one impl
        from .gbdt import GBDT
        return GBDT._device_route_ok(self)

    def _device_raw_predict(self, X, num_iteration=-1):
        from .gbdt import GBDT
        return GBDT._device_raw_predict(self, X, num_iteration)

    def predict(self, X, num_iteration=-1, raw_score=False, pred_leaf=False,
                pred_contrib=False):
        from .gbdt import GBDT
        return GBDT.predict(self, X, num_iteration, raw_score, pred_leaf,
                            pred_contrib)

    def feature_importance(self, importance_type="split", iteration=-1):
        from .gbdt import GBDT
        return GBDT.feature_importance(self, importance_type, iteration)


def load_model(model_str: str):
    """Parse a model string -> (LoadedBoosting, Config, objective)."""
    from .gbdt import GBDT
    header, _, rest = model_str.partition("\nTree=0")
    if not rest:
        raise LightGBMError("Model format error: no trees found")
    kv: Dict[str, str] = {}
    for line in header.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k.strip()] = v.strip()
        elif line.strip() == "average_output":
            kv["average_output"] = "1"
    out = LoadedBoosting()
    out.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
    out.max_feature_idx = int(kv.get("max_feature_idx", 0))
    # cap name length at the C-bridge buffer bound (LGBMTPU_MAX_NAME,
    # R-package shim / strings_out consumers copy into 4096-byte
    # buffers): an externally-authored model must not be able to
    # overflow them through a pathological feature_names line
    out.feature_names = [n[:4095] for n in
                         kv.get("feature_names", "").split()]
    out.average_output = "average_output" in kv
    if "init_scores" in kv and kv["init_scores"]:
        out.init_scores = [float(x) for x in kv["init_scores"].split()]
    else:
        out.init_scores = [0.0] * out.num_tree_per_iteration

    obj_name, obj_args = _parse_objective_string(
        kv.get("objective", "regression"))
    cfg_kwargs = {"objective": obj_name}
    if "num_class" in obj_args:
        cfg_kwargs["num_class"] = int(obj_args["num_class"])
    if "sigmoid" in obj_args:
        cfg_kwargs["sigmoid"] = float(obj_args["sigmoid"])
    config = Config.from_params(cfg_kwargs)
    from ..objective import create_objective
    objective = create_objective(config)

    trees_part = "Tree=0" + rest
    trees_part = trees_part.split("end of trees")[0]
    blocks = trees_part.split("Tree=")
    for block in blocks:
        block = block.strip()
        if not block:
            continue
        _, _, body = block.partition("\n")
        out.models.append(tree_from_block(body))
    out.iter_ = len(out.models) // max(out.num_tree_per_iteration, 1)
    out.objective = objective
    out.config = config
    # give the objective a convert_output without metadata init
    return out, config, objective


def load_trees_into(gbdt, init_booster, raw_data=None) -> None:
    """Continued training: seed a fresh GBDT with an existing model's trees
    (boosting.cpp:53-74 model-file continuation).  Init scores for the new
    training data are computed by predicting with the loaded model
    (application.cpp:89-92): on RAW feature values when available, else by
    re-mapping each tree's real-valued thresholds into the new dataset's bins
    (exact whenever the threshold is a bin boundary, which holds for
    same-distribution data)."""
    src = init_booster.gbdt
    C = gbdt.num_tree_per_iteration
    if src.num_tree_per_iteration != C:
        raise LightGBMError("init model has different num_tree_per_iteration")
    import jax.numpy as jnp
    gbdt.init_scores = list(src.init_scores)
    for k in range(C):
        gbdt.train_score = gbdt.train_score.at[k].add(
            float(src.init_scores[k]))
    if raw_data is not None:
        raw = np.asarray(raw_data, dtype=np.float64)
        deltas = [sum(src.models[it * C + k].predict_raw(raw)
                      for it in range(src.iter_)) for k in range(C)]
    else:
        ds = gbdt.train_set
        infos = ds.feature_infos()
        deltas = []
        for k in range(C):
            total = np.zeros(gbdt.num_data)
            for it in range(src.iter_):
                tree = src.models[it * C + k]
                if tree.num_leaves <= 1:
                    total += tree.leaf_value[0]
                    continue
                remapped = _remap_tree_to_bins(tree, ds)
                total += remapped.predict_binned(ds.binned, infos)
            deltas.append(total)
    for k in range(C):
        gbdt.train_score = gbdt.train_score.at[k].add(
            jnp.asarray(deltas[k], dtype=jnp.float32))
    for it in range(src.iter_):
        for k in range(C):
            tree = src.models[it * C + k]
            # keep the stored copies bin-aligned with the live dataset so
            # later binned passes (eval/rollback/DART) can route them
            if not tree.bins_aligned and gbdt.train_set is not None:
                tree = _remap_tree_to_bins(tree, gbdt.train_set)
            gbdt.models.append(tree)
    gbdt.iter_ += src.iter_
    gbdt._boosted_from_average = True


def _remap_tree_to_bins(tree: Tree, ds) -> Tree:
    """Rewrite a tree's inner (bin-space) split data against dataset ``ds``:
    numerical thresholds via BinMapper::ValueToBin of the stored real
    threshold (exact — Tree thresholds ARE bin upper bounds,
    Dataset::RealThreshold), categorical raw-value bitsets re-expressed
    over ``ds``'s category bins when the model file lacks the inner-bitset
    extension block (stock LightGBM files)."""
    import copy
    t = copy.copy(tree)
    n = tree.num_leaves - 1
    t.split_feature_inner = np.asarray(
        [ds.inner_feature_index(int(f)) for f in tree.split_feature],
        dtype=np.int32)
    thr = np.zeros(n, dtype=np.int32)
    rebuild_inner = (tree.num_cat > 0
                     and not getattr(tree, "cat_threshold_inner", None))
    if rebuild_inner:
        t.cat_threshold_inner = [None] * tree.num_cat
    for i in range(n):
        f = int(tree.split_feature[i])
        if tree.decision_type[i] & 1:
            # categorical nodes keep the cat-table index (the loader stores
            # it in threshold_in_bin for both our and stock model files)
            cat_idx = int(tree.threshold_in_bin[i])
            thr[i] = cat_idx
            if rebuild_inner:
                words = tree.cat_threshold[cat_idx]
                mapper = ds.bin_mappers[f]
                # sized by the MAPPER's bin count, not the raw bitset;
                # categories the new dataset never saw have no bin and are
                # skipped (value_to_bin's fallback would alias an
                # unrelated bin)
                inner = np.zeros(max(1, -(-mapper.num_bin // 32)),
                                 dtype=np.uint32)
                for c in range(len(words) * 32):
                    if words[c // 32] >> (c % 32) & 1:
                        b = mapper.categorical_2_bin.get(c)
                        if b is not None:
                            inner[b // 32] |= np.uint32(1 << (b % 32))
                t.cat_threshold_inner[cat_idx] = inner
            continue
        mapper = ds.bin_mappers[f]
        thr[i] = int(mapper.value_to_bin(
            np.asarray([tree.threshold[i]]))[0])
    if rebuild_inner:
        t.cat_threshold_inner = [w if w is not None
                                 else np.zeros(1, dtype=np.uint32)
                                 for w in t.cat_threshold_inner]
        # boundaries must describe the REBUILT word arrays (sized by the
        # mapper's bins), not the raw-category ones — a save/reload slices
        # the flattened inner words by these offsets
        bounds = [0]
        for w in t.cat_threshold_inner:
            bounds.append(bounds[-1] + len(w))
        t.cat_boundaries_inner = bounds
    t.threshold_in_bin = thr
    t.bins_aligned = True
    return t


def dump_model_dict(gbdt, config: Config, num_iteration: int = -1) -> Dict:
    """JSON model dump (GBDT::DumpModel, gbdt_model_text.cpp:19-64)."""
    C = gbdt.num_tree_per_iteration
    n_iter = (gbdt.iter_ if num_iteration <= 0
              else min(num_iteration, gbdt.iter_))

    def node_dict(tree: Tree, node: int) -> Dict:
        if node < 0:
            leaf = ~node
            return {
                "leaf_index": int(leaf),
                "leaf_value": float(tree.leaf_value[leaf]),
                "leaf_weight": float(tree.leaf_weight[leaf])
                if leaf < len(tree.leaf_weight) else 0.0,
                "leaf_count": int(tree.leaf_count[leaf])
                if leaf < len(tree.leaf_count) else 0,
            }
        dt = int(tree.decision_type[node])
        is_cat = bool(dt & 1)
        d = {
            "split_index": int(node),
            "split_feature": int(tree.split_feature[node]),
            "split_gain": float(tree.split_gain[node]),
            "threshold": (float(tree.threshold[node]) if not is_cat else
                          "||".join(str(c) for c in _cats_of(tree, node))),
            "decision_type": "==" if is_cat else "<=",
            "default_left": bool(dt & 2),
            "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
            "internal_value": float(tree.internal_value[node]),
            "internal_weight": float(tree.internal_weight[node]),
            "internal_count": int(tree.internal_count[node]),
            "left_child": node_dict(tree, int(tree.left_child[node])),
            "right_child": node_dict(tree, int(tree.right_child[node])),
        }
        return d

    def _cats_of(tree: Tree, node: int) -> List[int]:
        cat_idx = int(tree.threshold_in_bin[node])
        words = tree.cat_threshold[cat_idx]
        return [b for b in range(len(words) * 32)
                if words[b // 32] >> (b % 32) & 1]

    trees = []
    for i in range(n_iter * C):
        t = gbdt.models[i]
        td = {
            "tree_index": i,
            "num_leaves": int(t.num_leaves),
            "num_cat": int(t.num_cat),
            "shrinkage": float(t.shrinkage),
        }
        if t.num_leaves > 1:
            td["tree_structure"] = node_dict(t, 0)
        else:
            td["tree_structure"] = {"leaf_value": float(t.leaf_value[0])}
        trees.append(td)
    return {
        "name": "tree",
        "version": MODEL_VERSION,
        "num_class": config.num_class,
        "num_tree_per_iteration": C,
        "label_index": 0,
        "max_feature_idx": gbdt.max_feature_idx,
        "objective": _objective_to_string(config, gbdt.objective),
        "average_output": bool(getattr(gbdt, "average_output", False)),
        "feature_names": list(gbdt.feature_names),
        "feature_importances": {
            name: int(v) for name, v in zip(
                gbdt.feature_names, gbdt.feature_importance("split"))
            if v > 0},
        "tree_info": trees,
    }
