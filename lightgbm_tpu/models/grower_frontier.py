"""Frontier-batched segment grower: K splits per round, one batched
histogram kernel call.

The strict best-first segment grower (grower_seg.py) histograms ONE
leaf's smaller child per split, so the one-hot matmul's output is 8
channels wide and the MXU runs at ~6% utilization (PERF_NOTES round 3:
2.65 ns/row is that design's ceiling).  This grower splits the TOP-K
leaves of the candidate pool per round and computes all K smaller-child
histograms in a single ``histogram_frontier`` call whose matmul output
carries K x 8 = 128 channels — a full MXU lane tile — over the UNION of
the K leaves' confinement blocks (a prefetched block list, so DMA is
proportional to the union, with sibling leaves sharing blocks).

Semantics: "batched best-first".  Each round splits the K highest-gain
leaves of the pool simultaneously; with K=1 the tree is exactly the
strict best-first tree.  For K>1 a round may split a leaf that strict
best-first would have starved in favor of a just-created child, so trees
can differ slightly — the same locally-greedy family as the reference's
leaf-wise growth, traded for a 16x denser matmul.  Opt-in via
``tpu_tree_impl=frontier`` (config.py); the default remains the strict
grower.  The reference has no equivalent switch: its GPU learner
(src/treelearner/gpu_tree_learner.cpp) keeps strict leaf-wise order and
pays per-leaf kernel launches instead.

Distributed: parallel/learners.make_data_parallel_frontier_grower runs
this grower under shard_map — rows sharded, the whole [K, G, B, 3] batch
reduce-scattered in ONE collective per round (K x fewer collective
launches than the strict grower), and all 2K children's SplitInfos
merged in one all_gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

import os as _os

from ..ops.pallas_histogram import (_segment_buckets, frontier_width,
                                    fused_packed_optin,
                                    fused_route_decisions,
                                    fused_route_policy, histogram_frontier,
                                    histogram_frontier_fusedk,
                                    histogram_frontier_routed, null_route,
                                    pack_channels, pack_route,
                                    packed_acc_bits, packed_acc_decisions,
                                    packed_acc_enabled,
                                    quantize_pack_channels,
                                    segment_grid_size, unpack_hist,
                                    unpack_hist_packed)
from ..ops.split import (NEG_INF, FeatureMeta, best_split,
                         expand_group_hist)
from .grower import (GrowerParams, _node_feature_mask, mono_handoff)
from .grower_seg import (COMPACT_WASTE, _COMPACT_MUT, _SegState,
                         _unpermute, apply_route, compact_state,
                         cond_narrow, fresh_state, stripe_histogram)

# build-time decision, keyed "frontier" — benches read whether the
# round-carry stage actually ran (env gate + self-check + serial-only
# make the bare env value misleading)
hist_stage_decisions: dict = {}

_HIST_STAGE_CHECK: bool | None = None


def hist_stage_enabled() -> bool:
    """Whether frontier rounds should keep the round's parent/child
    histograms in the small ``[2K, G, B, 3]`` carry stage instead of
    gather/scatter against the full ``[L, G, B, 3]`` leaf_hist twice per
    round (``LIGHTGBM_TPU_HIST_STAGE``).

    Default OFF — no variant flips to default without a v5e number.
    ``1/on`` runs the one-shot bit-identity self-check (staged vs
    unstaged grow of the same tree) and falls back when it fails;
    ``force`` bypasses the check for on-chip A/B plumbing.  Serial-only
    either way: the distributed wrappers keep the direct carry."""
    global _HIST_STAGE_CHECK
    env = _os.environ.get("LIGHTGBM_TPU_HIST_STAGE", "").lower()
    if env in ("", "0", "off", "false"):
        return False
    if env == "force":
        return True
    if _HIST_STAGE_CHECK is None:
        try:
            _HIST_STAGE_CHECK = _hist_stage_self_check()
        except Exception:
            import sys
            import traceback
            sys.stderr.write("hist-stage self-check raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _HIST_STAGE_CHECK = False
    return _HIST_STAGE_CHECK


def _hist_stage_self_check() -> bool:
    """Round-carry staging must be BIT-identical: grow the same tree
    staged and unstaged (explicit ``hist_stage=`` overrides, so the env
    gate is bypassed and no recursion happens) and compare every tree
    array and the returned leaf_id exactly."""
    import numpy as np

    from ..ops.split import SplitParams

    rng = np.random.default_rng(23)
    n, F, B, L, rb, k = 1024, 4, 16, 8, 256, 3
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    grad = jnp.asarray(
        (-(np.asarray(binsT)[0] >= B // 2).astype(np.float32)
         - 0.5 * (np.asarray(binsT)[1] % 3 == 0)
         + 0.1 * rng.standard_normal(n)), jnp.float32)
    hess = jnp.ones(n, jnp.float32)
    member = jnp.asarray((rng.random(n) < 0.9).astype(np.float32))
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    fmask = jnp.ones(F, jnp.float32)
    key = jax.random.PRNGKey(0)
    params = GrowerParams(num_leaves=L, hist_backend="pallas",
                          split=SplitParams(min_data_in_leaf=2.0))

    outs = []
    for staged in (False, True):
        grow = make_grow_tree_frontier(B, params, rb, batch_k=k,
                                       hist_stage=staged)
        outs.append(grow(binsT, grad, hess, member, fmeta, fmask, key))
    (tree_a, lid_a, _), (tree_b, lid_b, _) = outs
    if not np.array_equal(np.asarray(lid_a), np.asarray(lid_b)):
        return False
    for fa, fb in zip(jax.tree_util.tree_leaves(tree_a),
                      jax.tree_util.tree_leaves(tree_b)):
        if not np.array_equal(np.asarray(fa), np.asarray(fb)):
            return False
    return True


def make_grow_tree_frontier(num_bins: int, params: GrowerParams,
                            block_rows: int, batch_k: int = 0,
                            gain_ratio: float = 0.0,
                            comm=None, wrap=None, hist_stage=None,
                            fused_k=None):
    """Build the jitted frontier-batched grower.

    Same call contract as make_grow_tree_segment:
    ``grow(binsT, grad, hess, member, fmeta, feature_mask, key)`` ->
    ``(TreeArrays, leaf_id_original_order)``.

    ``comm`` (CommHooks) makes this the data-parallel learner's core
    under shard_map: ``reduce_hist_batch`` reduce-scatters the whole
    [K, G, B, 3] batch in one collective, ``merge_split_batch`` merges
    all 2K children's SplitInfos by max gain in one all_gather.
    """
    from .grower import CommHooks
    p = params
    L = p.num_leaves
    B = num_bins
    rb = block_rows
    comm = comm or CommHooks()
    K = batch_k or frontier_width(
        p.num_columns or 64, B)
    K = max(1, min(K, L - 1))
    # a ratio above 1 would gate out even the round-best leaf and hang
    # the growth loop; config validates, this clamp guards direct callers
    gain_ratio = min(max(float(gain_ratio), 0.0), 1.0)
    # packed int16 accumulator stream (build-time decision — env inside
    # the jitted grow would poison the jit cache).  One quantize per
    # TREE; every unpack happens BEFORE the batch collectives, so
    # distributed reductions only ever see real-unit histograms.
    packed_acc = packed_acc_enabled()
    qbits = packed_acc_bits()
    packed_acc_decisions["frontier"] = packed_acc
    # serial := no distributed hooks.  Both the round-carry stage and
    # the fused-K pass require it: the wrappers' reduce/stripe hooks
    # read the full carry / per-child batches.
    serial = (comm.reduce_hist_batch is None and comm.column_block is None
              and not comm.no_subtract)
    # fused route+histogram tiers (fused_route_policy): "fusedk" folds
    # the round's K route updates AND all 2K children's histograms into
    # ONE pass (LIGHTGBM_TPU_FUSED_K) — no parent gather, no
    # subtraction trick, so the arithmetic bit-matches the no_subtract
    # path; "k1" is the legacy K==1 fused route.  Feature-parallel
    # stripes keep the unfused pair — the histogram scans a column
    # slice, the route needs the full matrix.  The packed stream keeps
    # the unfused pair unless LIGHTGBM_TPU_FUSED_PACKED opts the
    # combined variant in for A/B (docs/KERNELS.md).  An explicit
    # ``fused_k=`` (tests, self-checks) bypasses the env gate.
    packed_ok = not packed_acc or fused_packed_optin()
    fused_tier = fused_route_policy(K, p.num_columns or 64, B, rb,
                                    p.packed4)
    if fused_k is None:
        fused_k = fused_tier == "fusedk"
    fused_k = bool(fused_k) and serial and packed_ok
    fused_route = (fused_tier == "k1" and not fused_k
                   and comm.column_block is None and packed_ok)
    fused_route_decisions["frontier"] = ("fusedk" if fused_k
                                         else fused_route)
    # round-carry leaf-hist staging: serial-only (the distributed
    # wrappers' reduce/stripe hooks read the full carry); an explicit
    # ``hist_stage=`` (the self-check) bypasses the env gate.  Under
    # fused-K there is nothing to stage — no round ever reads leaf_hist
    # (both children come from data), so the staging cond would only
    # add latency.
    if hist_stage is None:
        hist_stage = hist_stage_enabled()
    hist_stage = bool(hist_stage) and serial and not fused_k
    hist_stage_decisions["frontier"] = hist_stage
    from ..ops.pallas_histogram import route_kernel_available
    route_kernel = route_kernel_available()

    def _one_scan(st, hist, g, h, c, depth, fmeta, fmask, key, step,
                  lo, hi):
        fmask_node = _node_feature_mask(fmask, key, step, p)
        if comm.shard_feature_mask is not None:
            fmask_node = comm.shard_feature_mask(fmask_node)
        adjust = None
        if p.cegb_penalty_split > 0.0 or p.use_cegb_coupled:
            from .grower import _cegb_split_coupled_adjust
            adjust = _cegb_split_coupled_adjust(st.feat_used, c, fmeta, p)
        hist = expand_group_hist(hist, fmeta, g, h, c)
        info = best_split(hist, g, h, c, fmeta, p.split, fmask_node,
                          mono_lo=lo if p.use_monotone else None,
                          mono_hi=hi if p.use_monotone else None,
                          gain_adjust=adjust)
        gain = info.gain
        if p.max_depth > 0:
            gain = jnp.where(depth >= p.max_depth, NEG_INF, gain)
        return info, gain

    def _write_scans(st: _SegState, leaf_idx, infos, gains):
        f32 = jnp.stack([gains, infos.left_g, infos.left_h, infos.left_c,
                         infos.left_out, infos.right_out],
                        axis=-1).astype(jnp.float32)
        i32 = jnp.stack([infos.feature, infos.threshold,
                         infos.default_left.astype(jnp.int32),
                         infos.is_cat.astype(jnp.int32)], axis=-1)
        return st._replace(
            best_f32=st.best_f32.at[leaf_idx].set(f32, mode="drop"),
            best_i32=st.best_i32.at[leaf_idx].set(i32, mode="drop"),
            best_cat_bitset=st.best_cat_bitset.at[leaf_idx].set(
                infos.cat_bitset, mode="drop"),
        )

    def compact(st: _SegState) -> _SegState:
        return compact_state(st, L, rb)

    def grow(binsT, grad, hess, member, fmeta: FeatureMeta, feature_mask,
             key, root_hist=None):
        # ``root_hist`` [G, B, 3]: externally-computed root histogram
        # (multiclass batched roots); serial only, like grower_seg
        n_phys, n = binsT.shape
        G_cols = p.num_columns or (2 * n_phys if p.packed4 else n_phys)
        F = fmeta.num_bin.shape[0]
        assert n % rb == 0, (n, rb)
        max_blocks = n // rb
        fpad = (-n_phys) % 4
        if fpad:
            binsT = jnp.pad(binsT, ((0, fpad), (0, 0)))

        if packed_acc:
            w8, qscales, qclips = quantize_pack_channels(
                grad, hess, member, bits=qbits)
        else:
            w8 = pack_channels(grad, hess, member)
            qscales, qclips = None, jnp.int32(0)
        G0 = jnp.sum(grad * member)
        H0 = jnp.sum(hess * member)
        C0 = jnp.sum(member)
        if comm.reduce_stats is not None:
            G0, H0, C0 = (comm.reduce_stats(G0), comm.reduce_stats(H0),
                          comm.reduce_stats(C0))
        all_blocks = jnp.arange(max_blocks, dtype=jnp.int32)
        # grid-step accounting (same rule as histogram_frontier's dispatch)
        bucket_arr = jnp.asarray(_segment_buckets(max_blocks), jnp.int32)

        def grid_of(nb):
            return segment_grid_size(bucket_arr, nb)

        def hist_batch(st: _SegState, targets, block_list, n_blocks,
                       routes=None, fmeta=None):
            """[K] targets (-1 = skip) -> (st, [K, G, B, 3]) over the
            union.  ``routes`` [K, 19] applies the round's K split routes
            inside the kernel (fused path) and updates st.leaf_id."""
            if comm.column_block is not None:
                # feature-parallel: batch-histogram only this shard's
                # column stripe (grower_seg.stripe_histogram)
                start, ncols = comm.column_block(st.binsT)
                out = stripe_histogram(
                    st.binsT, start, ncols,
                    lambda sub: histogram_frontier(
                        sub, st.w8, st.leaf_id, block_list, n_blocks,
                        targets, B, rb, packed4=p.packed4),
                    feat_axis=1)
            elif routes is not None:
                lid, out = histogram_frontier_routed(
                    st.binsT, st.w8, st.leaf_id, block_list, n_blocks,
                    targets, routes, B, rb, K, packed4=p.packed4)
                st = st._replace(leaf_id=lid)
            else:
                out = histogram_frontier(st.binsT, st.w8, st.leaf_id,
                                         block_list, n_blocks, targets, B,
                                         rb, packed4=p.packed4)
            h = (unpack_hist_packed(out[:, :G_cols], qscales)
                 if packed_acc else unpack_hist(out[:, :G_cols]))
            if comm.reduce_hist_batch is not None:
                h = comm.reduce_hist_batch(h, fmeta)
            return st, h

        def hist_batch_fusedk(st: _SegState, targets2, block_list,
                              n_blocks, routes):
            """[2K] child targets (-1 = skip) -> (st, [2K, G, B, 3]):
            ONE pass applies the round's K routes and accumulates every
            child's histogram from the updated ids (serial-only; the
            decision block guarantees no distributed hooks here)."""
            lid, out = histogram_frontier_fusedk(
                st.binsT, st.w8, st.leaf_id, block_list, n_blocks,
                targets2, routes, B, rb, K, packed4=p.packed4)
            st = st._replace(leaf_id=lid)
            h = (unpack_hist_packed(out[:, :G_cols], qscales)
                 if packed_acc else unpack_hist(out[:, :G_cols]))
            return st, h

        def apply_split(st: _SegState, leaf, new_leaf, node):
            """Routing + tree-array bookkeeping for ONE split (the cheap
            per-split work; histograms and scans happen batched)."""
            bi = st.best_i32[leaf]
            bf = st.best_f32[leaf]
            f = bi[0]
            t = bi[1]
            dl = bi[2].astype(bool)
            cat = bi[3].astype(bool)
            bitset = st.best_cat_bitset[leaf]

            lo, hi = st.leaf_lo[leaf], st.leaf_hi[leaf]
            if not (fused_route or fused_k):
                # routing confined to the parent's inherited block
                # interval (grower_seg.route_split_windowed); the fused
                # path routes inside the batched histogram kernel instead
                leaf_id = apply_route(
                    st.binsT, st.leaf_id, fmeta, p.packed4, rb,
                    f, t, dl, cat, bitset, leaf, new_leaf, lo, hi - lo,
                    route_kernel)
                st = st._replace(leaf_id=leaf_id)

            Gl, Hl, Cl = bf[1], bf[2], bf[3]
            Gp, Hp, Cp = st.leaf_g[leaf], st.leaf_h[leaf], st.leaf_c[leaf]
            Gr, Hr, Cr = Gp - Gl, Hp - Hl, Cp - Cl

            st = st._replace(
                leaf_lo=st.leaf_lo.at[new_leaf].set(lo),
                leaf_hi=st.leaf_hi.at[new_leaf].set(hi),
            )
            if p.use_monotone:
                lo_l, hi_l, lo_r, hi_r = mono_handoff(
                    st.leaf_mono_lo[leaf], st.leaf_mono_hi[leaf],
                    bf[4], bf[5], fmeta.monotone[f], cat)
                st = st._replace(
                    leaf_mono_lo=st.leaf_mono_lo
                    .at[leaf].set(lo_l).at[new_leaf].set(lo_r),
                    leaf_mono_hi=st.leaf_mono_hi
                    .at[leaf].set(hi_l).at[new_leaf].set(hi_r),
                )
            if p.use_cegb_coupled:
                st = st._replace(feat_used=st.feat_used.at[f].set(1.0))

            depth_child = st.tree.leaf_depth[leaf] + 1
            tree = st.tree
            parent = tree.leaf_parent[leaf]
            pl_ = jnp.where((parent >= 0)
                            & (tree.left_child[jnp.maximum(parent, 0)]
                               == ~leaf),
                            node, tree.left_child[jnp.maximum(parent, 0)])
            pr = jnp.where((parent >= 0)
                           & (tree.right_child[jnp.maximum(parent, 0)]
                              == ~leaf),
                           node, tree.right_child[jnp.maximum(parent, 0)])
            left_child = tree.left_child.at[jnp.maximum(parent, 0)].set(pl_)
            right_child = (tree.right_child.at[jnp.maximum(parent, 0)]
                           .set(pr))
            left_child = left_child.at[node].set(~leaf)
            right_child = right_child.at[node].set(~new_leaf)

            tree = tree._replace(
                num_leaves=tree.num_leaves + 1,
                split_feature=tree.split_feature.at[node].set(f),
                threshold_bin=tree.threshold_bin.at[node].set(t),
                default_left=tree.default_left.at[node].set(dl),
                is_cat=tree.is_cat.at[node].set(cat),
                cat_bitset=tree.cat_bitset.at[node].set(bitset),
                left_child=left_child,
                right_child=right_child,
                split_gain=tree.split_gain.at[node].set(bf[0]),
                internal_value=tree.internal_value.at[node].set(
                    tree.leaf_value[leaf]),
                internal_weight=tree.internal_weight.at[node].set(Hp),
                internal_count=tree.internal_count.at[node].set(Cp),
                leaf_value=(tree.leaf_value.at[leaf].set(bf[4])
                            .at[new_leaf].set(bf[5])),
                leaf_weight=(tree.leaf_weight.at[leaf].set(Hl)
                             .at[new_leaf].set(Hr)),
                leaf_count=(tree.leaf_count.at[leaf].set(Cl)
                            .at[new_leaf].set(Cr)),
                leaf_parent=(tree.leaf_parent.at[leaf].set(node)
                             .at[new_leaf].set(node)),
                leaf_depth=(tree.leaf_depth.at[leaf].set(depth_child)
                            .at[new_leaf].set(depth_child)),
            )
            st = st._replace(
                num_leaves=st.num_leaves + 1,
                leaf_g=st.leaf_g.at[leaf].set(Gl).at[new_leaf].set(Gr),
                leaf_h=st.leaf_h.at[leaf].set(Hl).at[new_leaf].set(Hr),
                leaf_c=st.leaf_c.at[leaf].set(Cl).at[new_leaf].set(Cr),
                tree=tree,
            )
            return st

        def round_body(carry):
            st, stage_ids, stage_hist, s_hits, s_looks, fk_rounds = carry
            base = st.num_leaves
            budget = L - base
            gains_top, leaves_top = lax.top_k(st.best_f32[:, 0], K)
            # positive-gain prefix, clipped to the leaf budget; top_k
            # sorts descending so validity is a prefix and new leaf ids
            # are base + j.  The gain-ratio gate only batches leaves
            # comparable to the round's best: a dominant leaf grows
            # strictly best-first, a flat pool batches fully.
            valid = (gains_top > 0.0) & (jnp.arange(K) < budget)
            if gain_ratio > 0.0:
                valid &= gains_top >= gain_ratio * gains_top[0]
            # clamp to the longest true PREFIX once, here, so the apply
            # loop, the fused routes and the histogram targets can never
            # disagree if a future gate is non-monotone in j (new leaf
            # ids are base + j, which only works applied in order)
            valid &= jnp.cumsum(~valid) == 0
            leaves_top = leaves_top.astype(jnp.int32)
            new_leaves = base + jnp.arange(K, dtype=jnp.int32)
            nodes = base - 1 + jnp.arange(K, dtype=jnp.int32)

            # Cl/Cr from the cached SplitInfo decide the smaller child
            Cl = st.best_f32[leaves_top, 3]
            Cp = st.leaf_c[leaves_top]
            smaller_is_left = Cl <= Cp - Cl

            # 1) apply the valid splits sequentially (cheap VPU/scalar
            # work).  ``valid`` is a PREFIX of K (top_k sorts gains
            # descending and the budget/ratio gates preserve order), so a
            # traced-bound fori over the prefix applies each split
            # UNCONDITIONALLY — the old per-split lax.cond made XLA copy
            # its carried leaf_id (~42 MB) through the identity branch
            # every split (the same copy class the strict grower's epoch
            # restructure eliminated; round-4 trace).  n_valid is uniform
            # across shards: it derives from merged gains and the budget.
            def apply_one(j, s):
                return apply_split(s, leaves_top[j], new_leaves[j],
                                   nodes[j])
            if fused_k:
                # both children come from data in the fused pass; no
                # round ever reads leaf_hist, so the [L, G, B, 3]
                # parent gather vanishes along with the child scatter
                parent_hist = None
            elif hist_stage:
                # round-carry staging: flush LAST round's staged children
                # into the full carry first (a later round may split a
                # leaf that left the stage), then look the round's K
                # parents up in the stage.  Best-first growth mostly
                # splits just-created children, so the common case reads
                # the small [2K, G, B, 3] stage instead of gathering from
                # the [L, G, B, 3] carry — and the cond's outputs are
                # only the small parent batch, so the miss path costs one
                # gather, not a carried-copy of the full leaf_hist.
                st = st._replace(leaf_hist=st.leaf_hist.at[
                    jnp.where(stage_ids >= 0, stage_ids, L)].set(
                        stage_hist, mode="drop"))
                m = ((stage_ids[None, :] == leaves_top[:, None])
                     & (stage_ids[None, :] >= 0))            # [K, 2K]
                hit = jnp.any(m, axis=1)
                pos = jnp.argmax(m, axis=1)
                all_hit = jnp.all(hit | ~valid)
                parent_hist = lax.cond(
                    all_hit,
                    lambda: stage_hist[jnp.where(hit, pos, 0)],
                    lambda: st.leaf_hist[leaves_top])       # [K, G, B, 3]
                s_hits = s_hits + jnp.sum((hit & valid).astype(jnp.int32))
                s_looks = s_looks + jnp.sum(valid.astype(jnp.int32))
            else:
                parent_hist = st.leaf_hist[leaves_top]      # [K, G, B, 3]
            # ``valid`` is prefix-clamped above, so the popcount IS the
            # prefix length
            n_valid = jnp.sum(valid).astype(jnp.int32)
            st = lax.fori_loop(0, n_valid, apply_one, st)

            # 2) union block list of the K smaller children's confinement
            # intervals (children inherit the parent interval, so read
            # either child's bounds)
            lo_k = st.leaf_lo[leaves_top]
            hi_k = st.leaf_hi[leaves_top]
            in_int = ((all_blocks[None, :] >= lo_k[:, None])
                      & (all_blocks[None, :] < hi_k[:, None])
                      & valid[:, None])                     # [K, max_blocks]
            mask = jnp.any(in_int, axis=0)
            n_un = jnp.sum(mask).astype(jnp.int32)
            pos = jnp.cumsum(mask) - 1
            block_list = jnp.zeros(max_blocks, jnp.int32).at[
                jnp.where(mask, pos, max_blocks)].set(all_blocks,
                                                      mode="drop")

            # 3) ONE batched kernel pass for the round's histograms
            if fused_route or fused_k:
                # the round's K routes ride the same pass (invalid slots
                # match nothing); split params still live in the best-*
                # cache — the scans that overwrite them run in step 4
                routes = jax.vmap(
                    lambda l, nl, v: jnp.where(
                        v,
                        pack_route(l, nl, st.best_i32[l, 0],
                                   st.best_i32[l, 1],
                                   st.best_i32[l, 2] == 1,
                                   st.best_i32[l, 3] == 1,
                                   st.best_cat_bitset[l], fmeta,
                                   p.packed4),
                        null_route()))(leaves_top, new_leaves, valid)
            else:
                routes = None
            if fused_k:
                # fused-K: route + ALL 2K children in one data pass.
                # Left children keep the parent leaf id after routing,
                # right children take the new id — so the target list is
                # simply [parents, new_leaves] and no smaller-child /
                # subtraction bookkeeping exists on this path (arithmetic
                # bit-matches comm.no_subtract, which also accumulates
                # both children from data).
                targets2 = jnp.concatenate([
                    jnp.where(valid, leaves_top, -1),
                    jnp.where(valid, new_leaves, -1)])
                st, hists2 = hist_batch_fusedk(st, targets2, block_list,
                                               n_un, routes)
                hist_left, hist_right = hists2[:K], hists2[K:]
                scanned = n_un
                grid_inc = grid_of(n_un)
                fk_rounds = fk_rounds + 1
            else:
                smaller = jnp.where(smaller_is_left, leaves_top,
                                    new_leaves)
                targets = jnp.where(valid, smaller, -1)
                st, hist_small = hist_batch(st, targets, block_list, n_un,
                                            routes, fmeta)
                if comm.no_subtract:
                    # voting-parallel: election masks differ per call, so
                    # the subtraction trick is invalid — batch-histogram
                    # the larger children from data too (routes applied)
                    larger = jnp.where(smaller_is_left, new_leaves,
                                       leaves_top)
                    targets_l = jnp.where(valid, larger, -1)
                    _, hist_large = hist_batch(st, targets_l, block_list,
                                               n_un, None, fmeta)
                    scanned = 2 * n_un
                    grid_inc = 2 * grid_of(n_un)
                else:
                    hist_large = parent_hist - hist_small
                    scanned = n_un
                    grid_inc = grid_of(n_un)
                sel = smaller_is_left[:, None, None, None]
                hist_left = jnp.where(sel, hist_small, hist_large)
                hist_right = jnp.where(sel, hist_large, hist_small)
            idx_l = jnp.where(valid, leaves_top, L)
            idx_r = jnp.where(valid, new_leaves, L)
            if fused_k:
                # children go straight to the step-4 scans; leaf_hist is
                # never read on this path, so neither of the per-round
                # [L, G, B, 3] staging copies happens
                st = st._replace(
                    scanned_since=st.scanned_since + scanned,
                    scanned_total=st.scanned_total + scanned,
                    grid_total=st.grid_total + grid_inc,
                )
            elif hist_stage:
                # the children stay in the stage this round; the flush at
                # the top of the NEXT round persists them (a fresh stage
                # entry shadows any stale carry slot until then)
                stage_ids = jnp.where(
                    jnp.concatenate([valid, valid]),
                    jnp.concatenate([leaves_top, new_leaves]),
                    jnp.int32(-1))
                stage_hist = jnp.concatenate([hist_left, hist_right])
                st = st._replace(
                    scanned_since=st.scanned_since + scanned,
                    scanned_total=st.scanned_total + scanned,
                    grid_total=st.grid_total + grid_inc,
                )
            else:
                st = st._replace(
                    leaf_hist=st.leaf_hist
                    .at[idx_l].set(hist_left, mode="drop")
                    .at[idx_r].set(hist_right, mode="drop"),
                    scanned_since=st.scanned_since + scanned,
                    scanned_total=st.scanned_total + scanned,
                    grid_total=st.grid_total + grid_inc,
                )

            # 4) scan all 2K children in one vmapped pass
            leaves2 = jnp.concatenate([idx_l, idx_r])
            hists2 = jnp.concatenate([hist_left, hist_right])
            g2 = st.leaf_g[jnp.minimum(leaves2, L - 1)]
            h2 = st.leaf_h[jnp.minimum(leaves2, L - 1)]
            c2 = st.leaf_c[jnp.minimum(leaves2, L - 1)]
            depth2 = st.tree.leaf_depth[jnp.minimum(leaves2, L - 1)]
            steps2 = jnp.concatenate([2 * nodes, 2 * nodes + 1])
            safe = jnp.minimum(leaves2, L - 1)
            infos, gains = jax.vmap(
                lambda hh, g, h, c, d, s, blo, bhi: _one_scan(
                    st, hh, g, h, c, d, fmeta, feature_mask, key, s,
                    blo, bhi)
            )(hists2, g2, h2, c2, depth2, steps2,
              st.leaf_mono_lo[safe], st.leaf_mono_hi[safe])
            if comm.merge_split_batch is not None:
                infos, gains = comm.merge_split_batch(infos, gains)
            st = _write_scans(st, leaves2, infos, gains)

            # 5) adaptive compaction, same rule as the strict grower
            st = cond_narrow(st.scanned_since >= limit_blocks,
                             compact, st, _COMPACT_MUT)
            return st, stage_ids, stage_hist, s_hits, s_looks, fk_rounds

        limit_blocks = min(max(1, int(COMPACT_WASTE * max_blocks)),
                           2**31 - 1)

        st = fresh_state(binsT, w8, n, L, G_cols, B, F, max_blocks,
                         G0, H0, C0, fmeta, p)
        if root_hist is None:
            # all-null routes on the fused paths: same kernel as the
            # round passes, so the root costs no extra Mosaic compile
            if fused_k:
                root_targets2 = (jnp.full(2 * K, -1, jnp.int32)
                                 .at[0].set(0))
                _, rh = hist_batch_fusedk(st, root_targets2, all_blocks,
                                          jnp.int32(max_blocks),
                                          jnp.tile(null_route(), (K, 1)))
            else:
                root_targets = jnp.full(K, -1, jnp.int32).at[0].set(0)
                root_routes = (jnp.tile(null_route(), (K, 1))
                               if fused_route else None)
                _, rh = hist_batch(st, root_targets, all_blocks,
                                   jnp.int32(max_blocks), root_routes,
                                   fmeta)
            root_hist = rh[0]
        st = st._replace(leaf_hist=st.leaf_hist.at[0].set(root_hist),
                         scanned_since=jnp.int32(max_blocks),
                         scanned_total=jnp.int32(max_blocks),
                         grid_total=jnp.int32(max_blocks))
        info0, gain0 = _one_scan(st, root_hist, G0, H0, C0, jnp.int32(0),
                                 fmeta, feature_mask, key, 2 * L,
                                 st.leaf_mono_lo[0], st.leaf_mono_hi[0])
        infos0 = jax.tree_util.tree_map(lambda x: x[None], info0)
        gains0 = gain0[None]
        if comm.merge_split_batch is not None:
            infos0, gains0 = comm.merge_split_batch(infos0, gains0)
        st = _write_scans(st, jnp.asarray([0], jnp.int32), infos0, gains0)

        def cond(st):
            return (st.num_leaves < L) & (jnp.max(st.best_f32[:, 0]) > 0.0)

        if hist_stage:
            # root pre-staged at slot 0 (it is also in leaf_hist[0], so
            # the first round's flush rewrites identical values)
            stage_ids0 = jnp.full(2 * K, -1, jnp.int32).at[0].set(0)
            stage_hist0 = jnp.zeros((2 * K, G_cols, B, 3),
                                    jnp.float32).at[0].set(root_hist)
        else:
            stage_ids0 = jnp.zeros(0, jnp.int32)
            stage_hist0 = jnp.zeros((0, G_cols, B, 3), jnp.float32)
        carry = (st, stage_ids0, stage_hist0, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        carry = lax.while_loop(lambda c: cond(c[0]), round_body, carry)
        st, _sid, _shist, s_hits, s_looks, fk_rounds = carry
        leaf_id_orig = _unpermute(st.order, st.leaf_id)
        # counters as a third jit output with stable arity (axon rejects
        # in-jit host callbacks); printing is env-gated at call sites
        stats = jnp.stack([st.scanned_total, st.num_sorts, st.grid_total,
                           jnp.int32(max_blocks), jnp.int32(K),
                           fk_rounds, qclips.astype(jnp.int32),
                           s_hits, s_looks])
        return st.tree, leaf_id_orig, stats

    if wrap is not None:
        return wrap(grow)
    from ..utils.jitcost import cost_jit
    # the fused-K label keeps "hist" in it so bench_suite's hist-pass
    # rollup (and bench_gate's latency gate) see fused rounds
    label = (f"grow/frontier[fused_hist_k{K}]" if fused_k
             else "grow/frontier")
    return cost_jit(label, jax.jit(grow))
