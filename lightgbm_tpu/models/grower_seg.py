"""Segment grower: leaf-wise growth with per-split cost proportional to
leaf size.

The fused grower (grower.py) scans the FULL dataset for every split's
histogram, so a 255-leaf tree costs 254 full passes — the reference instead
pays O(leaf size) per split by keeping each leaf's rows contiguous
(DataPartition, src/treelearner/data_partition.hpp:111; OrderedBin
re-sorting, src/io/ordered_sparse_bin.hpp).  TPUs can't afford a physical
re-partition per split (data-dependent scatter), so this grower uses
*epoch compaction*:

  * rows live in a permuted order (``order[pos] -> original row``); at a
    few leaf-count milestones the whole layout is re-sorted by ``leaf_id``
    with one ``lax.sort`` (stable, ~N log N but bandwidth-shaped on TPU —
    measured ~5ms/1M rows for the full payload);
  * between compactions rows never move, so every leaf's rows stay
    *confined* to the block interval its nearest compacted ancestor
    occupied — descendants only refine within it;
  * each split's smaller-child histogram runs the scalar-prefetched
    pallas segment kernel (ops/pallas_histogram.histogram_segment) over
    just that confinement interval: DMA and compute scale with the
    interval, and out-of-range grid steps are skipped for free.

Everything — splits, routing, compaction — is one ``lax.fori_loop`` inside
one jit; no host round-trips during growth.  Exact leaf-wise: the grown
tree is the same as the fused grower's up to histogram summation order.

Requires the pallas backend (feature-major [F, Npad] bins); serial learner
only — the distributed learners keep the fused grower for now.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas_histogram import (NUM_CHANNELS, _segment_buckets,
                                    bucket_index, fused_packed_optin,
                                    fused_route_decisions,
                                    fused_route_policy,
                                    histogram_segment,
                                    histogram_segment_routed, null_route,
                                    pack_channels, pack_route,
                                    packed_acc_bits, packed_acc_decisions,
                                    packed_acc_enabled,
                                    quantize_pack_channels,
                                    route_kernel_available, route_window,
                                    segment_grid_size, unpack_hist,
                                    unpack_hist_packed, unpack_nibble)
from ..ops.split import (NEG_INF, FeatureMeta, best_split, expand_group_hist,
                         reconstruct_feature_column)
from .grower import (CommHooks, GrowerParams, TreeArrays,
                     _node_feature_mask, mono_handoff, routed_left)

# Adaptive compaction: re-sort whenever the histogram kernels have scanned
# more than COMPACT_WASTE x N rows of confinement intervals since the last
# compaction.  Fixed leaf-count milestones (round 2) let waste balloon on
# skewed trees — best-first growth keeps splitting inside one big segment,
# so "compact at 4/16/64/256 leaves" could scan 30-40 N-equivalents per
# tree; the amortized rule bounds scan waste at ~(1 + COMPACT_WASTE/2) x
# ideal while the number of sorts stays <= total_scanned / (COMPACT_WASTE
# x N).  Overridable via LIGHTGBM_TPU_COMPACT_WASTE (in N multiples).
# Default from the on-chip sweeps at 10.5M rows (ONCHIP_LOG.md).  Round
# 4 (waste=1..6): strict 3.13 / 2.30 / 1.91 / 1.45, frontier 1.28 (3.0)
# / 1.12 (6.0) — the full-payload sort costs ~136-190 ms in context so
# fewer sorts win.  Round 5 refined around the knee (frontier, stats
# on): 6.0 -> 1.017 (2 sorts), 9.0 -> 0.929 (1 sort), 12.0 -> 0.985
# (scan growth overtakes); strict likewise prefers ~10 (1.42 -> 1.26).
import os as _os

COMPACT_WASTE = float(_os.environ.get("LIGHTGBM_TPU_COMPACT_WASTE", "9.0"))


# the growers' third jit output: i32 counter vector, one row per device
# under the data-parallel wrappers.  Fixed width so every grower/wrapper
# agrees; slots [fused_k_rounds, quant_clips, stage_hits, stage_lookups]
# stay 0 on paths that don't fuse-K / quantize / stage.
SEG_STATS_SLOTS = 9


def seg_stats_enabled() -> bool:
    """When LIGHTGBM_TPU_SEG_STATS is set, the counters the growers
    return — [scanned_blocks, compactions, grid_steps, max_blocks, K,
    fused_k_rounds, quant_clips, stage_hits, stage_lookups] — are
    printed per tree."""
    return bool(_os.environ.get("LIGHTGBM_TPU_SEG_STATS"))


def print_seg_stats(stats) -> None:
    """Host-side rendering of the counters a grower returned (the axon
    backend rejects in-jit host callbacks, so this replaces the old
    jax.debug.print).  Accepts [6] or a per-device concatenation [D*6].

    ``grid`` counts the kernel grid steps actually dispatched (the bucket
    the interval landed in, summed over calls); grid − scanned is the
    skipped-step waste the static bucket ladder pays
    (ops/pallas_histogram._segment_buckets)."""
    import sys

    import numpy as np

    rows = np.asarray(stats).reshape(-1, SEG_STATS_SLOTS)
    for d, (scanned, sorts, grid, max_blocks, k, fkr, clips, shits,
            slooks) in enumerate(rows):
        dev = f" dev{d}" if len(rows) > 1 else ""
        nb = max(int(max_blocks), 1)
        extra = ""
        if fkr:
            extra += f", fused-K rounds {int(fkr)}"
        if clips:
            extra += f", quant clips {int(clips)}"
        if slooks:
            extra += (f", stage hits {int(shits)}/{int(slooks)} "
                      f"({shits / max(int(slooks), 1):.0%})")
        sys.stderr.write(
            f"seg stats{dev}: scanned {int(scanned)} blocks "
            f"({scanned / nb:.1f} N-equivalents), "
            f"grid {int(grid)} steps ({grid / nb:.1f} N-equivalents), "
            f"{int(sorts)} compactions, K={int(k)}{extra}\n")
    sys.stderr.flush()


class _SegState(NamedTuple):
    binsT: jax.Array           # [F4, Npad] u8/i8, permuted
    w8: jax.Array              # [8, Npad] bf16 channels, permuted
    order: jax.Array           # [Npad] i32: pos -> original row
    leaf_id: jax.Array         # [Npad] i32 (permuted space)
    leaf_lo: jax.Array         # [L] i32 confinement start block
    leaf_hi: jax.Array         # [L] i32 confinement end block (exclusive)
    # blocks scanned by histogram kernels since the last compaction /
    # in total (adaptive-compaction accounting + perf introspection)
    scanned_since: jax.Array   # i32 scalar
    scanned_total: jax.Array   # i32 scalar
    grid_total: jax.Array      # i32 scalar: kernel grid steps dispatched
    num_sorts: jax.Array       # i32 scalar
    num_leaves: jax.Array
    leaf_hist: jax.Array       # [L, F, B, 3]
    leaf_g: jax.Array
    leaf_h: jax.Array
    leaf_c: jax.Array
    leaf_mono_lo: jax.Array    # [L] monotone output bounds
    leaf_mono_hi: jax.Array
    feat_used: jax.Array       # [F] CEGB coupled bookkeeping
    # best-split cache, PACKED so every scan writes 3 rows instead of 11
    # scalar scatters (each in-loop dynamic-update-slice costs fixed
    # overhead on TPU): f32 [L, 6] = (gain, left_g, left_h, left_c,
    # left_out, right_out); i32 [L, 4] = (feature, threshold,
    # default_left, is_cat); bitset [L, 8] u32
    best_f32: jax.Array
    best_i32: jax.Array
    best_cat_bitset: jax.Array
    tree: TreeArrays


def _pack_bins_words(binsT):
    """[F4, N] u8 -> [F4//4, N] i32 (4 features per word) for sort payload."""
    F4, n = binsT.shape
    b = binsT.astype(jnp.uint32).reshape(F4 // 4, 4, n)
    w = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)
    return w.astype(jnp.int32)


def _unpack_bins_words(words, dtype):
    W, n = words.shape
    u = words.astype(jnp.uint32)
    parts = [(u >> (8 * j)) & 0xFF for j in range(4)]
    return jnp.stack(parts, axis=1).reshape(W * 4, n).astype(dtype)


def _pack_w8_words(w8):
    """[8, N] bf16 -> [3, N] i32 for sort payload.

    Channels 5-7 are structurally zero (pack_channels pads g_hi/g_lo/
    h_hi/h_lo/member to 8 for the kernel's channel tile), so only 3 of
    the 4 halfword-pair words carry information — carrying the zero word
    through the multi-operand compaction sort was pure payload waste."""
    u = lax.bitcast_convert_type(w8, jnp.uint16).astype(jnp.uint32)  # [8,N]
    return (u[0:6:2] | (u[1:6:2] << 16)).astype(jnp.int32)


# lax.cond narrowing: a cond whose branches pass large arrays through
# unchanged still names them as branch OUTPUTS, and the merge can
# materialize copies of them every iteration (binsT is ~336 MB and w8
# ~168 MB at 10.5M rows — the round-4 trace measured 0.77 s/iter of such
# copies when the compact cond sat inside the per-split loop).  Each cond
# therefore carries ONLY the fields its true branch mutates — everything
# else reaches the branch as a closure capture — and the strict grower
# additionally keeps every remaining cond off the per-split path (epoch
# structure below).
_COMPACT_MUT = ("binsT", "w8", "order", "leaf_id", "leaf_lo", "leaf_hi",
                "scanned_since", "num_sorts")


def _take(st: _SegState, fields) -> tuple:
    return tuple(getattr(st, f) for f in fields)


def _put(st: _SegState, fields, vals) -> _SegState:
    return st._replace(**dict(zip(fields, vals)))


def cond_narrow(pred, fn, st: _SegState, fields) -> _SegState:
    """st -> lax.cond(pred, fn, identity, st) with the cond's carried
    operands narrowed to ``fields``."""
    rest = tuple(f for f in _SegState._fields if f not in fields)

    def true_branch(m):
        full_in = _put(st, fields, m)
        full_out = fn(full_in)
        # trace-time drift guard: a mutation to a non-carried field would
        # be silently DISCARDED by the narrowing — an untouched field is
        # the identical tracer object, so this fails loudly instead
        for f in rest:
            leaves_in = jax.tree_util.tree_leaves(getattr(full_in, f))
            leaves_out = jax.tree_util.tree_leaves(getattr(full_out, f))
            assert all(a is b for a, b in zip(leaves_in, leaves_out)), (
                f"cond_narrow: branch mutated non-carried field {f!r}; "
                f"add it to the mut list")
        return _take(full_out, fields)

    out = lax.cond(pred, true_branch, lambda m: m, _take(st, fields))
    return _put(st, fields, out)


def route_split_windowed(binsT, leaf_id, fmeta, packed4, rb,
                         f, t, dl, cat, bitset, leaf, new_leaf,
                         lo, n_blk):
    """Post-split ``leaf_id`` update confined to the parent's block
    interval — the routing half of the reference's O(leaf-size) split
    (DataPartition::Split, src/treelearner/data_partition.hpp:111).

    The parent's rows are confined to blocks [lo, lo+n_blk) (module
    docstring), so rows outside the window cannot match ``leaf`` and a
    full-N where() pass is pure waste — 254 of them per tree were the
    bulk of the growers' ~0.8 s/iter constant at 10.5M rows (round-4
    micro: route_pass ~51 ms/full-N vs 27 ms for a whole histogram
    pass).  Like the histogram kernels, the window is picked from the
    static ``_segment_buckets`` ladder: ``lax.switch`` over a few
    dynamic-slice widths, smallest bucket covering the interval.  The
    window may over-cover (block granularity + bucket rounding + end
    clamping); rows of other leaves inside it fail the ``== leaf`` test
    and pass through unchanged.
    """
    n = leaf_id.shape[0]
    max_blocks = n // rb
    buckets = _segment_buckets(max_blocks)
    col = f if fmeta.feat_group is None else fmeta.feat_group[f]
    row = col // 2 if packed4 else col

    def make_branch(bs):
        S = bs * rb

        def br(lid):
            start = jnp.clip(lo * rb, 0, n - S).astype(jnp.int32)
            fwin = lax.dynamic_slice(binsT, (row, start), (1, S))[0]
            if packed4:
                fwin = unpack_nibble(fwin, col)
            fwin = reconstruct_feature_column(fwin, f, fmeta)
            go_left = routed_left(fwin, t, dl, cat, bitset,
                                  fmeta.missing_type[f],
                                  fmeta.default_bin[f], fmeta.num_bin[f])
            lwin = lax.dynamic_slice(lid, (start,), (S,))
            lwin = jnp.where((lwin == leaf) & ~go_left, new_leaf, lwin)
            return lax.dynamic_update_slice(lid, lwin, (start,))
        return br

    if len(buckets) == 1:
        return make_branch(buckets[0])(leaf_id)
    idx = bucket_index(buckets, n_blk)
    return lax.switch(idx, [make_branch(b) for b in buckets], leaf_id)


def apply_route(binsT, leaf_id, fmeta, packed4, rb, f, t, dl, cat,
                bitset, leaf, new_leaf, lo, n_blk, use_kernel: bool):
    """One split's confined leaf_id update, through the aliased pallas
    window kernel when available (writes only the window's blocks; the
    XLA switch path below materializes a full-N leaf_id per call —
    measured 0.18 s/iter of conditional copies at the HIGGS shape) or
    the XLA windowed path otherwise."""
    if use_kernel:
        route = pack_route(leaf, new_leaf, f, t, dl, cat, bitset, fmeta,
                           packed4)
        return route_window(binsT, leaf_id, lo, n_blk, route, rb,
                            packed4=packed4)
    return route_split_windowed(binsT, leaf_id, fmeta, packed4, rb, f, t,
                                dl, cat, bitset, leaf, new_leaf, lo, n_blk)


def stripe_histogram(binsT, start, ncols, kernel_fn, feat_axis: int):
    """Feature-parallel stripe scatter shared by the strict and frontier
    growers: histogram a column SLICE of the bin matrix, then place the
    result back at its offset in a zero tensor (the scan masks hide the
    zero columns).  ``kernel_fn(sub)`` maps the [ncols, N] slice to a
    histogram whose feature axis is ``feat_axis``."""
    sub = lax.dynamic_slice_in_dim(binsT, start, ncols, axis=0)
    part = kernel_fn(sub)
    shape = (part.shape[:feat_axis] + (binsT.shape[0],)
             + part.shape[feat_axis + 1:])
    out = jnp.zeros(shape, part.dtype)
    return lax.dynamic_update_slice_in_dim(out, part, start,
                                           axis=feat_axis)


def _unpermute(order, leaf_id):
    """leaf_id (permuted space) -> original row order.

    ``order[pos] -> original row`` is a permutation, so sorting
    (order, leaf_id) by order is an exact inverse permute.  The obvious
    ``zeros.at[order].set(leaf_id)`` is a full-N random SCATTER — the op
    class the round-3 sort-vs-gather micro measured ~10x slower than
    multi-operand sorts on this backend — and it runs once per tree, so
    the sort formulation keeps the unpermute off the per-iteration
    critical path."""
    return lax.sort((order, leaf_id), num_keys=1)[1]


# above this many sort operands, compact via argsort + matrix gathers:
# XLA's variadic TPU sort compile time explodes with operand count
# (measured on v5e 2026-08-01: 12 operands at 56k rows = 94 s compile;
# the 39-operand sort a 136-feature dataset produces never finished
# inside a 70-minute budget and took the whole lambdarank-suite tier
# with it).  The gather path runs slower per sort (round-3 micro) but
# compiles in seconds and compaction is ~1 sort/tree at the default
# waste budget.
_MAX_SORT_OPERANDS = 16


def compact_state(st: _SegState, L: int, rb: int) -> _SegState:
    """Stable-sort the whole layout by leaf_id; leaves become contiguous
    segments and confinement intervals reset to them.  Shared by the
    strict and frontier growers (identical _SegState layout)."""
    W = st.binsT.shape[0] // 4
    # packed-accumulator stream: w8 is the [2, N] i32 quantized pair /
    # bitcast-member words — already sort-payload-shaped, so it rides the
    # variadic sort directly (2 operands vs the f32 path's 3 halfword
    # packs) and needs no re-pack after
    packed_w = st.w8.dtype == jnp.int32
    wrows = st.w8.shape[0] if packed_w else 3
    if W + 2 + wrows <= _MAX_SORT_OPERANDS:
        operands = ((st.leaf_id,)
                    + tuple(_pack_bins_words(st.binsT))
                    + (tuple(st.w8) if packed_w
                       else tuple(_pack_w8_words(st.w8)))
                    + (st.order,))
        sorted_ops = lax.sort(operands, num_keys=1, is_stable=True)
        lid = sorted_ops[0]
        binsT = _unpack_bins_words(jnp.stack(sorted_ops[1:1 + W]),
                                   st.binsT.dtype)
        wsorted = jnp.stack(sorted_ops[1 + W:1 + W + wrows])
        w8 = wsorted if packed_w else _unpack_w8_words(wsorted)
        order = sorted_ops[1 + W + wrows]
    else:
        # wide-feature path: 2-operand stable sort for the permutation,
        # then one gather per array (columns move as whole vectors)
        n = st.leaf_id.shape[0]
        lid, perm = lax.sort(
            (st.leaf_id, jnp.arange(n, dtype=jnp.int32)),
            num_keys=1, is_stable=True)
        binsT = jnp.take(st.binsT, perm, axis=1)
        if packed_w:
            w8 = jnp.take(st.w8, perm, axis=1)
        else:
            # channels 6-7 are structurally zero (pack_channels) — move
            # only the live ones, refill the rest (same trim the sort
            # path makes)
            w8 = jnp.concatenate(
                [jnp.take(st.w8[:6], perm, axis=1),
                 jnp.zeros((st.w8.shape[0] - 6, st.w8.shape[1]),
                           st.w8.dtype)])
        order = jnp.take(st.order, perm)
    leaves = jnp.arange(L, dtype=jnp.int32)
    starts = jnp.searchsorted(lid, leaves, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(lid, leaves, side="right").astype(jnp.int32)
    # block-granular bounds; empty/unused leaves get an empty interval
    leaf_lo = jnp.where(ends > starts, starts // rb, 0)
    leaf_hi = jnp.where(ends > starts, -(-ends // rb), 0)
    return st._replace(binsT=binsT, w8=w8, order=order, leaf_id=lid,
                       leaf_lo=leaf_lo, leaf_hi=leaf_hi,
                       scanned_since=jnp.int32(0),
                       num_sorts=st.num_sorts + 1)


def fresh_state(binsT, w8, n, L, G_cols, B, F, max_blocks, G0, H0, C0,
                fmeta, p) -> _SegState:
    """Initial _SegState + TreeArrays for a new tree (root covers
    everything).  Shared by the strict and frontier growers."""
    neg = jnp.full(L, NEG_INF, dtype=jnp.float32)
    zeros_l = jnp.zeros(L, dtype=jnp.float32)
    tree0 = TreeArrays(
        num_leaves=jnp.int32(1),
        split_feature=jnp.zeros(L - 1, dtype=jnp.int32),
        threshold_bin=jnp.zeros(L - 1, dtype=jnp.int32),
        default_left=jnp.zeros(L - 1, dtype=bool),
        is_cat=jnp.zeros(L - 1, dtype=bool),
        cat_bitset=jnp.zeros((L - 1, 8), dtype=jnp.uint32),
        left_child=jnp.full(L - 1, -1, dtype=jnp.int32),
        right_child=jnp.full(L - 1, -1, dtype=jnp.int32),
        split_gain=jnp.zeros(L - 1, dtype=jnp.float32),
        internal_value=jnp.zeros(L - 1, dtype=jnp.float32),
        internal_weight=jnp.zeros(L - 1, dtype=jnp.float32),
        internal_count=jnp.zeros(L - 1, dtype=jnp.float32),
        leaf_value=zeros_l,
        leaf_weight=zeros_l.at[0].set(H0),
        leaf_count=zeros_l.at[0].set(C0),
        leaf_parent=jnp.full(L, -1, dtype=jnp.int32),
        leaf_depth=jnp.zeros(L, dtype=jnp.int32),
    )
    return _SegState(
        binsT=binsT, w8=w8,
        order=jnp.arange(n, dtype=jnp.int32),
        leaf_id=jnp.zeros(n, dtype=jnp.int32),
        leaf_lo=jnp.zeros(L, dtype=jnp.int32),
        leaf_hi=jnp.zeros(L, dtype=jnp.int32).at[0].set(max_blocks),
        scanned_since=jnp.int32(0),
        scanned_total=jnp.int32(0),
        grid_total=jnp.int32(0),
        num_sorts=jnp.int32(0),
        num_leaves=jnp.int32(1),
        leaf_hist=jnp.zeros((L, G_cols, B, 3), dtype=jnp.float32),
        leaf_g=zeros_l.at[0].set(G0),
        leaf_h=zeros_l.at[0].set(H0),
        leaf_c=zeros_l.at[0].set(C0),
        leaf_mono_lo=jnp.full(L, -jnp.inf, dtype=jnp.float32),
        leaf_mono_hi=jnp.full(L, jnp.inf, dtype=jnp.float32),
        feat_used=(fmeta.cegb_used0
                   if (p.use_cegb_coupled and fmeta.cegb_used0 is not None)
                   else jnp.zeros(F, dtype=jnp.float32)),
        best_f32=jnp.zeros((L, 6), dtype=jnp.float32).at[:, 0].set(neg),
        best_i32=jnp.zeros((L, 4), dtype=jnp.int32).at[:, 0].set(-1),
        best_cat_bitset=jnp.zeros((L, 8), dtype=jnp.uint32),
        tree=tree0,
    )


def _unpack_w8_words(words):
    """[3, N] i32 -> [8, N] bf16 (channels 5-7 restored as zeros)."""
    u = words.astype(jnp.uint32)
    lo = (u & 0xFFFF).astype(jnp.uint16)
    hi = (u >> 16).astype(jnp.uint16)
    inter = jnp.stack([lo, hi], axis=1).reshape(6, -1)
    ch6 = lax.bitcast_convert_type(inter, jnp.bfloat16)
    return jnp.concatenate(
        [ch6, jnp.zeros((NUM_CHANNELS - 6, ch6.shape[1]), jnp.bfloat16)])


def make_grow_tree_segment(num_bins: int, params: GrowerParams,
                           block_rows: int, comm: CommHooks = CommHooks(),
                           wrap=None):
    """Build the jitted segment grower.

    Returned ``grow(binsT, grad, hess, member, fmeta, feature_mask, key)``
    takes feature-major bins [F, Npad] (Npad a multiple of block_rows; pad
    rows must carry member == 0) and returns ``(TreeArrays,
    leaf_id_original_order)`` exactly like the fused grower.

    ``comm`` hooks make this the data-parallel learner's core under
    ``shard_map`` (rows sharded; per-leaf cost stays O(leaf) per shard):
    ``reduce_hist`` runs on every leaf histogram, ``reduce_stats`` on the
    root scalars, ``merge_split`` on every per-leaf SplitInfo.
    """
    p = params
    L = p.num_leaves
    B = num_bins
    rb = block_rows
    # packed int16 accumulator stream (build-time decision — env inside
    # the jitted grow would poison the jit cache).  Quantization is per
    # TREE here (one stream for the whole grow); the per-leaf rescale
    # the unpack applies is the shared [2] scales vector.  Distributed-
    # safe: every unpack happens BEFORE comm.reduce_hist, so collectives
    # only ever see real-unit histograms.
    packed_acc = packed_acc_enabled()
    qbits = packed_acc_bits()
    packed_acc_decisions["segment"] = packed_acc
    # fused route+histogram: the split's leaf_id update rides the
    # smaller-child histogram pass instead of separate XLA passes over
    # the same blocks (self-checked on the live backend at build time).
    # Feature-parallel stripes (column_block) keep the unfused pair: the
    # histogram scans a column SLICE while the route needs the full
    # matrix (the winning split may live on another shard's stripe).
    # The packed stream keeps the unfused pair too — packed+fused has no
    # on-chip number yet (docs/KERNELS.md), so the A/B isolates one
    # variant at a time — unless LIGHTGBM_TPU_FUSED_PACKED opts the
    # combined variant in for its own A/B.
    fused_route = (fused_route_policy(1, p.num_columns or 64, B, rb,
                                      p.packed4) == "k1"
                   and comm.column_block is None
                   and (not packed_acc or fused_packed_optin()))
    fused_route_decisions["segment"] = fused_route
    route_kernel = route_kernel_available()

    def hist_leaf(st: _SegState, leaf, G_cols, fmeta=None, scales=None):
        """Returns (hist [G,B,3], blocks scanned).  ``scales`` is the
        packed stream's [2] rescale vector (None on the f32 path)."""
        lo = st.leaf_lo[leaf]
        n_blk = st.leaf_hi[leaf] - lo
        if comm.column_block is not None:
            # feature-parallel: histogram only this shard's column
            # stripe (the reference histograms only the rank's own
            # features, feature_parallel_tree_learner.cpp:36-75)
            start, ncols = comm.column_block(st.binsT)
            out = stripe_histogram(
                st.binsT, start, ncols,
                lambda sub: histogram_segment(sub, st.w8, st.leaf_id, lo,
                                              n_blk, leaf, B, rb,
                                              packed4=p.packed4),
                feat_axis=0)
        elif fused_route and not comm.no_subtract:
            # same kernel as the split path (one Mosaic compile), with a
            # match-nothing route; the aliased leaf_id passes through.
            # no_subtract comms never run the fused split path, so they
            # keep the plain kernel instead of paying the route's lid
            # write-back for nothing.
            _, out = histogram_segment_routed(
                st.binsT, st.w8, st.leaf_id, lo, n_blk, leaf,
                null_route(), B, rb, packed4=p.packed4)
        else:
            out = histogram_segment(st.binsT, st.w8, st.leaf_id, lo,
                                    n_blk, leaf, B, rb, packed4=p.packed4)
        h = (unpack_hist_packed(out[:G_cols], scales)
             if scales is not None else unpack_hist(out[:G_cols]))
        if comm.reduce_hist is not None:
            h = comm.reduce_hist(h, None, None, None, fmeta)
        return h, n_blk

    def _one_scan(hist, g, h, c, depth, fmeta, fmask, key, step,
                  lo, hi, feat_used):
        fmask_node = _node_feature_mask(fmask, key, step, p)
        if comm.shard_feature_mask is not None:
            fmask_node = comm.shard_feature_mask(fmask_node)
        adjust = None
        if p.cegb_penalty_split > 0.0 or p.use_cegb_coupled:
            from .grower import _cegb_split_coupled_adjust
            adjust = _cegb_split_coupled_adjust(feat_used, c, fmeta, p)
        # EFB: group-space histogram -> per-feature view
        hist = expand_group_hist(hist, fmeta, g, h, c)
        info = best_split(hist, g, h, c, fmeta, p.split, fmask_node,
                          mono_lo=lo if p.use_monotone else None,
                          mono_hi=hi if p.use_monotone else None,
                          gain_adjust=adjust)
        gain = info.gain
        if comm.merge_split is not None:
            info, gain = comm.merge_split(info, gain)
        if p.max_depth > 0:
            gain = jnp.where(depth >= p.max_depth, NEG_INF, gain)
        return info, gain

    def _write_scans(st: _SegState, leaf_idx, infos, gains):
        """leaf_idx/gains [k], infos batched SplitInfo; 3 packed scatters."""
        f32 = jnp.stack([gains, infos.left_g, infos.left_h, infos.left_c,
                         infos.left_out, infos.right_out],
                        axis=-1).astype(jnp.float32)
        i32 = jnp.stack([infos.feature, infos.threshold,
                         infos.default_left.astype(jnp.int32),
                         infos.is_cat.astype(jnp.int32)], axis=-1)
        return st._replace(
            best_f32=st.best_f32.at[leaf_idx].set(f32),
            best_i32=st.best_i32.at[leaf_idx].set(i32),
            best_cat_bitset=st.best_cat_bitset.at[leaf_idx].set(
                infos.cat_bitset),
        )

    def scan_leaf(st: _SegState, leaf_idx, hist, g, h, c, depth, fmeta,
                  fmask, key, step):
        info, gain = _one_scan(hist, g, h, c, depth, fmeta, fmask, key,
                               step, st.leaf_mono_lo[leaf_idx],
                               st.leaf_mono_hi[leaf_idx], st.feat_used)
        leaves = jnp.asarray(leaf_idx, jnp.int32)[None]
        batched = jax.tree_util.tree_map(lambda x: x[None], info)
        return _write_scans(st, leaves, batched, gain[None])

    def scan_pair(st: _SegState, leaves2, hists2, g2, h2, c2, depth, fmeta,
                  fmask, key, steps2):
        """Both children of a split evaluated in ONE vmapped scan — halves
        the per-split chain of small ops vs two sequential scans."""
        infos, gains = jax.vmap(
            lambda hh, g, h, c, s, blo, bhi: _one_scan(
                hh, g, h, c, depth, fmeta, fmask, key, s, blo, bhi,
                st.feat_used)
        )(hists2, g2, h2, c2, steps2, st.leaf_mono_lo[leaves2],
          st.leaf_mono_hi[leaves2])
        return _write_scans(st, leaves2, infos, gains)

    def compact(st: _SegState) -> _SegState:
        return compact_state(st, L, rb)

    def grow(binsT, grad, hess, member, fmeta: FeatureMeta, feature_mask,
             key, root_hist=None):
        # G_cols = logical bin-matrix columns (EFB groups); F = logical
        # features (fmeta/feature_mask space); binsT rows are PHYSICAL
        # (half of G_cols under 4-bit packing).
        # ``root_hist`` [G, B, 3], when given, replaces the root's own
        # full-data scan (multiclass batched roots: GBDT computes every
        # class-tree's root histogram in ONE kernel pass).  Serial only —
        # the distributed wrappers never pass it.
        n_phys, n = binsT.shape
        G_cols = p.num_columns or (2 * n_phys if p.packed4 else n_phys)
        F = fmeta.num_bin.shape[0]
        assert n % rb == 0, (n, rb)
        max_blocks = n // rb
        # pad physical rows to a multiple of 4 for the sort word packing
        fpad = (-n_phys) % 4
        if fpad:
            binsT = jnp.pad(binsT, ((0, fpad), (0, 0)))

        # grid-step accounting: the bucket ladder is static, so the grid
        # size a call dispatched is recomputable from its interval length
        bucket_arr = jnp.asarray(_segment_buckets(max_blocks), jnp.int32)

        def grid_of(nb):
            return segment_grid_size(bucket_arr, nb)

        if packed_acc:
            w8, qscales, qclips = quantize_pack_channels(
                grad, hess, member, bits=qbits)
        else:
            w8 = pack_channels(grad, hess, member)
            qscales, qclips = None, jnp.int32(0)
        G0 = jnp.sum(grad * member)
        H0 = jnp.sum(hess * member)
        C0 = jnp.sum(member)
        if comm.reduce_stats is not None:
            # allreduce of the root (cnt, sum_g, sum_h) tuple
            # (data_parallel_tree_learner.cpp:311-357)
            G0, H0, C0 = (comm.reduce_stats(G0), comm.reduce_stats(H0),
                          comm.reduce_stats(C0))

        def do_split(st: _SegState):
            # split ordinal (feature_fraction_bynode key folding); the
            # epoch-while structure has no fori index, but num_leaves-1
            # counts splits identically
            step = st.num_leaves - 1
            leaf = jnp.argmax(st.best_f32[:, 0]).astype(jnp.int32)
            new_leaf = st.num_leaves
            node = st.num_leaves - 1

            bi = st.best_i32[leaf]
            bf = st.best_f32[leaf]
            f = bi[0]
            t = bi[1]
            dl = bi[2].astype(bool)
            cat = bi[3].astype(bool)
            bitset = st.best_cat_bitset[leaf]

            # children inherit the parent's confinement interval; routing
            # only needs to touch that window
            lo, hi = st.leaf_lo[leaf], st.leaf_hi[leaf]
            Gl, Hl, Cl = bf[1], bf[2], bf[3]
            Gp, Hp, Cp = st.leaf_g[leaf], st.leaf_h[leaf], st.leaf_c[leaf]
            Gr, Hr, Cr = Gp - Gl, Hp - Hl, Cp - Cl
            smaller_is_left = Cl <= Cr
            smaller = jnp.where(smaller_is_left, leaf, new_leaf)

            if fused_route and not comm.no_subtract:
                # route + smaller-child histogram in ONE kernel pass over
                # the parent interval (histogram_segment_routed)
                route = pack_route(leaf, new_leaf, f, t, dl, cat, bitset,
                                   fmeta, p.packed4)
                leaf_id, out = histogram_segment_routed(
                    st.binsT, st.w8, st.leaf_id, lo, hi - lo, smaller,
                    route, B, rb, packed4=p.packed4)
                hist_small = unpack_hist(out[:G_cols])
                if comm.reduce_hist is not None:
                    hist_small = comm.reduce_hist(hist_small, None, None,
                                                  None, fmeta)
                blk = hi - lo
            else:
                leaf_id = apply_route(
                    st.binsT, st.leaf_id, fmeta, p.packed4, rb,
                    f, t, dl, cat, bitset, leaf, new_leaf, lo, hi - lo,
                    route_kernel)

            st = st._replace(
                leaf_id=leaf_id,
                leaf_lo=st.leaf_lo.at[new_leaf].set(lo),
                leaf_hi=st.leaf_hi.at[new_leaf].set(hi),
            )
            # monotone constraint handoff (serial_tree_learner.cpp:892-903)
            if p.use_monotone:
                lo_l, hi_l, lo_r, hi_r = mono_handoff(
                    st.leaf_mono_lo[leaf], st.leaf_mono_hi[leaf],
                    bf[4], bf[5],
                    fmeta.monotone[f], cat)
                st = st._replace(
                    leaf_mono_lo=st.leaf_mono_lo
                    .at[leaf].set(lo_l).at[new_leaf].set(lo_r),
                    leaf_mono_hi=st.leaf_mono_hi
                    .at[leaf].set(hi_l).at[new_leaf].set(hi_r),
                )
            if p.use_cegb_coupled:
                st = st._replace(feat_used=st.feat_used.at[f].set(1.0))

            if comm.no_subtract:
                # voting-parallel: each call's election masks differ, so
                # parent-minus-smaller is invalid (CommHooks doc) — build
                # BOTH children from data over the same interval
                hist_left, _b1 = hist_leaf(st, leaf, G_cols, fmeta,
                                            qscales)
                hist_right, _b2 = hist_leaf(st, new_leaf, G_cols, fmeta,
                                            qscales)
                blk = _b1 + _b2
                grid_blk = grid_of(_b1) + grid_of(_b2)
            else:
                if not fused_route:
                    hist_small, blk = hist_leaf(st, smaller, G_cols,
                                                fmeta, qscales)
                grid_blk = grid_of(blk)
                hist_parent = st.leaf_hist[leaf]
                hist_large = hist_parent - hist_small
                hist_left = jnp.where(smaller_is_left, hist_small,
                                      hist_large)
                hist_right = jnp.where(smaller_is_left, hist_large,
                                       hist_small)
            # the epoch-while predicates gate on scanned_since, so it must
            # be shard-uniform under the distributed wrappers (CommHooks
            # doc); scanned_total stays the shard-local truth for stats
            blk_u = (comm.uniform_scan(blk)
                     if comm.uniform_scan is not None else blk)
            st = st._replace(scanned_since=st.scanned_since + blk_u,
                             scanned_total=st.scanned_total + blk,
                             grid_total=st.grid_total + grid_blk)
            leaf_hist = (st.leaf_hist.at[leaf].set(hist_left)
                         .at[new_leaf].set(hist_right))

            depth_child = st.tree.leaf_depth[leaf] + 1
            tree = st.tree
            parent = tree.leaf_parent[leaf]
            pl_ = jnp.where((parent >= 0)
                            & (tree.left_child[jnp.maximum(parent, 0)]
                               == ~leaf),
                            node, tree.left_child[jnp.maximum(parent, 0)])
            pr = jnp.where((parent >= 0)
                           & (tree.right_child[jnp.maximum(parent, 0)]
                              == ~leaf),
                           node, tree.right_child[jnp.maximum(parent, 0)])
            left_child = tree.left_child.at[jnp.maximum(parent, 0)].set(pl_)
            right_child = tree.right_child.at[jnp.maximum(parent, 0)].set(pr)
            left_child = left_child.at[node].set(~leaf)
            right_child = right_child.at[node].set(~new_leaf)

            out_l = bf[4]
            out_r = bf[5]
            tree = tree._replace(
                num_leaves=st.num_leaves + 1,
                split_feature=tree.split_feature.at[node].set(f),
                threshold_bin=tree.threshold_bin.at[node].set(t),
                default_left=tree.default_left.at[node].set(dl),
                is_cat=tree.is_cat.at[node].set(cat),
                cat_bitset=tree.cat_bitset.at[node].set(bitset),
                left_child=left_child,
                right_child=right_child,
                split_gain=tree.split_gain.at[node].set(bf[0]),
                internal_value=tree.internal_value.at[node].set(
                    tree.leaf_value[leaf]),
                internal_weight=tree.internal_weight.at[node].set(Hp),
                internal_count=tree.internal_count.at[node].set(Cp),
                leaf_value=(tree.leaf_value.at[leaf].set(out_l)
                            .at[new_leaf].set(out_r)),
                leaf_weight=(tree.leaf_weight.at[leaf].set(Hl)
                             .at[new_leaf].set(Hr)),
                leaf_count=(tree.leaf_count.at[leaf].set(Cl)
                            .at[new_leaf].set(Cr)),
                leaf_parent=(tree.leaf_parent.at[leaf].set(node)
                             .at[new_leaf].set(node)),
                leaf_depth=(tree.leaf_depth.at[leaf].set(depth_child)
                            .at[new_leaf].set(depth_child)),
            )

            st = st._replace(
                num_leaves=st.num_leaves + 1,
                leaf_hist=leaf_hist,
                leaf_g=st.leaf_g.at[leaf].set(Gl).at[new_leaf].set(Gr),
                leaf_h=st.leaf_h.at[leaf].set(Hl).at[new_leaf].set(Hr),
                leaf_c=st.leaf_c.at[leaf].set(Cl).at[new_leaf].set(Cr),
                tree=tree,
            )
            st = scan_pair(
                st, jnp.stack([leaf, new_leaf]),
                jnp.stack([hist_left, hist_right]),
                jnp.stack([Gl, Gr]), jnp.stack([Hl, Hr]),
                jnp.stack([Cl, Cr]), depth_child, fmeta, feature_mask, key,
                jnp.stack([2 * step, 2 * step + 1]))
            return st

        # adaptive compaction (module docstring): amortize the sort against
        # the histogram DMA it saves.  Structured as EPOCH loops — an
        # inner while that splits unconditionally until the scan budget is
        # spent, and an outer loop that compacts between epochs.  The
        # round-3 form (one fori_loop whose body wrapped do_split and
        # compact in per-split lax.conds) made XLA materialize the conds'
        # carried operands through the identity branches every split:
        # the compact cond alone copied binsT+w8+order+leaf_id (~590 MB
        # at 10.5M rows) 254x/tree — 0.77 s/iter of pure copy in the
        # round-4 profiler trace (ONCHIP_LOG.md).  With the split work in
        # the loop PREDICATE instead of a cond, nothing is copied; the
        # compact cond now executes once per epoch (~#compactions/tree).
        limit_blocks = min(max(1, int(COMPACT_WASTE * max_blocks)),
                           2**31 - 1)   # compared against an i32 counter

        def can_grow(st: _SegState):
            return (st.num_leaves < L) & (jnp.max(st.best_f32[:, 0]) > 0.0)

        def epoch(st: _SegState) -> _SegState:
            st = lax.while_loop(
                lambda s: can_grow(s) & (s.scanned_since < limit_blocks),
                do_split, st)
            # compact only when another epoch follows (skip the pointless
            # final sort when growth ended mid-epoch)
            st = cond_narrow(can_grow(st)
                             & (st.scanned_since >= limit_blocks),
                             compact, st, _COMPACT_MUT)
            return st

        st = fresh_state(binsT, w8, n, L, G_cols, B, F, max_blocks,
                         G0, H0, C0, fmeta, p)
        if root_hist is None:
            root_hist, root_blk = hist_leaf(st, jnp.int32(0), G_cols,
                                            fmeta, qscales)
        else:
            # external batched pass: charge the same scan cost so the
            # adaptive-compaction accounting is unchanged
            root_blk = jnp.int32(max_blocks)
        st = st._replace(leaf_hist=st.leaf_hist.at[0].set(root_hist),
                         scanned_since=root_blk, scanned_total=root_blk,
                         grid_total=jnp.int32(max_blocks))
        st = scan_leaf(st, 0, root_hist, G0, H0, C0, jnp.int32(0), fmeta,
                       feature_mask, key, 2 * L)
        st = lax.while_loop(can_grow, epoch, st)
        leaf_id_orig = _unpermute(st.order, st.leaf_id)
        # scan/compaction counters always leave the jit as a third output
        # (stable arity; the axon PJRT backend rejects host callbacks, so
        # no jax.debug.print in compiled code) — printing them is gated
        # on LIGHTGBM_TPU_SEG_STATS at the call sites
        stats = jnp.stack([st.scanned_total, st.num_sorts, st.grid_total,
                           jnp.int32(max_blocks), jnp.int32(1),
                           jnp.int32(0), qclips.astype(jnp.int32),
                           jnp.int32(0), jnp.int32(0)])
        return st.tree, leaf_id_orig, stats

    if wrap is not None:
        return wrap(grow)
    from ..utils.jitcost import cost_jit
    return cost_jit("grow/segment", jax.jit(grow))
