"""SHAP feature contributions (TreeSHAP).

Reference: Tree::PredictContrib / TreeSHAP in src/io/tree.cpp (the
``predict_contrib`` path of c_api predict, tree.h:128).  Implements the
polynomial-time TreeSHAP algorithm (Lundberg et al.) over the host Tree
arrays; output layout matches LightGBM: per row, num_features + 1 values
(last = expected value / bias), concatenated per class for multiclass.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElem], zero_fraction, one_fraction,
                 feature_index):
    path.append(_PathElem(feature_index, zero_fraction, one_fraction,
                          1.0 if len(path) == 0 else 0.0))
    d = len(path) - 1
    for i in range(d - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (d + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (d - i) / (d + 1)


def _unwind_path(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (d - i) / (d + 1)
        else:
            path[i].pweight = path[i].pweight * (d + 1) / (zero_fraction * (d - i))
    for i in range(path_index, d):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    total = 0.0
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((d - i) / (d + 1))
        else:
            total += path[i].pweight / (zero_fraction * ((d - i) / (d + 1)))
    return total


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               path: List[_PathElem], parent_zero_fraction: float,
               parent_one_fraction: float, parent_feature_index: int):
    path = [ _PathElem(p.feature_index, p.zero_fraction, p.one_fraction,
                       p.pweight) for p in path ]
    _extend_path(path, parent_zero_fraction, parent_one_fraction,
                 parent_feature_index)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_path_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) \
                * tree.leaf_value[leaf]
        return
    # internal node
    hot, cold = _decide_children(tree, x, node)
    hot_count = _node_count(tree, hot)
    cold_count = _node_count(tree, cold)
    node_count = float(tree.internal_count[node])
    feature = int(tree.split_feature[node])
    incoming_zero, incoming_one = 1.0, 1.0
    path_index = next((i for i, el in enumerate(path)
                       if el.feature_index == feature), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, path_index)
    _tree_shap(tree, x, phi, hot, path,
               hot_count / node_count * incoming_zero, incoming_one, feature)
    _tree_shap(tree, x, phi, cold, path,
               cold_count / node_count * incoming_zero, 0.0, feature)


def _node_count(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _decide_children(tree: Tree, x: np.ndarray, node: int):
    go_left = bool(tree._decide(np.asarray([x[tree.split_feature[node]]]),
                                np.asarray([node]))[0])
    if go_left:
        return int(tree.left_child[node]), int(tree.right_child[node])
    return int(tree.right_child[node]), int(tree.left_child[node])


def tree_predict_contrib(tree: Tree, X: np.ndarray,
                         num_features: int) -> np.ndarray:
    out = np.zeros((X.shape[0], num_features + 1))
    if tree.num_leaves <= 1:
        out[:, -1] += tree.leaf_value[0]
        return out
    expected = tree.expected_value()
    for r in range(X.shape[0]):
        phi = np.zeros(num_features + 1)
        phi[-1] += expected
        _tree_shap(tree, X[r], phi, 0, [], 1.0, 1.0, -1)
        out[r] += phi
    return out


def predict_contrib(gbdt, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
    C = gbdt.num_tree_per_iteration
    n_iter = gbdt.iter_ if num_iteration <= 0 else min(num_iteration,
                                                       gbdt.iter_)
    nf = gbdt.max_feature_idx + 1
    out = np.zeros((C, X.shape[0], nf + 1))
    for k in range(C):
        out[k, :, -1] += gbdt.init_scores[k]
    for it in range(n_iter):
        for k in range(C):
            out[k] += tree_predict_contrib(gbdt.models[it * C + k], X, nf)
    if C == 1:
        return out[0]
    return out.transpose(1, 0, 2).reshape(X.shape[0], C * (nf + 1))
