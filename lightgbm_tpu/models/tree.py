"""Host-side decision tree model: prediction + (de)serialization.

Reference: include/LightGBM/tree.h:25-470 + src/io/tree.cpp.  Flat-array
binary tree with LightGBM's node numbering (internal node i created by the
i+1-th split; leaves referenced as ``~leaf``), decision_type bit flags
(bit0 categorical, bit1 default-left, bits2-3 missing type), numerical
``value <= threshold`` splits with missing routing, and categorical bitset
splits over category values (outer) / bin ids (inner).

Prediction here is vectorized numpy level-by-level routing — used for raw
feature matrices (Booster.predict) and for binned validation data during
training.  The training-time score update does not use this path at all: the
grower returns ``leaf_id`` directly on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

K_ZERO_THRESHOLD = 1e-35
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2


def _bitset_contains(words: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Vectorized Common::FindInBitset (utils/common.h:893-906)."""
    n_bits = len(words) * 32
    ok = (vals >= 0) & (vals < n_bits)
    safe = np.where(ok, vals, 0)
    word = words[safe // 32]
    return ok & (((word >> (safe % 32)) & 1).astype(bool))


def bitset_from_values(values: List[int]) -> np.ndarray:
    if not values:
        return np.zeros(1, dtype=np.uint32)
    size = max(values) // 32 + 1
    out = np.zeros(size, dtype=np.uint32)
    for v in values:
        if v >= 0:
            out[v // 32] |= np.uint32(1) << np.uint32(v % 32)
    return out


class Tree:
    """One trained decision tree (host copy)."""

    def __init__(self, num_leaves: int):
        n = max(num_leaves - 1, 0)
        self.num_leaves = num_leaves
        self.shrinkage = 1.0
        # internal nodes
        self.split_feature_inner = np.zeros(n, dtype=np.int32)
        self.split_feature = np.zeros(n, dtype=np.int32)   # real feature idx
        self.threshold_in_bin = np.zeros(n, dtype=np.int32)
        self.threshold = np.zeros(n, dtype=np.float64)     # real-valued
        self.decision_type = np.zeros(n, dtype=np.int8)
        self.left_child = np.full(n, -1, dtype=np.int32)
        self.right_child = np.full(n, -1, dtype=np.int32)
        self.split_gain = np.zeros(n, dtype=np.float32)
        self.internal_value = np.zeros(n, dtype=np.float64)
        self.internal_weight = np.zeros(n, dtype=np.float64)
        self.internal_count = np.zeros(n, dtype=np.int64)
        # categorical storage: per cat node, an index into cat_boundaries
        self.num_cat = 0
        self.cat_boundaries = [0]
        self.cat_threshold: List[np.ndarray] = []          # category-value bitsets
        self.cat_boundaries_inner = [0]
        self.cat_threshold_inner: List[np.ndarray] = []    # bin-id bitsets
        # leaves
        self.leaf_value = np.zeros(max(num_leaves, 1), dtype=np.float64)
        self.leaf_weight = np.zeros(max(num_leaves, 1), dtype=np.float64)
        self.leaf_count = np.zeros(max(num_leaves, 1), dtype=np.int64)
        self.leaf_parent = np.full(max(num_leaves, 1), -1, dtype=np.int32)
        self.leaf_depth = np.zeros(max(num_leaves, 1), dtype=np.int32)
        # whether threshold_in_bin / split_feature_inner / inner bitsets are
        # valid against some live dataset's bins.  Trees parsed from a model
        # file carry only real-valued thresholds until
        # serialization._remap_tree_to_bins aligns them (bin.h ValueToBin of
        # Tree threshold); using them binned before that would route rows
        # through garbage bins.
        self.bins_aligned = True

    # --------------------------------------------------------------- factory
    @classmethod
    def from_arrays(cls, arrays, dataset) -> "Tree":
        """Finalize a device TreeArrays pytree into a host Tree.

        ``dataset`` supplies bin->value realization: real thresholds come from
        BinMapper upper bounds (Dataset::RealThreshold) and categorical bin
        bitsets are re-expressed over raw category values for the outer model.
        """
        nl = int(arrays.num_leaves)
        t = cls(nl)
        n = nl - 1
        sf = np.asarray(arrays.split_feature)[:n]
        t.split_feature_inner = sf.astype(np.int32)
        used = np.asarray(dataset.used_feature_indices)
        t.split_feature = used[sf].astype(np.int32)
        t.threshold_in_bin = np.asarray(arrays.threshold_bin)[:n].astype(np.int32)
        t.left_child = np.asarray(arrays.left_child)[:n].astype(np.int32)
        t.right_child = np.asarray(arrays.right_child)[:n].astype(np.int32)
        t.split_gain = np.asarray(arrays.split_gain)[:n].astype(np.float32)
        t.internal_value = np.asarray(arrays.internal_value)[:n].astype(np.float64)
        t.internal_weight = np.asarray(arrays.internal_weight)[:n].astype(np.float64)
        t.internal_count = np.rint(
            np.asarray(arrays.internal_count)[:n]).astype(np.int64)
        t.leaf_value = np.asarray(arrays.leaf_value)[:nl].astype(np.float64)
        t.leaf_weight = np.asarray(arrays.leaf_weight)[:nl].astype(np.float64)
        t.leaf_count = np.rint(np.asarray(arrays.leaf_count)[:nl]).astype(np.int64)
        t.leaf_parent = np.asarray(arrays.leaf_parent)[:nl].astype(np.int32)
        t.leaf_depth = np.asarray(arrays.leaf_depth)[:nl].astype(np.int32)

        is_cat = np.asarray(arrays.is_cat)[:n]
        dl = np.asarray(arrays.default_left)[:n]
        bitsets = np.asarray(arrays.cat_bitset)[:n]
        infos = dataset.feature_infos()
        for i in range(n):
            f_inner = int(sf[i])
            info = infos[f_inner]
            dt = 0
            if is_cat[i]:
                dt |= K_CATEGORICAL_MASK
                # inner bitset over bins; outer over raw category values
                inner = bitsets[i].astype(np.uint32)
                bin_ids = [b for b in range(int(info.num_bin))
                           if inner[b // 32] >> (b % 32) & 1]
                mapper = dataset.bin_mappers[int(used[f_inner])]
                cats = [mapper.bin_2_categorical[b] for b in bin_ids
                        if b < len(mapper.bin_2_categorical)]
                t.threshold_in_bin[i] = t.num_cat
                t.threshold[i] = float(t.num_cat)
                t.num_cat += 1
                t.cat_threshold_inner.append(
                    bitset_from_values(bin_ids))
                t.cat_boundaries_inner.append(
                    t.cat_boundaries_inner[-1] + len(t.cat_threshold_inner[-1]))
                t.cat_threshold.append(bitset_from_values(cats))
                t.cat_boundaries.append(
                    t.cat_boundaries[-1] + len(t.cat_threshold[-1]))
            else:
                if dl[i]:
                    dt |= K_DEFAULT_LEFT_MASK
                t.threshold[i] = dataset.real_threshold(
                    f_inner, int(t.threshold_in_bin[i]))
            dt |= (int(info.missing_type) & 3) << 2
            t.decision_type[i] = dt
        return t

    @classmethod
    def from_grown(cls, arrays, dataset, shrinkage: float) -> "Tree":
        """Finalize one freshly-grown tree: bin->value realization plus
        learning-rate shrinkage — the materialization unit the boosting
        fetch pipeline applies to every tree it pulls off the device."""
        t = cls.from_arrays(arrays, dataset)
        t.apply_shrinkage(shrinkage)
        return t

    # ------------------------------------------------------------ prediction
    def _decide(self, fval: np.ndarray, nodes: np.ndarray) -> np.ndarray:
        """go-left decision for rows at internal ``nodes`` with raw values
        ``fval`` (NumericalDecision / CategoricalDecision, tree.h:221-278)."""
        dt = self.decision_type[nodes]
        is_cat = (dt & K_CATEGORICAL_MASK) > 0
        missing_type = (dt.astype(np.int32) >> 2) & 3
        default_left = (dt & K_DEFAULT_LEFT_MASK) > 0

        out = np.zeros(len(nodes), dtype=bool)
        # numerical
        num = ~is_cat
        if num.any():
            fv = fval[num].copy()
            mt = missing_type[num]
            nan = np.isnan(fv)
            fv[nan & (mt != 2)] = 0.0
            is_zero = (fv > -K_ZERO_THRESHOLD) & (fv <= K_ZERO_THRESHOLD)
            use_default = ((mt == 1) & is_zero) | ((mt == 2) & np.isnan(fv))
            go = np.where(use_default, default_left[num],
                          fv <= self.threshold[nodes[num]])
            out[num] = go
        # categorical
        if is_cat.any():
            idx = np.nonzero(is_cat)[0]
            fv = fval[idx]
            mt = missing_type[idx]
            int_fv = np.where(np.isnan(fv), -1, fv).astype(np.int64)
            nan_right = np.isnan(fv) & (mt == 2)
            int_fv = np.where(np.isnan(fv) & (mt != 2), 0, int_fv)
            go = np.zeros(len(idx), dtype=bool)
            for k, j in enumerate(idx):
                if nan_right[k] or int_fv[k] < 0:
                    go[k] = False
                    continue
                cat_idx = int(self.threshold_in_bin[nodes[j]])
                words = self.cat_threshold[cat_idx]
                go[k] = bool(_bitset_contains(
                    words, np.asarray([int_fv[k]]))[0])
            out[idx] = go
        return out

    def apply_raw(self, X: np.ndarray) -> np.ndarray:
        """Leaf index for each row of a raw feature matrix."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        cur = np.zeros(n, dtype=np.int32)   # internal node index
        leaf = np.full(n, -1, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        for _ in range(2 * self.num_leaves + 2):
            if not active.any():
                break
            nodes = cur[active]
            fv = X[active, self.split_feature[nodes]].astype(np.float64)
            go_left = self._decide(fv, nodes)
            nxt = np.where(go_left, self.left_child[nodes],
                           self.right_child[nodes])
            became_leaf = nxt < 0
            act_idx = np.nonzero(active)[0]
            leaf[act_idx[became_leaf]] = ~nxt[became_leaf]
            cur[act_idx] = nxt
            active[act_idx[became_leaf]] = False
        return leaf

    def apply_binned(self, binned: np.ndarray, feature_infos) -> np.ndarray:
        """Leaf index for each row of a BINNED matrix aligned with training
        bins (NumericalDecisionInner/CategoricalDecisionInner, tree.h:243-288)."""
        n = binned.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        if not self.bins_aligned:
            from ..utils.log import LightGBMError
            raise LightGBMError(
                "tree loaded from a model file has un-aligned bin "
                "thresholds; remap it against a dataset first "
                "(serialization._remap_tree_to_bins)")
        nb = np.asarray([fi.num_bin for fi in feature_infos], dtype=np.int32)
        db = np.asarray([fi.default_bin for fi in feature_infos], dtype=np.int32)
        # EFB (core/bundle.py): feature f lives in column grp[f] at
        # offset off[f]; out-of-range column values mean "f at default"
        grp = np.asarray([fi.group for fi in feature_infos], dtype=np.int32)
        off = np.asarray([fi.offset for fi in feature_infos], dtype=np.int32)
        cur = np.zeros(n, dtype=np.int32)
        leaf = np.full(n, -1, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        for _ in range(2 * self.num_leaves + 2):
            if not active.any():
                break
            nodes = cur[active]
            f = self.split_feature_inner[nodes]
            gv = binned[active, grp[f]].astype(np.int32)
            in_range = (gv >= off[f]) & (gv < off[f] + nb[f])
            fv = np.where(in_range, gv - off[f], db[f])
            dt = self.decision_type[nodes]
            is_cat = (dt & K_CATEGORICAL_MASK) > 0
            mt = (dt.astype(np.int32) >> 2) & 3
            dl = (dt & K_DEFAULT_LEFT_MASK) > 0
            is_missing = ((mt == 1) & (fv == db[f])) | \
                         ((mt == 2) & (fv == nb[f] - 1))
            go_left = np.where(is_missing, dl,
                               fv <= self.threshold_in_bin[nodes])
            if is_cat.any():
                idx = np.nonzero(is_cat)[0]
                for k in idx:
                    cat_idx = int(self.threshold_in_bin[nodes[k]])
                    words = self.cat_threshold_inner[cat_idx]
                    go_left[k] = bool(_bitset_contains(
                        words, np.asarray([fv[k]]))[0])
            nxt = np.where(go_left, self.left_child[nodes],
                           self.right_child[nodes])
            became_leaf = nxt < 0
            act_idx = np.nonzero(active)[0]
            leaf[act_idx[became_leaf]] = ~nxt[became_leaf]
            cur[act_idx] = nxt
            active[act_idx[became_leaf]] = False
        return leaf

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.full(X.shape[0], self.leaf_value[0])
        return self.leaf_value[self.apply_raw(X)]

    def predict_binned(self, binned: np.ndarray, feature_infos) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.full(binned.shape[0], self.leaf_value[0])
        return self.leaf_value[self.apply_binned(binned, feature_infos)]

    # -------------------------------------------------------------- mutation
    def apply_shrinkage(self, rate: float) -> None:
        """tree.h:149: scale leaf outputs by the learning rate."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value = np.asarray(values, dtype=np.float64)[: self.num_leaves]

    def as_constant(self, val: float) -> None:
        """tree.h:170 AsConstantTree."""
        self.num_leaves = 1
        self.shrinkage = 1.0
        self.leaf_value = np.asarray([val], dtype=np.float64)

    @property
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        return int(self.leaf_depth[: self.num_leaves].max())

    def expected_value(self) -> float:
        """Weighted mean output (for SHAP base value)."""
        w = self.leaf_count[: self.num_leaves].astype(np.float64)
        tot = w.sum()
        if tot <= 0:
            return float(self.leaf_value[0])
        return float((self.leaf_value[: self.num_leaves] * w).sum() / tot)
