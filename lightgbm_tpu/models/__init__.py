from .gbdt import GBDT
from .grower import GrowerParams, TreeArrays, make_grow_tree
from .tree import Tree

__all__ = ["GBDT", "GrowerParams", "TreeArrays", "make_grow_tree", "Tree"]
