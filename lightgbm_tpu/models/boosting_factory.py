"""Boosting-type factory (reference: Boosting::CreateBoosting,
src/boosting/boosting.cpp:36-77)."""

from __future__ import annotations

from ..utils.log import log_fatal


def create_boosting(config, train_set, objective):
    btype = str(config.boosting).strip().lower()
    if btype in ("gbdt", "gbrt"):
        from .gbdt import GBDT
        return GBDT(config, train_set, objective)
    if btype in ("dart",):
        from .dart import DART
        return DART(config, train_set, objective)
    if btype in ("goss",):
        from .goss import GOSS
        return GOSS(config, train_set, objective)
    if btype in ("rf", "random_forest"):
        from .rf import RF
        return RF(config, train_set, objective)
    log_fatal(f"Unknown boosting type {btype}")
