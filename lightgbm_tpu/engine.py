"""Training loop: train() and cv().

Reference: python-package/lightgbm/engine.py — train (:19: pure-Python
driver around Booster.update with callbacks and early stopping),
cv (:373: query-aware/stratified fold construction + per-fold boosters).
"""

from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import resolve_alias
from .utils.log import LightGBMError, log_info, log_warning


def estimate_working_set(params, data_shape, *, num_bins=None) -> int:
    """Estimated training working set in bytes for ``params`` (a dict
    or Config) over a ``(num_data, num_columns)`` dataset, without
    constructing a dataset or booster — the number the admission checks
    (``data_in_hbm=auto``, the sched plane's HBM gate, the serve
    registry) budget against.  See docs/TUNING.md."""
    from .models.gbdt import estimate_working_set as _estimate
    return _estimate(params, data_shape, num_bins=num_bins)


def _resolve_num_boost_round(params: Dict, num_boost_round: int) -> int:
    for k in list(params):
        if resolve_alias(k) == "num_iterations":
            num_boost_round = int(params.pop(k))
    return num_boost_round


def _importance_summary(booster, topk: int = 8) -> Optional[Dict]:
    """Top-K feature importances (split + gain, gain-ranked) for the
    health stream's summary record — model-shape observability on the
    training side (run_monitor renders it).  Best-effort: a booster
    that cannot report importances must not fail the summary write."""
    try:
        split = booster.feature_importance("split")
        gain = booster.feature_importance("gain")
        names = booster.feature_name()
        order = np.argsort(-gain, kind="stable")
        top = [{"feature": (names[i] if i < len(names)
                            else f"Column_{i}"),
                "split": int(split[i]),
                "gain": round(float(gain[i]), 6)}
               for i in (int(j) for j in order) if split[i] > 0][:topk]
        if not top:
            return None
        return {"feature_importance":
                {"top": top, "features_used": int((split > 0).sum())}}
    except Exception:
        return None


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: str = "auto", categorical_feature: str = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval: Union[bool, int] = True,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    params = dict(params or {})
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if fobj is not None:
        params["objective"] = "none"
    first_metric_only = bool(params.get("first_metric_only", False))

    if isinstance(init_model, str):
        init_booster = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        init_booster = init_model
    else:
        init_booster = None

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    booster = Booster(params=params, train_set=train_set)
    if init_booster is not None:
        from .models.serialization import load_trees_into
        raw = train_set.data if not isinstance(train_set.data, str) else None
        if raw is not None:
            raw = np.asarray(raw, dtype=np.float64)
            if raw.ndim == 1:
                raw = raw[:, None]
        load_trees_into(booster.gbdt, init_booster, raw_data=raw)
    if valid_sets:
        valid_names = valid_names or [f"valid_{i}"
                                      for i in range(len(valid_sets))]
        for vs, name in zip(valid_sets, valid_names):
            if vs is train_set:
                # the train set as a valid set is evaluated through the
                # train-score buffer under the name "training" (reference
                # engine.py:141-147); no separate score buffer exists
                booster._train_in_valid = True
                continue
            vs.reference = train_set
            booster.add_valid(vs, name)
    user_callbacks = list(callbacks or [])
    callbacks = list(user_callbacks)
    if verbose_eval is True:
        callbacks.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        callbacks.append(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_rounds, first_metric_only,
            verbose=bool(verbose_eval)))
    if evals_result is not None:
        callbacks.append(callback_mod.record_evaluation(evals_result))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    train_in_valid = getattr(booster, "_train_in_valid", False)

    # the profiler window is exception-safe (utils/phase.profile_session):
    # a callback or device error mid-training must not leak an open jax
    # profiler trace session
    from .utils import maybe_enable_compile_cache
    from .utils.phase import PROFILE_WINDOW, profile_session
    from .utils.telemetry import HEALTH, TELEMETRY
    # compile_cache= knob: persistent on-disk XLA compilation cache, so a
    # restarted/resumed run warm-starts its compiles (hits/misses surface
    # in the compile/cache_* telemetry counters)
    maybe_enable_compile_cache(booster.gbdt.config)

    # Chunked boosting: several iterations per device dispatch.  When
    # valid sets are attached and every metric is device-computable, the
    # in-scan eval path keeps the chunked dispatch: the scan body scores
    # the valid sets and computes the metrics per iteration, and the
    # loop below replays the per-iteration eval/callback/early-stopping
    # cadence from the fetched [T, n_cols] matrix at chunk boundaries —
    # bit-identical to per-iteration stepping.  A custom feval/fobj, a
    # before-iteration callback (e.g. reset_parameter), or a
    # host-computed metric forces per-iteration dispatch (the blocker is
    # named in the boost/inscan_blocked[...] gauge);
    # bagging/DART/GOSS clamps live in GBDT.boost_chunk_size.
    chunk = booster.gbdt.boost_chunk_size()
    use_inscan = False
    has_eval = bool(booster._valid_names or train_in_valid)
    user_after = [cb for cb in user_callbacks
                  if not getattr(cb, "before_iteration", False)]
    explicit = int(booster.gbdt.config.tpu_boost_chunk) != 0
    if callbacks_before or fobj is not None:
        chunk = 1
    elif has_eval and (chunk > 1 or explicit):
        blocker = ("feval" if feval is not None
                   else booster.setup_inscan_eval(train_in_valid))
        if blocker is None:
            use_inscan = True
        else:
            TELEMETRY.gauge_set(f"boost/inscan_blocked[{blocker}]", 1)
            chunk = 1
    elif chunk > 1 and not explicit and user_after:
        # auto chunking never changes a run's callback cadence
        chunk = 1
    # streaming run-health layer (health_out= / LIGHTGBM_TPU_HEALTH_JSONL):
    # per-iteration and per-eval records appended while the loop runs, so
    # a long job is observable before its finally-flush
    health_path = HEALTH.resolve_path(booster.gbdt.config)
    if health_path:
        HEALTH.open(health_path,
                    meta={"source": "engine",
                          "num_iterations": int(num_boost_round)})
    # memory_session brackets the run with HBM gauge samples and owns the
    # optional background sampler's lifetime (stopped even when a callback
    # or device error raises out of the loop)
    failed = False
    try:
        with profile_session(booster.gbdt.config), \
                TELEMETRY.memory_session():
            i = 0
            # in-scan rows carry GBDT-global iteration indices; with an
            # init_model those are offset from the engine's 0-based count
            base_iter = (booster.gbdt.current_iteration()
                         if use_inscan else 0)
            while i < num_boost_round:
                step = min(chunk, num_boost_round - i)
                # a profile_window boundary splits the chunk so the
                # capture covers exactly the requested iteration span
                step = PROFILE_WINDOW.clamp_step(i, step)
                PROFILE_WINDOW.step(i)
                for cb in callbacks_before:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=None))
                if step > 1 or use_inscan:
                    should_stop = booster.update_chunk(step)
                else:
                    should_stop = booster.update(fobj=fobj)
                it = i + step - 1

                if use_inscan:
                    # replay the chunk's per-iteration metric rows through
                    # the normal callback cadence (print/record/early-stop
                    # see exactly what per-iteration stepping shows them)
                    stopped_early = False
                    for j, vals in booster.take_inscan_evals():
                        jr = int(j) - base_iter
                        evaluation_result_list = (
                            booster.inscan_result_list(vals))
                        if HEALTH.active:
                            HEALTH.record("eval", {
                                "iter": jr, "in_scan": True,
                                "metrics": {f"{dn}/{mn}": float(v)
                                            for dn, mn, v, _ in
                                            evaluation_result_list}})
                        try:
                            for cb in callbacks_after:
                                cb(callback_mod.CallbackEnv(
                                    model=booster, params=params,
                                    iteration=jr, begin_iteration=0,
                                    end_iteration=num_boost_round,
                                    evaluation_result_list=(
                                        evaluation_result_list)))
                        except callback_mod.EarlyStopException as e:
                            booster.best_iteration = e.best_iteration + 1
                            for item in e.best_score:
                                booster.best_score.setdefault(
                                    item[0], {})[item[1]] = item[2]
                            # the stop fired INSIDE the chunk: surplus
                            # tail-of-chunk trees are discarded before
                            # they become model state, so the final
                            # model matches a per-iteration early stop
                            while booster.gbdt.current_iteration() > j + 1:
                                booster.gbdt.rollback_one_iter()
                            stopped_early = True
                            break
                    if stopped_early or should_stop:
                        break
                    i += step
                    continue

                evaluation_result_list = []
                if booster._valid_names or train_in_valid:
                    if train_in_valid:
                        evaluation_result_list.extend(
                            booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
                if evaluation_result_list and HEALTH.active:
                    HEALTH.record("eval", {
                        "iter": int(it), "in_scan": False,
                        "metrics": {f"{dn}/{mn}": float(v)
                                    for dn, mn, v, _ in
                                    evaluation_result_list}})
                try:
                    for cb in callbacks_after:
                        cb(callback_mod.CallbackEnv(
                            model=booster, params=params, iteration=it,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=evaluation_result_list))
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    for item in e.best_score:
                        booster.best_score.setdefault(
                            item[0], {})[item[1]] = item[2]
                    break
                if should_stop:
                    break
                i += step
    except BaseException:
        failed = True
        raise
    finally:
        if failed:
            # a raising callback or device error must still leave the run's
            # telemetry on the returned/half-trained booster and flush the
            # Chrome trace — the partial run is often the one worth debugging
            booster.train_stats = TELEMETRY.stats()
            TELEMETRY.maybe_export_trace()
        if health_path:
            # settle the async tree pipeline so the last iterations'
            # records land before the summary; best-effort on the
            # failure path (the original exception stays primary)
            try:
                booster.gbdt.models
            except Exception:
                pass
            # summary record (aborted on the failure path) + descriptor
            # release; the digest stays in stats()' health section.
            # The summary carries the trained model's top-K feature
            # importances so the stream describes the model's shape,
            # not just the run's
            HEALTH.close(aborted=failed,
                         extra=_importance_summary(booster))
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.gbdt.current_iteration()
    # success path: snapshot AFTER the finalizing fetch above so the
    # attached counters match a later stats() call exactly
    booster.train_stats = TELEMETRY.stats()
    TELEMETRY.maybe_export_trace()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters returned by cv(return_cvbooster=True)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    group = full_data.get_group()
    rng = np.random.RandomState(seed)
    if group is not None:
        # group-aware folds: split whole queries (engine.py:310-340)
        num_group = len(group)
        gidx = np.arange(num_group)
        if shuffle:
            rng.shuffle(gidx)
        boundaries = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
        folds_rows = [[] for _ in range(nfold)]
        folds_groups = [[] for _ in range(nfold)]
        for i, g in enumerate(gidx):
            f = i % nfold
            folds_rows[f].extend(range(boundaries[g], boundaries[g + 1]))
            folds_groups[f].append(int(group[g]))
        for f in range(nfold):
            test_rows = np.asarray(sorted(folds_rows[f]), dtype=np.int64)
            train_rows = np.setdiff1d(np.arange(num_data), test_rows)
            yield train_rows, test_rows
        return
    label = full_data.get_label()
    if stratified and label is not None:
        order = np.argsort(label, kind="stable")
        folds = [order[f::nfold] for f in range(nfold)]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        folds = np.array_split(idx, nfold)
    for f in range(nfold):
        test_rows = np.sort(folds[f])
        train_rows = np.setdiff1d(np.arange(num_data), test_rows)
        yield train_rows, test_rows


def _agg_cv_result(raw_results):
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params: Dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name: str = "auto",
       categorical_feature: str = "auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    params = dict(params or {})
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if metrics:
        params["metric"] = metrics
    if fobj is not None:
        params["objective"] = "none"
    obj_name = str(params.get("objective", "")).lower()
    if stratified and obj_name not in ("binary", "multiclass",
                                       "multiclassova"):
        stratified = False

    train_set.construct()
    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed,
                                   stratified, shuffle))
    elif hasattr(folds, "split"):
        label = train_set.get_label()
        folds = list(folds.split(np.zeros(train_set.num_data()), label))

    cvbooster = CVBooster()
    fold_data = []
    for train_rows, test_rows in folds:
        tr = train_set.subset(train_rows)
        te = train_set.subset(test_rows)
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, dict(params))
        else:
            fold_params = params
        b = Booster(params=fold_params, train_set=tr)
        te.reference = tr
        b.add_valid(te, "valid")
        cvbooster.append(b)
        fold_data.append(b)

    callbacks = list(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_rounds, verbose=False))
    if verbose_eval is True:
        callbacks.append(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        callbacks.append(callback_mod.print_evaluation(verbose_eval,
                                                       show_stdv))
    callbacks.sort(key=lambda cb: getattr(cb, "order", 0))

    results = collections.defaultdict(list)
    for i in range(num_boost_round):
        for b in fold_data:
            b.update(fobj=fobj)
        raw = []
        for b in fold_data:
            one = []
            if eval_train_metric:
                one.extend(b.eval_train(feval))
            one.extend(b.eval_valid(feval))
            raw.append(one)
        agg = _agg_cv_result(raw)
        for _, key, mean, _, std in agg:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg))
        except callback_mod.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for k in list(results):
                results[k] = results[k][: cvbooster.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return dict(results)
