"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

Feature-complete re-design of LightGBM (reference: Luo-Liang/LightGBM v2.2.4)
for TPU: histogram GBDT/DART/GOSS/RF training where the compute core is
JAX/XLA/Pallas (bin matrix in HBM, fused histogram+split+partition tree
growth under jit, distributed learners as XLA collectives over a device mesh)
instead of C++/OpenMP/OpenCL/sockets.
"""

from .config import Config
from .core.dataset import TpuDataset
from .utils.log import LightGBMError, register_log_callback, set_verbosity

__version__ = "0.1.0"

__all__ = ["Config", "TpuDataset", "LightGBMError", "register_log_callback",
           "set_verbosity", "__version__"]
