"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

Feature-complete re-design of LightGBM (reference: Luo-Liang/LightGBM v2.2.4)
for TPU: histogram GBDT/DART/GOSS/RF training where the compute core is
JAX/XLA/Pallas (bin matrix in HBM, fused histogram+split+partition tree
growth under jit, distributed learners as XLA collectives over a device mesh)
instead of C++/OpenMP/OpenCL/sockets.  The Python surface mirrors the
reference python-package so existing LightGBM user code ports unchanged.
"""

from . import callback
from .basic import Booster, Dataset
from .config import Config
from .core.dataset import TpuDataset
from .engine import CVBooster, cv, estimate_working_set, train
from .utils.log import LightGBMError, register_log_callback, set_verbosity

__version__ = "0.1.0"

__all__ = ["Booster", "Dataset", "Config", "TpuDataset", "CVBooster", "cv",
           "train", "estimate_working_set", "callback", "LightGBMError",
           "register_log_callback",
           "set_verbosity", "__version__"]


def __getattr__(name):
    # lazy sklearn/plotting imports (mirrors lightgbm.sklearn availability)
    try:
        if name in ("LGBMModel", "LGBMClassifier", "LGBMRegressor",
                    "LGBMRanker"):
            from . import sklearn as _sk
            return getattr(_sk, name)
        if name in ("plot_importance", "plot_metric", "plot_tree",
                    "plot_split_value_histogram", "create_tree_digraph"):
            from . import plotting as _pl
            return getattr(_pl, name)
    except ImportError as e:
        raise AttributeError(
            f"'{name}' is unavailable: {e}") from e
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name}")
