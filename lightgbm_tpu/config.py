"""Config / flag system.

Single source of truth for every training parameter: the ``_PARAMS`` registry
below declares name, type, default and aliases; the alias table and setters the
reference generates from ``config.h`` doc comments via
``helpers/parameter_generator.py`` (reference: include/LightGBM/config.h:52-561,
src/io/config_auto.cpp:10-285) are instead derived at import time from this one
table.  Parsing accepts ``key=value`` strings (CLI / config file) and Python
dicts, resolves aliases, coerces types, and cross-validates conflicting
parameters (reference: src/io/config.cpp:318-433 ``CheckParamConflict``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .utils.log import log_warning


class _P:
    """One parameter spec: (default, aliases)."""

    __slots__ = ("default", "aliases", "ptype")

    def __init__(self, default, aliases=(), ptype=None):
        self.default = default
        self.aliases = tuple(aliases)
        self.ptype = ptype if ptype is not None else type(default)


# The full parameter registry.  Matches the reference's Config::parameter_set
# (src/io/config_auto.cpp:172-285) and alias_table (config_auto.cpp:10-170).
_PARAMS: Dict[str, _P] = {
    # -- core --
    "config": _P("", ["config_file"]),
    "task": _P("train", ["task_type"]),
    "objective": _P("regression", ["objective_type", "app", "application"]),
    "boosting": _P("gbdt", ["boosting_type", "boost"]),
    "data": _P("", ["train", "train_data", "train_data_file", "data_filename"]),
    "valid": _P([], ["test", "valid_data", "valid_data_file", "test_data",
                     "test_data_file", "valid_filenames"], ptype=list),
    "num_iterations": _P(100, ["num_iteration", "n_iter", "num_tree", "num_trees",
                               "num_round", "num_rounds", "num_boost_round",
                               "n_estimators"]),
    "learning_rate": _P(0.1, ["shrinkage_rate", "eta"]),
    "num_leaves": _P(31, ["num_leaf", "max_leaves", "max_leaf"]),
    "tree_learner": _P("serial", ["tree", "tree_type", "tree_learner_type"]),
    "num_threads": _P(0, ["num_thread", "nthread", "nthreads", "n_jobs"]),
    "device_type": _P("tpu", ["device"]),
    "seed": _P(0, ["random_seed", "random_state"]),
    # -- learning control --
    "max_depth": _P(-1),
    "min_data_in_leaf": _P(20, ["min_data_per_leaf", "min_data", "min_child_samples"]),
    "min_sum_hessian_in_leaf": _P(1e-3, ["min_sum_hessian_per_leaf", "min_sum_hessian",
                                         "min_hessian", "min_child_weight"]),
    "bagging_fraction": _P(1.0, ["sub_row", "subsample", "bagging"]),
    "pos_bagging_fraction": _P(1.0, ["pos_sub_row", "pos_subsample", "pos_bagging"]),
    "neg_bagging_fraction": _P(1.0, ["neg_sub_row", "neg_subsample", "neg_bagging"]),
    "bagging_freq": _P(0, ["subsample_freq"]),
    "bagging_seed": _P(3, ["bagging_fraction_seed"]),
    "feature_fraction": _P(1.0, ["sub_feature", "colsample_bytree"]),
    "feature_fraction_bynode": _P(1.0, ["sub_feature_bynode", "colsample_bynode"]),
    "feature_fraction_seed": _P(2),
    "early_stopping_round": _P(0, ["early_stopping_rounds", "early_stopping"]),
    "first_metric_only": _P(False),
    "max_delta_step": _P(0.0, ["max_tree_output", "max_leaf_output"]),
    "lambda_l1": _P(0.0, ["reg_alpha"]),
    "lambda_l2": _P(0.0, ["reg_lambda", "lambda"]),
    "min_gain_to_split": _P(0.0, ["min_split_gain"]),
    "drop_rate": _P(0.1, ["rate_drop"]),
    "max_drop": _P(50),
    "skip_drop": _P(0.5),
    "xgboost_dart_mode": _P(False),
    "uniform_drop": _P(False),
    "drop_seed": _P(4),
    "top_rate": _P(0.2),
    "other_rate": _P(0.1),
    "min_data_per_group": _P(100),
    "max_cat_threshold": _P(32),
    "cat_l2": _P(10.0),
    "cat_smooth": _P(10.0),
    "max_cat_to_onehot": _P(4),
    "top_k": _P(20, ["topk"]),
    "monotone_constraints": _P([], ["mc", "monotone_constraint"], ptype=list),
    "feature_contri": _P([], ["feature_contrib", "fc", "fp", "feature_penalty"],
                         ptype=list),
    "forcedsplits_filename": _P("", ["fs", "forced_splits_filename",
                                     "forced_splits_file", "forced_splits"]),
    "refit_decay_rate": _P(0.9),
    "cegb_tradeoff": _P(1.0),
    "cegb_penalty_split": _P(0.0),
    "cegb_penalty_feature_lazy": _P([], ptype=list),
    "cegb_penalty_feature_coupled": _P([], ptype=list),
    # -- IO --
    "verbosity": _P(1, ["verbose"]),
    "max_bin": _P(255),
    "max_bin_by_feature": _P([], ptype=list),
    "min_data_in_bin": _P(3),
    "bin_construct_sample_cnt": _P(200000, ["subsample_for_bin"]),
    "histogram_pool_size": _P(-1.0, ["hist_pool_size"]),
    "data_random_seed": _P(1, ["data_seed"]),
    "output_model": _P("LightGBM_model.txt", ["model_output", "model_out"]),
    "snapshot_freq": _P(-1, ["save_period"]),
    "input_model": _P("", ["model_input", "model_in"]),
    "output_result": _P("LightGBM_predict_result.txt",
                        ["predict_result", "prediction_result", "predict_name",
                         "prediction_name", "pred_name", "name_pred"]),
    "initscore_filename": _P("", ["init_score_filename", "init_score_file",
                                  "init_score", "input_init_score"]),
    "valid_data_initscores": _P([], ["valid_data_init_scores", "valid_init_score_file",
                                     "valid_init_score"], ptype=list),
    "pre_partition": _P(False, ["is_pre_partition"]),
    "enable_bundle": _P(True, ["is_enable_bundle", "bundle"]),
    "max_conflict_rate": _P(0.0),
    "is_enable_sparse": _P(True, ["is_sparse", "enable_sparse", "sparse"]),
    "sparse_threshold": _P(0.8),
    "use_missing": _P(True),
    "zero_as_missing": _P(False),
    "two_round": _P(False, ["two_round_loading", "use_two_round_loading"]),
    "save_binary": _P(False, ["is_save_binary", "is_save_binary_file"]),
    "header": _P(False, ["has_header"]),
    "label_column": _P("", ["label"]),
    "weight_column": _P("", ["weight"]),
    "group_column": _P("", ["group", "group_id", "query_column", "query", "query_id"]),
    "ignore_column": _P("", ["ignore_feature", "blacklist"]),
    "categorical_feature": _P("", ["cat_feature", "categorical_column", "cat_column"]),
    "predict_raw_score": _P(False, ["is_predict_raw_score", "predict_rawscore",
                                    "raw_score"]),
    "predict_leaf_index": _P(False, ["is_predict_leaf_index", "leaf_index"]),
    "predict_contrib": _P(False, ["is_predict_contrib", "contrib"]),
    "num_iteration_predict": _P(-1),
    "pred_early_stop": _P(False),
    "pred_early_stop_freq": _P(10),
    "pred_early_stop_margin": _P(10.0),
    "convert_model_language": _P(""),
    "convert_model": _P("gbdt_prediction.cpp", ["convert_model_file"]),
    # -- objective --
    "num_class": _P(1, ["num_classes"]),
    "is_unbalance": _P(False, ["unbalance", "unbalanced_sets"]),
    "scale_pos_weight": _P(1.0),
    "sigmoid": _P(1.0),
    "boost_from_average": _P(True),
    "reg_sqrt": _P(False),
    "alpha": _P(0.9),
    "fair_c": _P(1.0),
    "poisson_max_delta_step": _P(0.7),
    "tweedie_variance_power": _P(1.5),
    "max_position": _P(20),
    "lambdamart_norm": _P(True),
    "label_gain": _P([], ptype=list),
    # -- metric --
    "metric": _P([], ["metrics", "metric_types"], ptype=list),
    "metric_freq": _P(1, ["output_freq"]),
    "is_provide_training_metric": _P(False, ["training_metric", "is_training_metric",
                                             "train_metric"]),
    "eval_at": _P([1, 2, 3, 4, 5], ["ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"],
                  ptype=list),
    "multi_error_top_k": _P(1),
    # -- network (reference: socket/MPI machine list; here: JAX mesh over ICI/DCN) --
    "num_machines": _P(1, ["num_machine"]),
    "local_listen_port": _P(12400, ["local_port", "port"]),
    "time_out": _P(120),
    "machine_list_filename": _P("", ["machine_list_file", "machine_list", "mlist"]),
    "machines": _P("", ["workers", "nodes"]),
    # -- multi-host lifecycle (parallel/distributed.py): explicit
    # jax.distributed world.  coordinator_address="" leaves init to the
    # launcher/env; num_hosts=0 / host_rank=-1 = auto-detect from the
    # binning_world() launch markers (SLURM/OMPI).  The
    # LIGHTGBM_TPU_COORDINATOR_ADDRESS/_NUM_HOSTS/_HOST_RANK env vars
    # win.  Runtime-only: per-host topology, never part of the model
    "coordinator_address": _P(""),
    "num_hosts": _P(0),
    "host_rank": _P(-1),
    # hardened collective seam: extra attempts after the first failure
    # of a host-level collective (retry-once default preserved), and
    # the per-attempt wall budget for collectives, barriers, and the
    # distributed-init handshake — a dead host then surfaces as an
    # error naming the missing rank instead of a hang
    "collective_retries": _P(1),
    "collective_timeout_s": _P(120.0),
    # -- device --
    "gpu_platform_id": _P(-1),
    "gpu_device_id": _P(-1),
    "gpu_use_dp": _P(False),
    # -- tpu-specific (new in this framework) --
    "tpu_histogram_backend": _P("auto"),   # auto | onehot | pallas
    "tpu_tree_impl": _P("auto"),           # auto | fused | segment | frontier
    "tpu_row_chunk": _P(0),                # 0 = auto-pick row chunk for histogram scan
    # frontier impl: leaves batched per growth round (0 = auto: fill the
    # 128-wide MXU tile, 8 channels x 16 leaves); 1 = strict best-first
    "tpu_frontier_width": _P(0),
    # frontier impl: only batch leaves whose gain >= ratio * round-best
    # gain — rounds adapt between strict (one dominant leaf) and fully
    # batched (many comparable leaves); 0.0 = pure top-K.  Default 0.0:
    # on-chip at the HIGGS shape the fuller rounds cut per-round
    # while-carry copies (0.766 -> 0.709 s/iter) at equal train AUC
    # (0.97110 vs 0.97102 @6it, within the bench A/B's 0.002 gate)
    "tpu_frontier_gain_ratio": _P(0.0),
    # boosting iterations dispatched as ONE device program (lax.scan over
    # the fused step), with tree fetches batched at the chunk boundary.
    # 0 = auto (chunk on TPU when the run is chunk-eligible, 1 elsewhere);
    # 1 disables chunking.  Auto-clamps to 1 when the iteration needs host
    # interaction (bagging re-draws, feature_fraction sampling, DART/RF
    # tree mutation, CEGB state, custom gradients, per-iter callbacks).
    # Attached valid sets no longer force the clamp: when every attached
    # metric is device-computable, the in-scan eval path (metric/device.py)
    # scores and evaluates them inside the scan at unchanged per-iteration
    # cadence; a custom feval or host-only metric still falls back to 1
    # (blocker named in the boost/inscan_blocked[...] telemetry gauge).
    "tpu_boost_chunk": _P(0, ["boost_chunk"]),
    "tpu_double_precision": _P(False),     # accumulate histograms in f64-equivalent
    # telemetry (utils/telemetry.py): 0 = off, 1 = counters/gauges/
    # timeline (default), 2 = + span ring buffer for Chrome trace export.
    # Env LIGHTGBM_TPU_TELEMETRY overrides; LIGHTGBM_TPU_TRACE_JSON
    # forces >= 2.
    "telemetry_level": _P(1),
    # CLI (task=train): write the versioned metrics JSON blob here after
    # training ("" = don't)
    "metrics_out": _P(""),
    # streaming run-health JSONL (utils/telemetry.HealthStream): one
    # atomically-appended record per iteration/eval/snapshot/fault while
    # training runs, consumable live via tools/run_monitor.py; a resumed
    # run compacts past the snapshot iteration and keeps appending.
    # Env LIGHTGBM_TPU_HEALTH_JSONL wins; "" = no stream
    "health_out": _P(""),
    # persistent on-disk XLA compilation cache so a restarted/resumed run
    # warm-starts its compiles: "" (default) = off, "1"/"true"/"on"/
    # "default" = on at <repo>/.jax_cache, any other string = cache
    # directory path.  Hits/misses surface as compile/cache_hits|misses
    # telemetry counters
    "compile_cache": _P(""),
    # measured per-dispatch device timing (utils/jitcost.py): every
    # cost-instrumented jit dispatch is timed wall-to-ready (sync on the
    # returned buffers) into the metrics blob's v4 ``timing`` section —
    # per-label count/total/mean/p50/p99 plus host dispatch-gap time —
    # yielding MEASURED FLOP/s and B/s next to the static XLA estimates.
    # Values (and models) are unchanged, but the sync serializes the
    # async pipeline: an opt-in measurement mode, never a benchmark
    # default.  Env LIGHTGBM_TPU_DEVICE_TIMING wins; runtime-only
    "device_timing": _P(False),
    # windowed programmatic jax-profiler capture: "START:END" opens the
    # profiler trace only for that half-open boosting-iteration span,
    # wrapping chunk dispatches in StepTraceAnnotation and phases in
    # TraceAnnotation so the device trace aligns with the host Chrome
    # trace.  Artifact dir: LIGHTGBM_TPU_PROFILE_DIR, else
    # lightgbm_tpu.profile; path + actual window land in the blob's
    # ``timing`` section.  "" = off.  Env LIGHTGBM_TPU_PROFILE_WINDOW
    # wins; runtime-only
    "profile_window": _P(""),
    # -- robustness (utils/faults.py, docs/ROBUSTNESS.md) --
    # blocking finiteness check on the boosted scores at chunk
    # boundaries (and per-iteration when chunking is off): a NaN/Inf
    # rolls the ensemble back to the last good iteration and raises
    # instead of silently shipping a poisoned model
    "check_nonfinite": _P(True),
    # CLI (task=train): discover the newest <output_model>.snapshot_iter_N
    # (with its .state sidecar) and continue bit-exactly from iteration N
    "resume": _P(False),
    # keep only the newest K snapshots, deleting older ones after each
    # successful snapshot write; 0 = keep all (reference save_period
    # keeps all)
    "snapshot_keep": _P(0),
    # deterministic fault injection spec (same grammar as the
    # LIGHTGBM_TPU_FAULTS env var, which wins per-site); "" = off
    "fault_injection": _P(""),
    # where the binned training matrix lives during boosting
    # (data/hostspill.py): "auto" = admission-check the estimated
    # working set against the device's reported HBM and start in the
    # host-spill (out-of-core) tier only when it does not fit;
    # "resident" = always keep it in HBM and never spill (the ladder
    # then ends at chunk size 1); "spill" = force the host-spill tier:
    # the matrix stays in host memory and is streamed into HBM as
    # fixed-order row-blocks per dispatch window.  Bit-identical models
    # either way.  Runtime-only: never serialized into the model
    "data_in_hbm": _P("auto"),
    # --- prediction service (lightgbm_tpu/serve, docs/SERVING.md) ---
    # how Booster.predict routes: "auto" = compiled stacked-tensor
    # routing (models/device_predict.py) when an accelerator is
    # attached, host tree walk otherwise; "on" = always the device
    # path (useful for parity testing on CPU); "off" = always the
    # host walk.  Output is bit-identical either way.  Runtime-only
    "predict_device": _P("auto"),
    # rows per serve dispatch AND the cap a micro-batching queue
    # drain coalesces up to; larger batches amortize dispatch
    # overhead at the price of padding small traffic up to a bucket
    "serve_max_batch": _P(256),
    # how long (ms) the serve queue holds the oldest pending request
    # hoping to coalesce more rows into the same dispatch; 0 =
    # dispatch-per-request (lowest latency, most dispatches)
    "serve_max_delay_ms": _P(2.0),
    # give-up budget for one queued serve request; a stuck dispatch
    # surfaces as a named ServeError instead of a hang
    "serve_queue_timeout_s": _P(30.0),
    # load-shedding bound on the micro-batch queue: total rows allowed
    # to sit pending; a submit that would exceed it is rejected with a
    # named ServeOverloadError (counted and health-streamed) instead of
    # growing the queue without bound.  0 = unbounded (pre-v20 behavior)
    "serve_max_queue_rows": _P(65536),
    # quality gate on hot model swap (ServeSession.swap / the refit
    # loop): the candidate is shadow-scored on a deterministic holdout
    # and rejected when its holdout metric is more than this fraction
    # worse than the incumbent's (or any output is non-finite); the old
    # model keeps serving and a swap_rejected record is emitted
    "swap_quality_threshold": _P(0.1),
    # seconds between DriftGate polls in the background refit loop
    # (serve/refit_loop.py): each drifted poll refits the booster on
    # fresh labeled data and pushes it through the gated swap
    "refit_poll_s": _P(30.0),
    # streaming serve-health JSONL (serve/health.py): the session
    # appends serve_start/serve_window/serve_admit/serve_fault/
    # serve_summary records through the same never-torn O_APPEND writer
    # training uses, consumable live via tools/serve_monitor.py.  Env
    # LIGHTGBM_TPU_SERVE_HEALTH_JSONL wins; "" = no stream
    "serve_health_out": _P(""),
    # seconds between serve_window records (QPS, stage p50/p99, pad and
    # coalesce fill ratios) while a serve session with a health stream
    # is alive; idle windows are still written so a wedged server is
    # distinguishable from an idle one
    "serve_health_window_s": _P(5.0),
    # model-and-data drift plane (obs/drift.py, metrics v7): when on, a
    # serve session accumulates per-(model, feature) bin-occupancy
    # counts from the already-binned device rows plus a bounded
    # reservoir of replied raw scores, and each serve_window close
    # emits a serve_drift record (per-feature PSI vs the training
    # baseline, score-shift JS).  Host-side accounting only: models
    # stay byte-identical and replies bit-identical either way
    "drift_detect": _P(False),
    # PSI at or above which a model counts as drifted: serve_drift
    # records flag it, the monitors render the DRIFT banner and
    # DriftGate.drifted() (the refit trigger) flips.  0.2 is the
    # classic "act" operating point (0.1 = watch)
    "drift_psi_threshold": _P(0.2),
    # how many of the worst-drifting features a serve_drift record
    # names (sorted by PSI, descending)
    "drift_topk": _P(5),
    # multi-tenant training scheduler (lightgbm_tpu/sched,
    # docs/SCHEDULING.md): path of a job spec file; a non-empty value
    # (or task=sched) runs the spec's jobs cooperatively time-sliced
    # on this process's device set instead of one training run
    "sched": _P(""),
    # chunk dispatches one job runs per scheduler time slice before the
    # next tenant is considered; the chunk boundary is the preemption
    # point, so a larger quantum trades fairness granularity for fewer
    # scheduler round-trips
    "sched_quantum_chunks": _P(4),
    # slice-picking policy: "round_robin" rotates tenants per quantum;
    # "fair" (alias fair_share) is the deficit policy — always run the
    # runnable job with the least accumulated device-seconds, weighted
    # by its share weight (measured via device_timing when on, slice
    # wall otherwise)
    "sched_policy": _P("round_robin"),
    # concurrently RESIDENT jobs; submissions beyond it queue (FIFO)
    # until a running job finishes
    "sched_max_jobs": _P(8),
    # scheduler health JSONL (sched_start/sched_admit/sched_slice/
    # sched_preempt_job/job_done/sched_summary records) through the
    # same never-torn O_APPEND writer training uses; tail it with
    # tools/sched_monitor.py.  "" = no stream
    "sched_health_out": _P(""),
    # fleet observability plane (obs/, metrics v6): every N iterations
    # ranks kv-allgather their per-collective enter/duration windows,
    # split collective wall into wait vs work seconds, and name the
    # straggler rank in a dist_window health record.  0 = sync only at
    # summary.  Multi-host runs only; host-side timing, so trained
    # models stay byte-identical with any value
    "fleet_obs_sync_iters": _P(0),
    # ping/pong exchanges per clock-offset estimate (obs/clockskew.py);
    # the minimum-RTT sample wins, so more pings tighten the bound
    "fleet_obs_clock_pings": _P(5),
}

# runtime-only knobs excluded from a saved model's ``parameters:``
# section: they describe how THIS process ran, not what was learned, and
# including them would make a resumed run's model differ byte-wise from
# an uninterrupted one
RUNTIME_ONLY_PARAMS = frozenset(["resume", "fault_injection",
                                 "compile_cache", "device_timing",
                                 "profile_window", "data_in_hbm",
                                 "coordinator_address", "num_hosts",
                                 "host_rank", "collective_retries",
                                 "collective_timeout_s",
                                 "predict_device", "serve_max_batch",
                                 "serve_max_delay_ms",
                                 "serve_queue_timeout_s",
                                 "serve_max_queue_rows",
                                 "swap_quality_threshold",
                                 "refit_poll_s",
                                 "serve_health_out",
                                 "serve_health_window_s",
                                 "drift_detect", "drift_psi_threshold",
                                 "drift_topk",
                                 "sched", "sched_quantum_chunks",
                                 "sched_policy", "sched_max_jobs",
                                 "sched_health_out",
                                 "telemetry_level", "metrics_out",
                                 "health_out",
                                 "fleet_obs_sync_iters",
                                 "fleet_obs_clock_pings"])

# alias -> canonical name
ALIAS_TABLE: Dict[str, str] = {}
for _name, _spec in _PARAMS.items():
    for _a in _spec.aliases:
        ALIAS_TABLE[_a] = _name

PARAMETER_SET = frozenset(_PARAMS)

_TRUE_SET = {"1", "t", "true", "y", "yes", "on"}
_FALSE_SET = {"0", "f", "false", "n", "no", "off"}

# objective alias strings (reference: docs in config.h:184-214 and
# ObjectiveFunction::CreateObjectiveFunction src/objective/objective_function.cpp:15)
OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression",
    "l2_root": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def _coerce(name: str, value: Any, ptype: type) -> Any:
    """Coerce a raw (usually string) value to the parameter's type."""
    if ptype is list:
        if isinstance(value, (list, tuple)):
            return list(value)
        if isinstance(value, str):
            if not value:
                return []
            return [_maybe_num(v) for v in value.replace(";", ",").split(",")]
        return [value]
    if ptype is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):
            return bool(value)
        s = str(value).strip().lower()
        if s in _TRUE_SET:
            return True
        if s in _FALSE_SET:
            return False
        raise ValueError(f"cannot parse bool parameter {name}={value!r}")
    if ptype is int:
        return int(float(value))
    if ptype is float:
        return float(value)
    return str(value)


def _maybe_num(s: str) -> Any:
    s = s.strip()
    try:
        f = float(s)
        return int(f) if f == int(f) and "." not in s and "e" not in s.lower() else f
    except ValueError:
        return s


def resolve_alias(key: str) -> str:
    k = key.strip().lower()
    return ALIAS_TABLE.get(k, k)


def str2map(parameters: str) -> Dict[str, str]:
    """Parse whitespace-separated ``key=value`` pairs (reference Config::Str2Map)."""
    out: Dict[str, str] = {}
    for tok in parameters.split():
        kv2map(out, tok)
    return out


def kv2map(params: Dict[str, str], kv: str) -> None:
    kv = kv.strip()
    if not kv or kv.startswith("#"):
        return
    if "=" not in kv:
        log_warning(f"Unknown parameter {kv}")
        return
    k, v = kv.split("=", 1)
    k = k.strip()
    v = v.split("#", 1)[0].strip()
    if k in params and params[k] != v:
        log_warning(f"{k} is set with {params[k]}, will be overridden by {v}")
    params[k] = v


@dataclasses.dataclass
class Config:
    """Resolved training configuration.

    Construct with :meth:`from_params` from a dict of possibly-aliased keys.
    Unknown keys warn (matching the reference's tolerance of unknown params).
    """

    def __init__(self, **kwargs):
        for name, spec in _PARAMS.items():
            v = spec.default
            object.__setattr__(self, name,
                               list(v) if isinstance(v, list) else v)
        self.raw: Dict[str, Any] = {}
        self.update(kwargs)

    # -- construction --
    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]] = None,
                    **kwargs) -> "Config":
        merged = dict(params or {})
        merged.update(kwargs)
        return cls(**merged)

    @classmethod
    def from_string(cls, parameters: str) -> "Config":
        return cls(**str2map(parameters))

    def update(self, params: Dict[str, Any]) -> None:
        resolved: Dict[str, Any] = {}
        for k, v in params.items():
            name = resolve_alias(k)
            if name in resolved and resolved[name] != v:
                log_warning(f"{name} is set with {resolved[name]}, "
                            f"will be overridden by {v}")
            resolved[name] = v
        for name, v in resolved.items():
            if name not in _PARAMS:
                log_warning(f"Unknown parameter: {name}")
                self.raw[name] = v
                continue
            setattr(self, name, _coerce(name, v, _PARAMS[name].ptype))
            self.raw[name] = v
        self._post_process()

    # -- validation (reference Config::CheckParamConflict, config.cpp:318+) --
    def _post_process(self) -> None:
        self.objective = OBJECTIVE_ALIASES.get(
            str(self.objective).strip().lower(), self.objective)
        if isinstance(self.metric, str):
            self.metric = _coerce("metric", self.metric, list)
        self.metric = [str(m).strip().lower() for m in self.metric if str(m).strip()]
        if self.num_leaves < 2:
            log_warning("num_leaves must be >= 2; setting to 2")
            self.num_leaves = 2
        if self.max_bin < 2:
            raise ValueError("max_bin should be >= 2")
        if self.bagging_freq > 0 and not (0.0 < self.bagging_fraction <= 1.0):
            raise ValueError("bagging_fraction must be in (0, 1]")
        if not (0.0 < self.feature_fraction <= 1.0):
            raise ValueError("feature_fraction must be in (0, 1]")
        if not (0.0 <= self.tpu_frontier_gain_ratio <= 1.0):
            # > 1.0 would reject every leaf including the round best and
            # spin the growth loop forever
            raise ValueError("tpu_frontier_gain_ratio must be in [0, 1]")
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            raise ValueError("num_class must be > 1 for multiclass objectives")
        if (self.objective not in ("multiclass", "multiclassova", "none")
                and self.num_class != 1):
            raise ValueError("num_class must be 1 for non-multiclass objectives")
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            raise ValueError(
                "is_unbalance and scale_pos_weight cannot both be set")
        # distributed learner implies a parallel tree learner choice stays valid
        tl = str(self.tree_learner).strip().lower()
        if tl in ("serial",):
            pass
        elif tl in ("feature", "feature_parallel", "data", "data_parallel",
                    "voting", "voting_parallel", "benchmark"):
            pass
        else:
            raise ValueError(f"Unknown tree learner type {self.tree_learner}")
        self.tree_learner = tl
        dib = str(self.data_in_hbm).strip().lower() or "auto"
        if dib not in ("auto", "resident", "spill"):
            raise ValueError("data_in_hbm must be one of auto, resident, "
                             f"spill (got {self.data_in_hbm!r})")
        if self.collective_retries < 0:
            raise ValueError("collective_retries must be >= 0")
        if self.collective_timeout_s <= 0:
            raise ValueError("collective_timeout_s must be > 0")
        if (self.coordinator_address and self.num_hosts > 0
                and self.host_rank >= self.num_hosts):
            raise ValueError(
                f"host_rank={self.host_rank} must be in "
                f"[0, num_hosts={self.num_hosts}) when "
                "coordinator_address is set (or -1 to auto-detect)")
        self.data_in_hbm = dib
        pd = str(self.predict_device).strip().lower() or "auto"
        if pd not in ("auto", "on", "off"):
            raise ValueError("predict_device must be one of auto, on, off "
                             f"(got {self.predict_device!r})")
        self.predict_device = pd
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_max_delay_ms < 0:
            raise ValueError("serve_max_delay_ms must be >= 0")
        if self.serve_queue_timeout_s <= 0:
            raise ValueError("serve_queue_timeout_s must be > 0")
        if self.serve_max_queue_rows < 0:
            raise ValueError("serve_max_queue_rows must be >= 0 "
                             "(0 = unbounded)")
        if self.swap_quality_threshold <= 0:
            raise ValueError("swap_quality_threshold must be > 0")
        if self.refit_poll_s <= 0:
            raise ValueError("refit_poll_s must be > 0")
        if self.serve_health_window_s <= 0:
            raise ValueError("serve_health_window_s must be > 0")
        if self.drift_psi_threshold <= 0:
            raise ValueError("drift_psi_threshold must be > 0")
        if self.drift_topk < 1:
            raise ValueError("drift_topk must be >= 1")
        sp = str(self.sched_policy).strip().lower() or "round_robin"
        sp = {"rr": "round_robin", "fair_share": "fair",
              "deficit": "fair"}.get(sp, sp)
        if sp not in ("round_robin", "fair"):
            raise ValueError(
                "sched_policy must be one of round_robin, fair "
                f"(got {self.sched_policy!r})")
        self.sched_policy = sp
        if self.sched_quantum_chunks < 1:
            raise ValueError("sched_quantum_chunks must be >= 1")
        if self.sched_max_jobs < 1:
            raise ValueError("sched_max_jobs must be >= 1")
        if self.fleet_obs_sync_iters < 0:
            raise ValueError("fleet_obs_sync_iters must be >= 0")
        if self.fleet_obs_clock_pings < 1:
            raise ValueError("fleet_obs_clock_pings must be >= 1")

    # -- accessors --
    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _PARAMS}

    def __repr__(self) -> str:
        diffs = {n: getattr(self, n) for n, s in _PARAMS.items()
                 if getattr(self, n) != s.default}
        return f"Config({diffs})"


def default_params() -> Dict[str, Any]:
    return {n: (list(s.default) if isinstance(s.default, list) else s.default)
            for n, s in _PARAMS.items()}
