"""Distributed tree learners over a device mesh.

The reference parallelizes tree learning across machines with hand-rolled
socket/MPI collectives (SURVEY.md §2.7): data-parallel (rows sharded,
histogram reduce-scatter + best-split allreduce,
src/treelearner/data_parallel_tree_learner.cpp:209-601), feature-parallel
(full data, split finding sharded by feature, 2xSplitInfo max-gain allreduce,
feature_parallel_tree_learner.cpp:33-75), and voting-parallel (top-k vote to
cut reduce volume, voting_parallel_tree_learner.cpp:170-380).

Here each strategy is a set of collective hooks injected into the SAME fused
grower and executed under ``shard_map`` over a 1-D ``machines`` mesh axis:

  * data-parallel:    rows sharded; every leaf histogram ``psum_scatter``s
    so each shard owns one contiguous COLUMN stripe (the reference's
    ReduceScatter-then-scan §3.4 pattern), each shard scans only its
    stripe, and the winning SplitInfo merges by max-gain all_gather.
    Forced-split runs fall back to a full-histogram ``lax.psum`` (the
    forced path reads the local leaf histogram without a merge).
  * feature-parallel: data replicated; each shard histograms AND scans
    only its contiguous column stripe, and the per-leaf SplitInfos merge
    via all_gather + argmax on gain (the packed-SplitInfo max-gain
    allreduce).
  * voting-parallel:  rows sharded; each shard votes its local top-k
    features by local best gain, votes are psum'd, and only the 2*top_k
    globally-elected features' histograms are reduced.

Multi-host: initialize ``jax.distributed`` so ``jax.devices()`` spans hosts;
the same axis then rides ICI within a slice and DCN across hosts — no code
changes (the reference's machine-list/socket handshake has no equivalent
work here).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.grower import CommHooks, GrowerParams, make_grow_tree
from ..ops.split import (NEG_INF, SplitInfo, SplitParams, expand_group_hist,
                         per_feature_gains)


def _instrument_grower(grow_fn, kind: str, tree_bytes: int):
    """Wrap a parallel grower so every tree records one collective
    entry (parallel/network.py counters): the static per-tree wire-byte
    estimate plus the host dispatch wall of the grow call (device
    collectives execute asynchronously inside the jitted grower, so
    dispatch wall is the honest host-side measure).

    The fused boosting step closes over the grower INSIDE a jit, where
    this Python wrapper only runs while tracing — recording there would
    count one bogus trace-time entry per compile instead of one per
    tree.  Tracing calls are skipped, and the kind/bytes tags are
    exposed as attributes so the fused dispatch site (gbdt.py) can
    record each eager step itself."""
    from . import network

    @functools.wraps(grow_fn)
    def grow(*args, **kwargs):
        if any(isinstance(a, jax.core.Tracer) for a in args):
            return grow_fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = grow_fn(*args, **kwargs)
        network.record_collective(kind, tree_bytes,
                                  time.perf_counter() - t0)
        return out
    grow._collective_kind = kind
    grow._collective_bytes = tree_bytes
    return grow


def _shard_map(fn, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _merge_split_by_gain(info: SplitInfo, gain, axis):
    """all_gather each SplitInfo field, keep the max-gain shard's
    (SyncUpGlobalBestSplit, parallel_tree_learner.h:356-397)."""
    gains = lax.all_gather(gain, axis)              # [D]
    winner = jnp.argmax(gains)
    merged = SplitInfo(*[lax.all_gather(f, axis)[winner] for f in info])
    return merged, gains[winner]


def _stripe_feature_mask(fmask, axis, start, per, feat_group):
    """Mask features whose physical COLUMN lies in [start, start+per) —
    the one place that maps a shard's column stripe back to feature space
    (identity column map when the dataset is unbundled)."""
    col = (jnp.asarray(np.asarray(feat_group), dtype=jnp.int32)
           if feat_group is not None
           else jnp.arange(fmask.shape[0], dtype=jnp.int32))
    stripe = (col >= start) & (col < start + per)
    return fmask * stripe.astype(fmask.dtype)


def _balanced_stripes(column_bins, D: int):
    """Contiguous column stripes with ~equal Σbins per shard (the
    reference re-balances feature-parallel shards by #bins,
    feature_parallel_tree_learner.cpp:36-47; an even column split skews
    badly when EFB bundles concentrate bins in few columns).

    Returns (starts [D], widths [D], per) where ``per`` is the max stripe
    width — the static column-block size every shard reads (narrower
    stripes mask the surplus columns out of the scan).  Because every
    shard's histogram block is ``per`` wide regardless of its own stripe,
    widths are capped at 2x the even split: bins-balance may only double
    the static block, never degenerate into one shard reading almost all
    columns.  Each boundary picks the side of the Σbins-crossing column
    closer to the target, so profiles the even split already handles
    optimally (e.g. [3, 5] on 2 shards) are never made worse."""
    cb = np.maximum(np.asarray(column_bins, dtype=np.int64), 1)
    G = len(cb)
    csum = np.cumsum(cb)
    total = int(csum[-1])
    even = -(-G // D)
    cap = min(2 * even, G)
    starts = np.zeros(D, dtype=np.int32)
    ends = np.zeros(D, dtype=np.int32)
    pos = 0
    for d in range(D):
        starts[d] = pos
        if d == D - 1:
            ends[d] = G
            break
        target = (d + 1) * total / D
        e = int(np.searchsorted(csum, target, side="left")) + 1
        # nearer boundary of the crossing column
        if e - 1 > pos and abs(csum[e - 2] - target) <= \
                abs(csum[e - 1] - target):
            e -= 1
        # feasibility: the remaining shards (cap wide each) must be able
        # to cover the remaining columns; this shard must respect cap
        e = max(e, pos, G - cap * (D - 1 - d))
        e = min(e, pos + cap, G)
        ends[d] = e
        pos = e
    widths = (ends - starts).astype(np.int32)
    assert int(widths.sum()) == G
    return starts, widths, int(widths.max(initial=1))


def _log_collective_estimate(mode: str, D: int, num_columns: int,
                             num_bins: int, num_leaves: int,
                             top_k: int = 0) -> int:
    """Static wire-byte estimate from mesh math (SURVEY §5: the TPU
    equivalent of the fork's Linkers byte counters, linkers.h:114-117).
    Ring allreduce moves ~2x the payload, reduce-scatter ~1x; the
    SplitInfo merge is ~14 scalars all_gathered per leaf scan.  Returns
    the per-tree byte total so the grower factories can feed the
    runtime collective counters (network.record_collective)."""
    from ..utils.log import log_info
    hist_bytes = num_columns * num_bins * 3 * 4
    per_split = {
        "data": hist_bytes,                # psum_scatter (reduce-scatter)
        "data_allreduce": 2 * hist_bytes,  # full-hist psum fallback
        "data_segment": hist_bytes,        # psum_scatter (reduce-scatter)
        # same total bytes as data_segment, but one K-batched launch per
        # round instead of one per split — K x fewer collectives
        "data_frontier": hist_bytes,
        "voting": 2 * hist_bytes * min(1.0, 2 * top_k / max(num_columns, 1))
        + num_columns * 4,                 # elected slices + vote psum
        "feature": 0,                      # scan-only; no hist crosses
    }.get(mode, 0)
    split_info = 14 * 4 * D * 2            # all_gather of 2 SplitInfos
    total = (num_leaves - 1) * (per_split + split_info)
    log_info(f"collective estimate [{mode}, D={D}]: "
             f"{per_split + split_info} B/split, "
             f"{total / 1e6:.1f} MB/tree on the wire")
    return int(total)


def _make_voting_reduce(axis, sp, top_k: int):
    """Voting-parallel histogram reduction (PV-Tree,
    voting_parallel_tree_learner.cpp:170-380): local top-k vote in
    FEATURE space, global election by psum'd votes, and only elected
    columns' histograms cross the wire."""
    def reduce_voted(h, G, H, C, fmeta):
        # vote in FEATURE space on the expanded view (identity when
        # unbundled), reduce in COLUMN space.  The vote must use LOCAL
        # leaf totals — G/H/C are already psum'd global stats, and
        # expanding the pre-reduce partial histogram with global totals
        # would inflate the reconstructed default-bin slot by the other
        # shards' mass.  Every row lands in exactly one bin of every
        # column, so column 0's bin-sum IS the local (g, h, count).
        loc = h[0].sum(axis=0)
        hf = expand_group_hist(h, fmeta, loc[0], loc[1], loc[2])
        local_gains = per_feature_gains(hf, loc[0], loc[1], loc[2],
                                        fmeta, sp)               # [F]
        F = local_gains.shape[0]
        k = min(top_k, F)
        gains_top, local_top = lax.top_k(local_gains, k)
        votes = jnp.zeros(F, dtype=jnp.int32).at[local_top].add(
            jnp.where(gains_top > NEG_INF, 1, 0))
        votes = lax.psum(votes, axis)
        k2 = min(2 * top_k, F)
        _, elected = lax.top_k(votes, k2)
        fmask = jnp.zeros(F, dtype=h.dtype).at[elected].set(1.0)
        if fmeta.feat_group is not None:
            # a column crosses the wire if ANY member feature is elected
            mask = jnp.zeros(h.shape[0], dtype=h.dtype) \
                .at[fmeta.feat_group].max(fmask)
        else:
            mask = fmask
        # only elected columns' histograms cross the wire; the rest are
        # zeroed so their candidates mask out in the scan
        return lax.psum(h * mask[:, None, None], axis)
    return reduce_voted


def make_parallel_grower(num_bins: int, params: GrowerParams, mesh: Mesh,
                         mode: str, top_k: int = 20,
                         num_columns: int = 0, feat_group=None,
                         column_bins=None):
    """shard_map-wrapped grower for mode in {'data', 'feature', 'voting'}.

    Argument order of the returned fn matches the serial grower:
    (bins, grad, hess, member, fmeta, feature_mask, key).
    ``num_columns``/``feat_group`` locate features in the physical bin
    matrix for the feature-parallel column stripes (EFB, core/bundle.py);
    ``column_bins`` (per-column bin counts) balances those stripes by
    Σbins the way the reference does.
    """
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    sp = params.split
    repl = P()

    if mode in ("data", "data_parallel"):
        # forced splits read the local leaf histogram without a merge, so
        # they need the full-histogram psum variant, not stripe ownership
        if num_columns > 0 and not params.forced_plan:
            # the reference's §3.4 pattern (data_parallel_tree_learner.cpp:
            # 437-447): reduce-scatter so each shard owns one contiguous
            # column stripe, scan only the stripe, merge the winning
            # SplitInfo by max gain — half the wire bytes of an allreduce
            # and no redundant scan work
            G = num_columns
            Gpad = -(-G // D) * D
            per = Gpad // D

            def reduce_hist(h, *_):
                hp = jnp.pad(h, ((0, Gpad - G), (0, 0), (0, 0)))
                mine = lax.psum_scatter(hp, axis, scatter_dimension=0,
                                        tiled=True)
                me = lax.axis_index(axis)
                out = jnp.zeros_like(hp)
                out = lax.dynamic_update_slice(out, mine, (me * per, 0, 0))
                return out[:G]

            def shard_mask(fmask):
                return _stripe_feature_mask(
                    fmask, axis, lax.axis_index(axis) * per, per,
                    feat_group)

            comm = CommHooks(
                reduce_hist=reduce_hist,
                reduce_stats=lambda x: lax.psum(x, axis),
                merge_split=lambda info, gain: _merge_split_by_gain(
                    info, gain, axis),
                shard_feature_mask=shard_mask)
        else:
            comm = CommHooks(
                reduce_hist=lambda h, G, H, C, f: lax.psum(h, axis),
                reduce_stats=lambda x: lax.psum(x, axis))
        in_specs = (P(axis, None), P(axis), P(axis), P(axis), repl, repl,
                    repl)
        out_specs = (repl, P(axis))
    elif mode in ("feature", "feature_parallel"):
        # every shard holds the FULL data but histograms and scans only a
        # contiguous COLUMN stripe; the winning SplitInfo merges by
        # max-gain and all shards split locally — the reference's
        # feature-parallel contract (feature_parallel_tree_learner.cpp:
        # 36-75, histograms only for the rank's own features).  Stripe
        # boundaries balance per-shard Σbins like the reference (:36-47)
        # when per-column bin counts are known; even column split is the
        # uniform-bins special case.
        column_block, shard_mask, per = _feature_stripes(
            mesh, num_columns, feat_group, column_bins)

        comm = CommHooks(
            merge_split=lambda info, gain: _merge_split_by_gain(
                info, gain, axis),
            shard_feature_mask=shard_mask,
            column_block=column_block)
        in_specs = (repl, repl, repl, repl, repl, repl, repl)
        out_specs = (repl, repl)
    elif mode in ("voting", "voting_parallel"):
        reduce_voted = _make_voting_reduce(axis, sp, top_k)
        # votes differ per histogram call, so parent/child histograms carry
        # different election masks; the subtraction trick is invalid here
        # and both children must be histogrammed from data
        comm = CommHooks(
            reduce_hist=reduce_voted,
            reduce_stats=lambda x: lax.psum(x, axis),
            no_subtract=True)
        in_specs = (P(axis, None), P(axis), P(axis), P(axis), repl, repl,
                    repl)
        out_specs = (repl, P(axis))
    else:
        raise ValueError(f"Unknown parallel tree learner mode {mode}")

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    est_mode = mode.split("_")[0]
    if est_mode == "data" and (num_columns <= 0 or params.forced_plan):
        est_mode = "data_allreduce"        # the full-hist psum fallback
    tree_bytes = _log_collective_estimate(est_mode, D, num_columns or 0,
                                          num_bins, params.num_leaves,
                                          top_k)
    return _instrument_grower(
        make_grow_tree(num_bins, params, comm=comm, wrap=wrap),
        est_mode, tree_bytes)


def _stripe_setup(mesh: Mesh, num_columns: int, feat_group):
    """Shared data-parallel stripe scaffolding: (axis, D, Gpad, per,
    shard_mask, wrap-in/out specs).  Both the strict segment learner and
    the frontier learner shard rows on the mesh axis and own one
    contiguous reduced column stripe each."""
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    Gpad = -(-num_columns // D) * D
    per = Gpad // D

    def shard_mask(fmask):
        return _stripe_feature_mask(fmask, axis,
                                    lax.axis_index(axis) * per, per,
                                    feat_group)

    in_specs = (P(None, axis), P(axis), P(axis), P(axis), P(), P(), P())
    # third output: the grower's [6] counter vector, stacked per device so
    # the host prints one seg-stats row per shard
    out_specs = (P(), P(axis), P(axis))
    return axis, D, Gpad, per, shard_mask, in_specs, out_specs


def make_data_parallel_segment_grower(num_bins: int, params: GrowerParams,
                                      mesh: Mesh, block_rows: int,
                                      num_columns: int, feat_group=None):
    """Data-parallel learner with the segment grower's O(leaf) per-split
    cost AND the reference's §3.4 communication pattern
    (data_parallel_tree_learner.cpp:437-447):

      * rows sharded over the mesh axis; each shard keeps its own permuted
        layout / confinement intervals / compaction (sorts are D× smaller
        and run in parallel);
      * every leaf histogram is ``psum_scatter``-reduced so each shard owns
        the reduced histogram of one CONTIGUOUS feature stripe — the wire
        carries reduce-scatter bytes only, not a full allreduce;
      * each shard scans only its stripe (scan feature-mask) and the
        winning SplitInfo is merged by max-gain all_gather
        (SyncUpGlobalBestSplit, parallel_tree_learner.h:356-397);
      * all shards then apply the winning split locally — no row data ever
        crosses the interconnect.
    """
    from ..models.grower_seg import make_grow_tree_segment

    G = num_columns
    axis, D, Gpad, per, shard_mask, in_specs, out_specs = _stripe_setup(
        mesh, G, feat_group)

    def reduce_hist(h, *_):
        # [G, B, 3] per-shard partials -> reduced COLUMN stripe per shard,
        # placed back at its offset (non-stripe rows zero; the scan masks
        # out their features)
        hp = jnp.pad(h, ((0, Gpad - G), (0, 0), (0, 0)))
        mine = lax.psum_scatter(hp, axis, scatter_dimension=0, tiled=True)
        me = lax.axis_index(axis)
        out = jnp.zeros_like(hp)
        out = lax.dynamic_update_slice(out, mine, (me * per, 0, 0))
        return out[:G]

    comm = CommHooks(
        reduce_hist=reduce_hist,
        reduce_stats=lambda x: lax.psum(x, axis),
        merge_split=lambda info, gain: _merge_split_by_gain(info, gain,
                                                            axis),
        shard_feature_mask=shard_mask,
        uniform_scan=lambda b: lax.pmax(b, axis))

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    tree_bytes = _log_collective_estimate("data_segment", D, G, num_bins,
                                          params.num_leaves)
    return _instrument_grower(
        make_grow_tree_segment(num_bins, params, block_rows, comm=comm,
                               wrap=wrap),
        "data_segment", tree_bytes)


def make_data_parallel_frontier_grower(num_bins: int, params: GrowerParams,
                                       mesh: Mesh, block_rows: int,
                                       num_columns: int, feat_group=None,
                                       batch_k: int = 0,
                                       gain_ratio: float = 0.0):
    """Data-parallel frontier-batched learner: the K-splits-per-round
    grower (models/grower_frontier.py) under shard_map.

    Same wire pattern as the strict data-parallel segment learner —
    psum_scatter column stripes, stripe-masked scans, max-gain SplitInfo
    merge — but one collective carries the WHOLE [K, G, B, 3] round batch
    and one all_gather merges all 2K children's SplitInfos: K x fewer
    collective launches per tree, which matters on a latency-bound
    interconnect exactly the way the batched matmul matters on the MXU.
    """
    from ..models.grower import CommHooks
    from ..models.grower_frontier import make_grow_tree_frontier

    G = num_columns
    axis, D, Gpad, per, shard_mask, in_specs, out_specs = _stripe_setup(
        mesh, G, feat_group)

    def reduce_hist_batch(h, fmeta=None):
        # [K, G, B, 3] per-shard partials -> each shard owns the reduced
        # [K, stripe, B, 3] of one contiguous column stripe, placed back
        # at its offset (zeros elsewhere; stripe masks hide them)
        hp = jnp.pad(h, ((0, 0), (0, Gpad - G), (0, 0), (0, 0)))
        mine = lax.psum_scatter(hp, axis, scatter_dimension=1, tiled=True)
        me = lax.axis_index(axis)
        out = jnp.zeros_like(hp)
        out = lax.dynamic_update_slice(out, mine, (0, me * per, 0, 0))
        return out[:, :G]

    comm = CommHooks(
        reduce_stats=lambda x: lax.psum(x, axis),
        shard_feature_mask=shard_mask,
        reduce_hist_batch=reduce_hist_batch,
        merge_split_batch=lambda infos, gains: _merge_batch_by_gain(
            infos, gains, axis))

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    tree_bytes = _log_collective_estimate("data_frontier", D, G, num_bins,
                                          params.num_leaves)
    return _instrument_grower(
        make_grow_tree_frontier(num_bins, params, block_rows,
                                batch_k=batch_k, gain_ratio=gain_ratio,
                                comm=comm, wrap=wrap),
        "data_frontier", tree_bytes)


def _feature_stripes(mesh: Mesh, num_columns: int, feat_group,
                     column_bins):
    """Feature-parallel stripe maps shared by the fused and O(leaf)
    learners: (column_block, shard_mask, per) with Σbins balancing
    (feature_parallel_tree_learner.cpp:36-47)."""
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    G = num_columns
    if column_bins is not None and len(column_bins) == G and D > 1:
        starts_np, widths_np, per = _balanced_stripes(column_bins, D)
    else:
        per = -(-G // D)
        starts_np = (np.arange(D) * per).astype(np.int32)
        widths_np = np.minimum(per, np.maximum(
            G - starts_np, 0)).astype(np.int32)
    block_starts_d = jnp.asarray(np.minimum(starts_np, max(G - per, 0))
                                 .astype(np.int32))
    starts_d = jnp.asarray(starts_np)
    widths_d = jnp.asarray(widths_np)

    def column_block(bins):
        return block_starts_d[lax.axis_index(axis)], per

    def shard_mask(fmask):
        me = lax.axis_index(axis)
        return _stripe_feature_mask(fmask, axis, starts_d[me],
                                    widths_d[me], feat_group)

    return column_block, shard_mask, per


def make_feature_parallel_oleaf_grower(num_bins: int, params: GrowerParams,
                                       mesh: Mesh, block_rows: int,
                                       num_columns: int, feat_group=None,
                                       column_bins=None,
                                       impl: str = "segment",
                                       batch_k: int = 0,
                                       gain_ratio: float = 0.0):
    """Feature-parallel learner on the O(leaf) segment/frontier growers.

    The reference's feature-parallel contract
    (feature_parallel_tree_learner.cpp:33-75) on the O(leaf) machinery:
    data REPLICATED on every shard; each shard histograms AND scans only
    its Σbins-balanced column stripe over the leaf's confinement
    interval; SplitInfos merge by max-gain all_gather; every shard then
    routes/compacts locally (identical layouts, no row data on the
    wire).  Histogram kernel cost is cut D× by the column slice — the
    interval scan structure is untouched.
    """
    from ..models.grower_frontier import make_grow_tree_frontier
    from ..models.grower_seg import make_grow_tree_segment

    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    column_block, shard_mask, _per = _feature_stripes(
        mesh, num_columns, feat_group, column_bins)

    repl = P()
    in_specs = (repl,) * 7
    out_specs = (repl, repl, repl)

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    tree_bytes = _log_collective_estimate("feature", D, num_columns,
                                          num_bins, params.num_leaves)
    if impl == "frontier":
        comm = CommHooks(
            shard_feature_mask=shard_mask, column_block=column_block,
            merge_split_batch=lambda infos, gains: _merge_batch_by_gain(
                infos, gains, axis))
        return _instrument_grower(
            make_grow_tree_frontier(num_bins, params, block_rows,
                                    batch_k=batch_k,
                                    gain_ratio=gain_ratio, comm=comm,
                                    wrap=wrap),
            "feature", tree_bytes)
    comm = CommHooks(
        merge_split=lambda info, gain: _merge_split_by_gain(info, gain,
                                                            axis),
        shard_feature_mask=shard_mask, column_block=column_block)
    return _instrument_grower(
        make_grow_tree_segment(num_bins, params, block_rows, comm=comm,
                               wrap=wrap),
        "feature", tree_bytes)


def _merge_batch_by_gain(infos, gains, axis):
    """[2K]-batched SyncUpGlobalBestSplit (shared by the data- and
    feature-parallel frontier learners)."""
    gall = lax.all_gather(gains, axis)              # [D, 2K]
    winner = jnp.argmax(gall, axis=0)               # [2K]
    pick = jnp.arange(gains.shape[0])
    merged = SplitInfo(*[lax.all_gather(f, axis)[winner, pick]
                         for f in infos])
    return merged, gall[winner, pick]


def make_voting_parallel_oleaf_grower(num_bins: int, params: GrowerParams,
                                      mesh: Mesh, block_rows: int,
                                      num_columns: int, feat_group=None,
                                      top_k: int = 20,
                                      impl: str = "segment",
                                      batch_k: int = 0,
                                      gain_ratio: float = 0.0):
    """Voting-parallel learner on the O(leaf) segment/frontier growers.

    PV-Tree (voting_parallel_tree_learner.cpp:170-380) with rows sharded
    like the data-parallel O(leaf) learners: each shard votes its local
    top-k features per histogram call, only the globally-elected columns'
    histograms are psum'd, and both children are histogrammed from data
    (election masks differ per call, so parent-minus-smaller is invalid
    — CommHooks.no_subtract).
    """
    from ..models.grower_frontier import make_grow_tree_frontier
    from ..models.grower_seg import make_grow_tree_segment

    G = num_columns
    axis, D, Gpad, per, _smask, in_specs, out_specs = _stripe_setup(
        mesh, G, feat_group)
    reduce_voted = _make_voting_reduce(axis, params.split, top_k)

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    tree_bytes = _log_collective_estimate("voting", D, G, num_bins,
                                          params.num_leaves, top_k)
    if impl == "frontier":
        def reduce_batch(h, fmeta=None):
            # per-leaf elections over the [K, G, B, 3] round batch
            return jax.vmap(
                lambda hk: reduce_voted(hk, None, None, None, fmeta))(h)

        comm = CommHooks(
            reduce_stats=lambda x: lax.psum(x, axis),
            reduce_hist_batch=reduce_batch,
            merge_split_batch=lambda infos, gains: (infos, gains),
            no_subtract=True)
        return _instrument_grower(
            make_grow_tree_frontier(num_bins, params, block_rows,
                                    batch_k=batch_k,
                                    gain_ratio=gain_ratio, comm=comm,
                                    wrap=wrap),
            "voting", tree_bytes)
    comm = CommHooks(
        reduce_hist=reduce_voted,
        reduce_stats=lambda x: lax.psum(x, axis),
        no_subtract=True,
        uniform_scan=lambda b: lax.pmax(b, axis))
    return _instrument_grower(
        make_grow_tree_segment(num_bins, params, block_rows, comm=comm,
                               wrap=wrap),
        "voting", tree_bytes)
