"""Distributed tree learners over a device mesh.

The reference parallelizes tree learning across machines with hand-rolled
socket/MPI collectives (SURVEY.md §2.7): data-parallel (rows sharded,
histogram reduce-scatter + best-split allreduce,
src/treelearner/data_parallel_tree_learner.cpp:209-601), feature-parallel
(full data, split finding sharded by feature, 2xSplitInfo max-gain allreduce,
feature_parallel_tree_learner.cpp:33-75), and voting-parallel (top-k vote to
cut reduce volume, voting_parallel_tree_learner.cpp:170-380).

Here each strategy is a set of collective hooks injected into the SAME fused
grower and executed under ``shard_map`` over a 1-D ``machines`` mesh axis:

  * data-parallel:    rows sharded; per-histogram ``lax.psum`` over ICI (the
    runtime lowers the replicated-output psum to reduce-scatter +
    all-gather, i.e. the reference's ReduceScatter-then-scan pattern but
    compiler-scheduled); root stats psum.
  * feature-parallel: data replicated; each shard strips the tree-level
    feature mask to its modulo stripe, scans only those features, and the
    per-leaf SplitInfos merge via all_gather + argmax on gain (the packed-
    SplitInfo max-gain allreduce).
  * voting-parallel:  rows sharded; each shard votes its local top-k
    features by local best gain, votes are psum'd, and only the 2*top_k
    globally-elected features' histograms are reduced.

Multi-host: initialize ``jax.distributed`` so ``jax.devices()`` spans hosts;
the same axis then rides ICI within a slice and DCN across hosts — no code
changes (the reference's machine-list/socket handshake has no equivalent
work here).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.grower import CommHooks, GrowerParams, make_grow_tree
from ..ops.split import NEG_INF, SplitInfo, SplitParams, per_feature_gains


def _shard_map(fn, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _merge_split_by_gain(info: SplitInfo, gain, axis):
    """all_gather each SplitInfo field, keep the max-gain shard's
    (SyncUpGlobalBestSplit, parallel_tree_learner.h:356-397)."""
    gains = lax.all_gather(gain, axis)              # [D]
    winner = jnp.argmax(gains)
    merged = SplitInfo(*[lax.all_gather(f, axis)[winner] for f in info])
    return merged, gains[winner]


def make_parallel_grower(num_bins: int, params: GrowerParams, mesh: Mesh,
                         mode: str, top_k: int = 20):
    """shard_map-wrapped grower for mode in {'data', 'feature', 'voting'}.

    Argument order of the returned fn matches the serial grower:
    (bins, grad, hess, member, fmeta, feature_mask, key).
    """
    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    sp = params.split
    repl = P()

    if mode in ("data", "data_parallel"):
        comm = CommHooks(
            reduce_hist=lambda h, G, H, C, f: lax.psum(h, axis),
            reduce_stats=lambda x: lax.psum(x, axis))
        in_specs = (P(axis, None), P(axis), P(axis), P(axis), repl, repl,
                    repl)
        out_specs = (repl, P(axis))
    elif mode in ("feature", "feature_parallel"):
        def shard_mask(fmask):
            # features striped modulo D (the reference re-balances by #bins
            # per tree, feature_parallel_tree_learner.cpp:36-47; a stripe is
            # an even split when bins are uniform)
            F = fmask.shape[0]
            me = lax.axis_index(axis)
            stripe = (jnp.arange(F, dtype=jnp.int32) % D) == me
            return fmask * stripe.astype(fmask.dtype)

        # TODO(perf): histograms are still built for ALL features on every
        # shard (only the scan is striped); sharding construction itself
        # needs the grower to histogram a per-shard feature slice while
        # routing on the full matrix — tracked for the distributed phase.
        comm = CommHooks(
            merge_split=lambda info, gain: _merge_split_by_gain(
                info, gain, axis),
            shard_feature_mask=shard_mask)
        in_specs = (repl, repl, repl, repl, repl, repl, repl)
        out_specs = (repl, repl)
    elif mode in ("voting", "voting_parallel"):
        def reduce_voted(h, G, H, C, fmeta):
            local_gains = per_feature_gains(h, G, H, C, fmeta, sp)   # [F]
            F = h.shape[0]
            k = min(top_k, F)
            gains_top, local_top = lax.top_k(local_gains, k)
            votes = jnp.zeros(F, dtype=jnp.int32).at[local_top].add(
                jnp.where(gains_top > NEG_INF, 1, 0))
            votes = lax.psum(votes, axis)
            k2 = min(2 * top_k, F)
            _, elected = lax.top_k(votes, k2)
            mask = jnp.zeros(F, dtype=h.dtype).at[elected].set(1.0)
            # only elected features' histograms cross the wire; the rest are
            # zeroed so their candidates mask out in the scan
            return lax.psum(h * mask[:, None, None], axis)

        # votes differ per histogram call, so parent/child histograms carry
        # different election masks; the subtraction trick is invalid here
        # and both children must be histogrammed from data
        comm = CommHooks(
            reduce_hist=reduce_voted,
            reduce_stats=lambda x: lax.psum(x, axis),
            no_subtract=True)
        in_specs = (P(axis, None), P(axis), P(axis), P(axis), repl, repl,
                    repl)
        out_specs = (repl, P(axis))
    else:
        raise ValueError(f"Unknown parallel tree learner mode {mode}")

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    return make_grow_tree(num_bins, params, comm=comm, wrap=wrap)


def make_data_parallel_segment_grower(num_bins: int, params: GrowerParams,
                                      mesh: Mesh, block_rows: int,
                                      num_features: int):
    """Data-parallel learner with the segment grower's O(leaf) per-split
    cost AND the reference's §3.4 communication pattern
    (data_parallel_tree_learner.cpp:437-447):

      * rows sharded over the mesh axis; each shard keeps its own permuted
        layout / confinement intervals / compaction (sorts are D× smaller
        and run in parallel);
      * every leaf histogram is ``psum_scatter``-reduced so each shard owns
        the reduced histogram of one CONTIGUOUS feature stripe — the wire
        carries reduce-scatter bytes only, not a full allreduce;
      * each shard scans only its stripe (scan feature-mask) and the
        winning SplitInfo is merged by max-gain all_gather
        (SyncUpGlobalBestSplit, parallel_tree_learner.h:356-397);
      * all shards then apply the winning split locally — no row data ever
        crosses the interconnect.
    """
    from ..models.grower_seg import make_grow_tree_segment

    axis = mesh.axis_names[0]
    D = int(mesh.devices.size)
    F = num_features
    Fpad = -(-F // D) * D
    per = Fpad // D

    def reduce_hist(h, *_):
        # [F, B, 3] per-shard partials -> reduced stripe per shard, placed
        # back at its offset (non-stripe rows zero; the scan masks them)
        hp = jnp.pad(h, ((0, Fpad - F), (0, 0), (0, 0)))
        mine = lax.psum_scatter(hp, axis, scatter_dimension=0, tiled=True)
        me = lax.axis_index(axis)
        out = jnp.zeros_like(hp)
        out = lax.dynamic_update_slice(out, mine, (me * per, 0, 0))
        return out[:F]

    def shard_mask(fmask):
        me = lax.axis_index(axis)
        idx = jnp.arange(F, dtype=jnp.int32)
        stripe = (idx >= me * per) & (idx < (me + 1) * per)
        return fmask * stripe.astype(fmask.dtype)

    comm = CommHooks(
        reduce_hist=reduce_hist,
        reduce_stats=lambda x: lax.psum(x, axis),
        merge_split=lambda info, gain: _merge_split_by_gain(info, gain,
                                                            axis),
        shard_feature_mask=shard_mask)

    in_specs = (P(None, axis), P(axis), P(axis), P(axis), P(), P(), P())
    out_specs = (P(), P(axis))

    def wrap(grow):
        return jax.jit(_shard_map(grow, mesh, in_specs, out_specs))

    return make_grow_tree_segment(num_bins, params, block_rows, comm=comm,
                                  wrap=wrap)
