"""Preemption-tolerant multi-host lifecycle over ``jax.distributed``.

The reference's multi-machine story is a machine list + socket handshake
(``Network::Init``, src/network/linkers_socket.cpp:23-230) and its
failure story is "the TCP read times out and the job dies".  On a
preemptible TPU fleet the collectives themselves are XLA's problem; the
hard part is everything around them — bringing the world up from a
launcher's environment, surviving a host that vanishes mid-run, and
stopping N hosts at the *same* iteration when any one of them receives
a preemption notice.  This module owns that layer:

  * **Init lifecycle** — :func:`maybe_initialize` drives an explicit
    ``jax.distributed.initialize`` from config (``coordinator_address=``,
    ``num_hosts=``, ``host_rank=``) or from the same launch markers
    ``network.binning_world()`` recognizes (SLURM / OpenMPI / TPU pod
    env), with retry/backoff via ``utils/retry.py`` and the
    deterministic ``dist/init`` fault site.  :func:`shutdown_owned`
    tears down only a client this module created — an externally
    initialized world is adopted, never destroyed.

  * **Host-level collectives over the coordinator KV store** — the
    coordination service that ``jax.distributed`` already runs gives
    every host a tiny strongly-consistent KV namespace with *per-call
    timeouts*.  :func:`kv_allgather_bytes` is the transport behind
    ``network.allgather_obj`` on multi-process runs: it works on every
    backend (XLA's CPU backend has no cross-process computations, so
    ``multihost_utils`` cannot serve the 2-process CPU test harness),
    and a dead peer surfaces as a DEADLINE naming the missing rank
    instead of a hang.

  * **Barrier with a deadline** — :func:`barrier` announces this rank
    under a per-call generation key and polls every other rank's
    announcement with a bounded budget; on expiry it raises a
    ``LightGBMError`` naming exactly which ranks never arrived.  Used
    at snapshot and resume boundaries so one dead host produces an
    actionable error, not a wedged fleet.

  * **Cross-host snapshot election** — :func:`elect_snapshot` allgathers
    each host's local snapshot manifest and elects the newest iteration
    *every* host possesses; hosts whose local newest is ahead roll back
    to the common one, so a fleet restarted after an uncoordinated kill
    resumes bit-identically instead of diverging.

  * **Coordinated preemption** — any host that receives SIGTERM (or
    trips the ``dist/preempt`` fault site) posts a preemption notice to
    the KV store; every host sees it at its next iteration boundary,
    the fleet allgathers its per-host progress and agrees on the
    maximum (:func:`negotiate_preempt_target`), trains up to that
    iteration, barriers, snapshots synchronously, and exits with
    :data:`PREEMPT_EXIT_CODE` — a restart with ``resume=true`` then
    elects exactly that snapshot on every host.

Every cross-host step lands in the run-health stream as a ``dist``
record (rank, world, barrier waits, elected iteration), so a live
monitor can watch a preemption drain in real time.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.log import LightGBMError, log_info, log_warning

# sysexits.h EX_TEMPFAIL: "try again later" — the scheduler-facing
# contract for a run that checkpointed and exited under preemption
PREEMPT_EXIT_CODE = 75

# KV namespaces (all under the coordination service's flat store)
_AG_PREFIX = "lgbm/ag"           # allgather payload chunks
_BAR_PREFIX = "lgbm/bar"         # barrier announcements
_PREEMPT_DIR = "lgbm/preempt/"   # preemption notice directory
_PREEMPT_KEY = _PREEMPT_DIR + "notice"   # single JSON value

_KV_CHUNK = 1 << 20              # 1 MiB per KV value: stay far below the
#                                  coordination-service gRPC message cap

# env fallbacks for the config knobs (a launcher that cannot edit argv
# exports these instead); the conftest scrub namespace is deliberate —
# tests must opt in explicitly
ENV_COORDINATOR = "LIGHTGBM_TPU_COORDINATOR_ADDRESS"
ENV_NUM_HOSTS = "LIGHTGBM_TPU_NUM_HOSTS"
ENV_HOST_RANK = "LIGHTGBM_TPU_HOST_RANK"


class _State:
    __slots__ = ("owned", "ag_gen", "bar_gen", "preempt_seen",
                 "local_notice")

    def __init__(self):
        self.owned = False           # this module called initialize()
        self.ag_gen = 0              # allgather generation counter
        self.bar_gen = 0             # barrier generation counter
        self.preempt_seen = False    # a notice was already acted on
        self.local_notice = None     # reason set by SIGTERM/fault site


_state = _State()


# --------------------------------------------------------------------- world
def client():
    """The live coordination-service client, or ``None``.  Read through
    jax's private distributed state (same access the rest of this repo
    uses in ``network.binning_world``) — it never initializes a device
    backend."""
    try:
        from jax._src import distributed as _jd
        return _jd.global_state.client
    except (ImportError, AttributeError):
        return None


def world() -> int:
    """Process count of the initialized world (1 when uninitialized).
    Read from distributed state, not ``jax.process_count()``, so asking
    never triggers a backend init."""
    try:
        from jax._src import distributed as _jd
        n = _jd.global_state.num_processes
        return int(n) if n else 1
    except (ImportError, AttributeError):
        return 1


def rank() -> int:
    try:
        from jax._src import distributed as _jd
        r = _jd.global_state.process_id
        return int(r) if r else 0
    except (ImportError, AttributeError):
        return 0


def is_active() -> bool:
    """True when a multi-process world is up (client present, world>1)."""
    return client() is not None and world() > 1


def _health(event: str, **fields) -> None:
    """One ``dist`` record into the run-health stream (no-op when no
    stream is open): every cross-host step is narrated with rank/world
    so a live monitor can watch a preemption drain."""
    from ..utils.telemetry import HEALTH
    if not HEALTH.active:
        return
    rec: Dict[str, Any] = {"event": event, "rank": rank(),
                           "world": world()}
    rec.update(fields)
    HEALTH.record("dist", rec)


def probe_slow() -> None:
    """Deterministic straggler injection: the ``dist/slow`` fault site,
    probed at every host-side collective ENTRY.  Unlike every other
    site it does not fail the operation — a fired spec converts into a
    fixed sleep (``LIGHTGBM_TPU_SLOW_MS``, default 300ms) before this
    rank enters the collective, making the armed rank arrive last and
    exercising the fleet plane's wait-vs-work attribution end to end
    (the fault_matrix fleet pass and the 2-process straggler test)."""
    from ..utils.faults import FAULTS, InjectedFault
    if not FAULTS.enabled:
        return
    try:
        FAULTS.maybe_raise("dist/slow")
    except InjectedFault:
        from ..utils.telemetry import TELEMETRY
        delay = float(os.environ.get("LIGHTGBM_TPU_SLOW_MS", "300")) / 1e3
        TELEMETRY.fault_event("injected_slow", site="dist/slow",
                              detail=f"sleep {delay:g}s rank {rank()}")
        time.sleep(delay)


# ------------------------------------------------------------------ detection
def detect_launch(config=None) -> Optional[Tuple[str, int, int]]:
    """Resolve ``(coordinator_address, num_hosts, host_rank)`` from the
    env fallbacks (which win, mirroring every other knob) or the config.
    Returns ``None`` when nothing requests a multi-host world.  A
    partial spec (coordinator without a resolvable world/rank) is a
    config error, not a silent single-host run."""
    coord = os.environ.get(ENV_COORDINATOR, "")
    nhosts_s = os.environ.get(ENV_NUM_HOSTS, "")
    rank_s = os.environ.get(ENV_HOST_RANK, "")
    if not coord and config is not None:
        coord = str(getattr(config, "coordinator_address", "") or "")
        if not nhosts_s:
            nhosts_s = str(int(getattr(config, "num_hosts", 0) or 0))
        if not rank_s:
            hr = int(getattr(config, "host_rank", -1))
            rank_s = "" if hr < 0 else str(hr)
    if not coord:
        return None
    # the launch markers binning_world() recognizes double as world/rank
    # sources when the explicit knobs are absent
    if not nhosts_s or int(nhosts_s or 0) <= 0:
        nhosts_s = (os.environ.get("SLURM_JOB_NUM_NODES", "")
                    or os.environ.get("OMPI_COMM_WORLD_SIZE", ""))
    if not rank_s:
        rank_s = (os.environ.get("SLURM_PROCID", "")
                  or os.environ.get("OMPI_COMM_WORLD_RANK", ""))
    try:
        nhosts = int(nhosts_s)
        host_rank = int(rank_s)
    except ValueError:
        raise LightGBMError(
            f"coordinator_address={coord!r} is set but the world could "
            f"not be resolved (num_hosts={nhosts_s!r}, "
            f"host_rank={rank_s!r}); set num_hosts=/host_rank= (or the "
            f"{ENV_NUM_HOSTS}/{ENV_HOST_RANK} env vars)")
    if nhosts <= 0 or host_rank < 0 or host_rank >= nhosts:
        raise LightGBMError(
            f"invalid multi-host spec: coordinator={coord} "
            f"num_hosts={nhosts} host_rank={host_rank}")
    return coord, nhosts, host_rank


def maybe_initialize(config=None) -> bool:
    """Bring the multi-host world up when the config/env requests one.

    Idempotent: an already-initialized world (ours or external) is
    adopted as-is.  The handshake itself retries with backoff under the
    configured collective policy, and the deterministic ``dist/init``
    fault site fires before the real call so init-failure handling is
    testable without killing a coordinator.  Returns True when a
    multi-process world is up after the call."""
    if client() is not None:
        return world() > 1
    launch = detect_launch(config)
    if launch is None:
        return False
    coord, nhosts, host_rank = launch
    if nhosts == 1:
        log_info("multi-host spec resolves to a single host; skipping "
                 "jax.distributed init")
        return False
    from ..utils.faults import FAULTS
    from ..utils.retry import retry_call
    from ..utils.telemetry import TELEMETRY
    from . import network

    retries, timeout_s, backoff_s = network.collective_policy()

    def _init():
        FAULTS.maybe_raise("dist/init")
        import jax
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nhosts,
            process_id=host_rank,
            initialization_timeout=max(1, int(timeout_s)))

    def _on_retry(_k, e):
        TELEMETRY.fault_event("collective_retry", site="dist/init",
                              detail=str(e))

    t0 = time.perf_counter()
    retry_call(_init, attempts=1 + retries, backoff_s=backoff_s,
               fatal=(LightGBMError,), on_retry=_on_retry,
               label="dist/init")
    _state.owned = True
    log_info(f"jax.distributed initialized: rank {host_rank}/{nhosts} "
             f"via {coord} ({time.perf_counter() - t0:.2f}s)")
    _health("init", coordinator=coord,
            init_s=round(time.perf_counter() - t0, 3))
    return True


def shutdown_owned() -> None:
    """Tear down the distributed client IF this module created it (an
    adopted external world is left alone), so a dispose()d process can
    re-init a fresh world under a new size."""
    if not _state.owned:
        return
    _state.owned = False
    _state.ag_gen = 0
    _state.bar_gen = 0
    _state.preempt_seen = False
    _state.local_notice = None
    try:
        import jax
        jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 — teardown is best-effort
        log_warning(f"jax.distributed.shutdown failed: {e}")


# ------------------------------------------------------------ KV collectives
def _remaining_ms(deadline: float) -> int:
    """Milliseconds left until ``deadline`` (perf_counter), floored at 1
    so the coordination service still raises DEADLINE promptly instead
    of an invalid-argument error."""
    return max(1, int((deadline - time.perf_counter()) * 1000))


def kv_allgather_bytes(blob: bytes, timeout_s: float,
                       label: str = "allgather") -> List[bytes]:
    """Allgather one byte payload per rank through the coordination
    service KV store; returns rank-ordered blobs (self included).

    Every rank must call this the same number of times in the same
    order — a per-process generation counter namespaces each call.
    Payloads are chunked at ``_KV_CHUNK`` to stay under the service's
    message cap.  A rank that never posts its payload surfaces as a
    ``LightGBMError`` naming that rank once the budget expires.  Own
    keys from generation g-2 are deleted on entry (provably no peer
    can still need them once this rank reaches g), so long runs do not
    grow coordinator memory."""
    c = client()
    if c is None or world() <= 1:
        return [blob]
    me, n = rank(), world()
    gen = _state.ag_gen
    _state.ag_gen += 1
    if gen >= 2:
        try:
            c.key_value_delete(f"{_AG_PREFIX}/{gen - 2}/{me}/")
        except Exception:  # noqa: BLE001 — GC is best-effort
            pass
    nchunks = max(1, (len(blob) + _KV_CHUNK - 1) // _KV_CHUNK)
    for i in range(nchunks):
        c.key_value_set_bytes(f"{_AG_PREFIX}/{gen}/{me}/{i}",
                              blob[i * _KV_CHUNK:(i + 1) * _KV_CHUNK])
    c.key_value_set(f"{_AG_PREFIX}/{gen}/{me}/n", str(nchunks))
    deadline = time.perf_counter() + max(0.001, timeout_s)
    out: List[bytes] = []
    for r in range(n):
        try:
            cnt = int(c.blocking_key_value_get(
                f"{_AG_PREFIX}/{gen}/{r}/n", _remaining_ms(deadline)))
            parts = [
                c.blocking_key_value_get_bytes(
                    f"{_AG_PREFIX}/{gen}/{r}/{i}", _remaining_ms(deadline))
                for i in range(cnt)]
        except Exception as e:  # noqa: BLE001 — deadline or service loss
            raise LightGBMError(
                f"{label}: rank {r} did not publish its payload within "
                f"{timeout_s:g}s (world {n}, generation {gen}) — host "
                f"{r} is dead or partitioned: {e}") from e
        out.append(b"".join(parts))
    return out


def barrier(name: str, timeout_s: Optional[float] = None) -> float:
    """Cross-host barrier with a deadline; returns the wait in seconds.

    No-op (0.0) on single-process runs.  Each rank announces itself
    under a per-call generation key and polls every other rank's
    announcement against the shared budget; on expiry the error names
    exactly the ranks that never arrived.  Probes the deterministic
    ``collective/barrier`` fault site per call, and records the wait in
    the per-collective counters plus a ``dist`` health record carrying
    this rank's monotonic enter/exit pair (the raw material for the
    fleet plane's skew-corrected straggler attribution)."""
    from ..utils.faults import FAULTS
    from . import network
    if not is_active():
        return 0.0
    FAULTS.maybe_raise("collective/barrier")
    probe_slow()
    if timeout_s is None:
        timeout_s = network.collective_policy()[1]
    c = client()
    me, n = rank(), world()
    gen = _state.bar_gen
    _state.bar_gen += 1
    prefix = f"{_BAR_PREFIX}/{name}/{gen}"
    enter_mono = time.monotonic()
    c.key_value_set(f"{prefix}/{me}", "1", allow_overwrite=True)
    t0 = time.perf_counter()
    deadline = t0 + max(0.001, timeout_s)
    missing: List[int] = []
    for r in range(n):
        if r == me:
            continue
        try:
            c.blocking_key_value_get(f"{prefix}/{r}",
                                     _remaining_ms(deadline))
        except Exception:  # noqa: BLE001 — deadline or service loss
            missing.append(r)
    wait = time.perf_counter() - t0
    if missing:
        arrived = sorted(set(range(n)) - set(missing) - {me})
        raise LightGBMError(
            f"barrier '{name}' timed out after {timeout_s:g}s: missing "
            f"rank(s) {missing} of world {n} (rank {me} waited, "
            f"rank(s) {arrived or '[]'} arrived) — a host died or is "
            "partitioned; restart the fleet with resume=true to "
            "continue from the elected snapshot")
    exit_mono = time.monotonic()
    network.record_collective("barrier", 0, wait, enter_mono=enter_mono)
    _health("barrier", name=name, wait_s=round(wait, 6),
            enter_mono=round(enter_mono, 6),
            exit_mono=round(exit_mono, 6))
    return wait


# ------------------------------------------------------- snapshot election
def local_snapshot_manifest(output_model: str) -> List[int]:
    """Sorted iterations of every RESUMABLE local snapshot (model file
    plus exact-state sidecar) for ``output_model``."""
    from ..utils.snapshots import _SNAP_RE, state_path
    d = os.path.dirname(os.path.abspath(output_model))
    base = os.path.basename(output_model)
    iters = []
    if not os.path.isdir(d):
        return iters
    for fname in os.listdir(d):
        if not fname.startswith(base + ".snapshot_iter_"):
            continue
        m = _SNAP_RE.search(fname)
        if m is None:
            continue
        path = os.path.join(d, fname)
        if os.path.exists(state_path(path)):
            iters.append(int(m.group(1)))
    return sorted(iters)


def elect_common_iteration(manifests: List[List[int]]) -> int:
    """The newest iteration present in EVERY manifest (0 when none):
    the only snapshot the whole fleet can roll to together."""
    if not manifests:
        return 0
    common = set(manifests[0])
    for m in manifests[1:]:
        common &= set(m)
    return max(common) if common else 0


def elect_snapshot(output_model: str) -> Tuple[Optional[str], int]:
    """Cross-host-consistent snapshot discovery: allgather every host's
    local manifest, elect the newest iteration ALL hosts possess, and
    return this host's ``(path, iteration)`` for it — ``(None, 0)``
    when no common snapshot exists.  Single-process runs fall through
    to plain local discovery."""
    from ..utils.snapshots import find_latest_snapshot
    if not is_active():
        return find_latest_snapshot(output_model)
    from . import network
    local = local_snapshot_manifest(output_model)
    manifests = network.allgather_obj({"rank": rank(), "iters": local})
    elected = elect_common_iteration(
        [m["iters"] for m in manifests])
    _health("elect", iteration=elected,
            local_newest=(local[-1] if local else 0),
            manifests={str(m["rank"]): len(m["iters"])
                       for m in manifests})
    if elected <= 0:
        if any(m["iters"] for m in manifests):
            log_warning(
                "no snapshot iteration is present on every host "
                f"(manifests: {[m['iters'] for m in manifests]}); "
                "starting from scratch on all hosts")
        return None, 0
    if local and local[-1] > elected:
        log_warning(
            f"local newest snapshot (iteration {local[-1]}) is ahead of "
            f"the fleet-wide elected iteration {elected}; rolling back "
            "to the common snapshot")
    log_info(f"elected snapshot iteration {elected} across "
             f"{world()} hosts")
    return f"{output_model}.snapshot_iter_{elected}", elected


# ----------------------------------------------------------- preemption flow
def note_local_preemption(reason: str) -> None:
    """Record that THIS host was asked to stop (SIGTERM handler or the
    ``dist/preempt`` fault site).  Consumed at the next iteration
    boundary by :func:`preempt_notice`."""
    if _state.local_notice is None:
        _state.local_notice = reason
        log_warning(f"preemption notice on rank {rank()}: {reason}")


def local_preemption() -> Optional[str]:
    return _state.local_notice


def publish_preempt(reason: str, iteration: int) -> None:
    """Post the fleet-wide preemption notice (idempotent; last writer
    wins, which is fine — any notice drains the whole fleet)."""
    c = client()
    if c is None:
        return
    notice = json.dumps({"rank": rank(), "reason": reason,
                         "iter": int(iteration)})
    try:
        c.key_value_set(_PREEMPT_KEY, notice, allow_overwrite=True)
    except Exception as e:  # noqa: BLE001
        log_warning(f"could not publish preemption notice: {e}")
    _health("preempt", reason=reason, iter=int(iteration))


def preempt_notice(poll: bool = True) -> Optional[Dict[str, Any]]:
    """The fleet-wide preemption notice, or ``None``.  A local notice
    (this host's SIGTERM / fault site) counts without any KV traffic;
    otherwise one cheap KV probe per call (``poll=False`` skips it for
    hot paths)."""
    if _state.local_notice is not None:
        return {"rank": rank(), "reason": _state.local_notice,
                "iter": -1}
    if not poll:
        return None
    c = client()
    if c is None or world() <= 1:
        return None
    try:
        pairs = c.key_value_dir_get(_PREEMPT_DIR)
    except Exception:  # noqa: BLE001 — absent key / service loss
        return None
    for key, val in pairs:
        if key.endswith("notice"):
            try:
                return json.loads(val)
            except ValueError:
                return {"rank": -1, "reason": val, "iter": -1}
    return None


def negotiate_preempt_target(done: int) -> int:
    """Agree on the iteration every host will snapshot at: the MAXIMUM
    of all hosts' completed iterations, so no host has to un-train.
    Hosts behind the target keep training up to it before the barrier."""
    from . import network
    if not is_active():
        return int(done)
    progress = network.allgather_obj({"rank": rank(), "done": int(done)})
    target = max(int(p["done"]) for p in progress)
    _health("preempt_target", target=target, done=int(done))
    return target
