"""Distributed "network" state: the TPU-native stand-in for the reference's
static Network class (include/LightGBM/network.h:102-297, src/network/).

The reference builds a TCP/MPI mesh from a machine list and hand-rolls
Bruck/recursive-halving/ring collectives (network.cpp:115-434).  On TPU the
runtime owns transport and algorithm selection: collectives are XLA ops over
a `jax.sharding.Mesh` spanning ICI (and DCN for multi-host).  This module
keeps the reference's API seam — init/rank/num_machines/dispose — and holds
the process-wide mesh used by the parallel tree learners.

Multi-host: run one process per host under `jax.distributed.initialize`;
`jax.devices()` then spans all hosts and the same mesh covers DCN, which is
the TPU equivalent of the reference's machine list + socket handshake
(linkers_socket.cpp:23-230).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import jax
import numpy as np

from ..utils.log import LightGBMError, log_info, log_warning

_mesh: Optional["jax.sharding.Mesh"] = None
_injected: Optional[dict] = None

MACHINES_AXIS = "machines"

# ---------------------------------------------------------------------------
# Per-collective counters: calls, payload bytes, wall seconds — the TPU
# equivalent of the reference Linkers byte/time counters (linkers.h:114-117).
# For XLA collectives launched from jitted growers the bytes are the static
# mesh-math estimate and the seconds are the HOST DISPATCH wall of the
# enclosing grow call (device execution is asynchronous); for host-side
# collectives (allgather_obj) both are measured for real.
_coll_lock = threading.Lock()
_collectives: Dict[str, Dict[str, float]] = {}
_coll_writer: Optional[int] = None
_coll_race_warned = False


def record_collective(kind: str, nbytes: float = 0,
                      seconds: float = 0.0, calls: int = 1) -> None:
    """Accumulate one collective's stats under ``kind``.  Thread-safe,
    with the reference Network's single-writer check relaxed to a
    warning (include/LightGBM/network.h keeps all collectives on one
    thread; here a second writer is flagged, not fatal)."""
    global _coll_writer, _coll_race_warned
    from ..utils.telemetry import TELEMETRY
    if TELEMETRY.level < 1:
        return
    with _coll_lock:
        ident = threading.get_ident()
        if _coll_writer is None:
            _coll_writer = ident
        elif _coll_writer != ident and not _coll_race_warned:
            _coll_race_warned = True
            log_warning("network collectives recorded from multiple "
                        "threads; the reference keeps Network "
                        "single-threaded — counters stay consistent but "
                        "per-kind attribution may interleave")
        st = _collectives.setdefault(
            kind, {"calls": 0, "bytes": 0, "seconds": 0.0})
        st["calls"] += int(calls)
        st["bytes"] += int(nbytes)
        st["seconds"] += float(seconds)


def collective_stats() -> Dict[str, Dict[str, float]]:
    """{kind: {calls, bytes, seconds}} copy (rounded for JSON)."""
    with _coll_lock:
        return {k: {"calls": int(v["calls"]), "bytes": int(v["bytes"]),
                    "seconds": round(v["seconds"], 6)}
                for k, v in _collectives.items()}


def collective_summary() -> str:
    """One-line rendering for the phase summary; empty when no
    collective ran."""
    stats = collective_stats()
    if not stats:
        return ""
    parts = [f"{k}={v['calls']}x/{v['bytes'] / 1e6:.1f}MB/"
             f"{v['seconds']:.3f}s" for k, v in sorted(stats.items())]
    return "net " + " ".join(parts)


def reset_collective_stats() -> None:
    global _coll_writer, _coll_race_warned
    with _coll_lock:
        _collectives.clear()
        _coll_writer = None
        _coll_race_warned = False


def init(num_machines: int = 0) -> "jax.sharding.Mesh":
    """Build (or rebuild) the 1-D device mesh over the `machines` axis."""
    global _mesh
    devices = jax.devices()
    if num_machines <= 0:
        num_machines = len(devices)
    if num_machines > len(devices):
        log_warning(f"num_machines={num_machines} > available devices "
                    f"({len(devices)}); clamping")
        num_machines = len(devices)
    _mesh = jax.sharding.Mesh(np.asarray(devices[:num_machines]),
                              (MACHINES_AXIS,))
    log_info(f"Initialized TPU collective mesh with {num_machines} devices")
    return _mesh


def init_from_machines(machines: str, num_machines: int = 1) -> None:
    """Reference-API shim: LGBM_NetworkInit(machines, port, ...) — the
    machine list is advisory on TPU (the runtime already knows the slice)."""
    init(num_machines)


def init_with_functions(reduce_scatter_fn: Callable, allgather_fn: Callable,
                        rank: int, num_machines: int) -> None:
    """External-collective injection seam (network.h:123,
    LGBM_NetworkInitWithFunctions c_api.cpp:1572) — used by tests to fake
    multi-machine runs in one process."""
    global _injected
    _injected = {"reduce_scatter": reduce_scatter_fn,
                 "allgather": allgather_fn,
                 "rank": rank, "num_machines": num_machines}


def injected() -> Optional[dict]:
    return _injected


def mesh() -> "jax.sharding.Mesh":
    global _mesh
    if _mesh is None:
        init()
    return _mesh


def num_machines() -> int:
    if _injected is not None:
        return _injected["num_machines"]
    return mesh().devices.size


def rank() -> int:
    if _injected is not None:
        return _injected["rank"]
    return jax.process_index()


def binning_world() -> tuple:
    """(world, rank) for host-level distributed bin finding
    (dataset_loader.cpp:933-1034).  Machine count here means PROCESSES
    (hosts) — a single process driving 8 local devices gains nothing from
    sharding host-side binning, so the mesh size is deliberately not used.

    jax.process_count() would INITIALIZE the backend; dataset loading is
    pure host work and must not block on a device runtime (a down TPU
    tunnel turns backend init into a retry loop), so multi-process is only
    consulted when jax.distributed was explicitly initialized."""
    if _injected is not None:
        return _injected["num_machines"], _injected["rank"]
    try:
        from jax._src import distributed
        client = distributed.global_state.client
    except (ImportError, AttributeError):
        # private-API drift: silently reporting world=1 on a real
        # multi-process run would desynchronize bin mappers across hosts,
        # so if any multi-process launch marker is in the environment this
        # is fatal, not a warning
        import os

        def _multi(var: str) -> bool:
            val = os.environ.get(var, "")
            if not val:
                return False
            if var in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
                try:
                    return int(val) > 1   # 1-node/1-rank runs are serial
                except ValueError:
                    return True
            if var == "TPU_WORKER_HOSTNAMES":
                return "," in val         # single-host pod slice is serial
            return True                    # coordinator address present

        markers = [v for v in (
            "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
            "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE",
        ) if _multi(v)]
        if markers:
            raise LightGBMError(
                "cannot determine the multi-process world for distributed "
                "bin finding (jax distributed-state API unavailable) but "
                f"multi-process launch markers are set ({markers}); "
                "refusing to fit bin mappers per-host — use "
                "network.init_with_functions to inject the topology")
        log_warning("could not inspect jax.distributed state; assuming a "
                    "single-process run for bin finding")
        return 1, 0
    if client is None:
        return 1, 0
    return jax.process_count(), jax.process_index()


def allgather_obj(obj):
    """Allgather a picklable object across binning ranks; returns the list
    of every rank's object (self included), rank-ordered.

    Uses the injected allgather when tests fake a multi-machine run
    (init_with_functions), else jax.experimental.multihost_utils over DCN
    for real multi-process meshes, else identity.

    One transient failure is retried (recorded as a ``collective_retry``
    fault event): host-level allgather runs over DCN during data loading,
    where a single hiccup should not kill a long job.  A second failure
    propagates — a dead link is not transient.  The retry path is
    exercised deterministically via the ``collective/allgather`` fault
    site."""
    try:
        return _allgather_obj_once(obj)
    except LightGBMError:
        raise                        # config/topology errors: not transient
    except Exception as e:
        from ..utils.telemetry import TELEMETRY
        log_warning(f"allgather_obj failed ({type(e).__name__}: {e}); "
                    "retrying once")
        TELEMETRY.fault_event("collective_retry",
                              site="collective/allgather", detail=str(e))
        return _allgather_obj_once(obj)


def _allgather_obj_once(obj):
    import pickle

    from ..utils.faults import FAULTS
    FAULTS.maybe_raise("collective/allgather")   # probed per attempt
    blob = pickle.dumps(obj)
    t0 = time.perf_counter()
    if _injected is not None:
        out = [pickle.loads(b) for b in _injected["allgather"](blob)]
        record_collective("allgather_obj", len(blob),
                          time.perf_counter() - t0)
        return out
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils
    arr = np.frombuffer(blob, np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([arr.size], np.int64))
    maxn = int(np.max(sizes))
    pad = np.zeros(maxn, np.uint8)
    pad[: arr.size] = arr
    gathered = multihost_utils.process_allgather(pad)
    out = [pickle.loads(gathered[i, : int(sizes[i])].tobytes())
           for i in range(gathered.shape[0])]
    record_collective("allgather_obj", maxn, time.perf_counter() - t0)
    return out


def dispose() -> None:
    """Tear down the mesh/injection AND the collective counters —
    back-to-back runs in one process (tests, notebooks) must not leak
    the previous run's call/byte totals into the next stats() blob."""
    global _mesh, _injected
    _mesh = None
    _injected = None
    reset_collective_stats()
