"""Distributed "network" state: the TPU-native stand-in for the reference's
static Network class (include/LightGBM/network.h:102-297, src/network/).

The reference builds a TCP/MPI mesh from a machine list and hand-rolls
Bruck/recursive-halving/ring collectives (network.cpp:115-434).  On TPU the
runtime owns transport and algorithm selection: collectives are XLA ops over
a `jax.sharding.Mesh` spanning ICI (and DCN for multi-host).  This module
keeps the reference's API seam — init/rank/num_machines/dispose — and holds
the process-wide mesh used by the parallel tree learners.

Multi-host: run one process per host under `jax.distributed.initialize`;
`jax.devices()` then spans all hosts and the same mesh covers DCN, which is
the TPU equivalent of the reference's machine list + socket handshake
(linkers_socket.cpp:23-230).  `parallel/distributed.py` owns the init
lifecycle, barriers, snapshot election and preemption flow; this module
owns the mesh, the per-collective counters, and the hardened host-level
collective seam (`allgather_obj` with configurable retries / backoff /
per-attempt timeout via `collective_retries=` / `collective_timeout_s=`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import jax
import numpy as np

from ..utils.log import LightGBMError, log_info, log_warning

_mesh: Optional["jax.sharding.Mesh"] = None
_mesh_fingerprint: Optional[tuple] = None
_injected: Optional[dict] = None

MACHINES_AXIS = "machines"

# Retry policy for host-level collectives (and the distributed-init
# handshake): total extra attempts, per-attempt wall budget, first
# backoff.  The defaults preserve the historical retry-once behavior;
# configure() rebinds them from `collective_retries=` /
# `collective_timeout_s=` at the same lifecycle point as
# FAULTS.configure.
_policy = {"retries": 1, "timeout_s": 120.0, "backoff_s": 0.05}


def configure(config) -> None:
    """Bind the collective retry policy from a Config (clamped sane)."""
    retries = int(getattr(config, "collective_retries", 1))
    timeout_s = float(getattr(config, "collective_timeout_s", 120.0))
    _policy["retries"] = max(0, retries)
    _policy["timeout_s"] = max(0.001, timeout_s)


def collective_policy() -> tuple:
    """(retries, timeout_s, backoff_s) currently in force."""
    return _policy["retries"], _policy["timeout_s"], _policy["backoff_s"]

# ---------------------------------------------------------------------------
# Per-collective counters: calls, payload bytes, wall seconds — the TPU
# equivalent of the reference Linkers byte/time counters (linkers.h:114-117).
# For XLA collectives launched from jitted growers the bytes are the static
# mesh-math estimate and the seconds are the HOST DISPATCH wall of the
# enclosing grow call (device execution is asynchronous); for host-side
# collectives (allgather_obj) both are measured for real.
_coll_lock = threading.Lock()
_collectives: Dict[str, Dict[str, float]] = {}
_coll_writer: Optional[int] = None
_coll_race_warned = False
# v6 fleet plane: per-kind window of (call_index, enter mono_ts,
# seconds) samples since the last take_collective_window().  Every rank
# issues collectives in the same order, so (kind, call_index) pairs the
# same logical collective across ranks after a kv-allgather — that pair
# is what obs/fleet.py splits into wait vs work seconds.  Bounded per
# kind; the call index keeps pairing correct even after drops.
_COLL_WINDOW_CAP = 4096
_coll_window: Dict[str, list] = {}


def record_collective(kind: str, nbytes: float = 0,
                      seconds: float = 0.0, calls: int = 1,
                      enter_mono: Optional[float] = None) -> None:
    """Accumulate one collective's stats under ``kind``.  Thread-safe,
    with the reference Network's single-writer check relaxed to a
    warning (include/LightGBM/network.h keeps all collectives on one
    thread; here a second writer is flagged, not fatal).

    ``enter_mono`` — the ``time.monotonic()`` instant this rank ENTERED
    the collective (before any peer wait) — additionally feeds the
    fleet-plane attribution window; callers that cannot observe entry
    (async device dispatch) omit it and stay out of the window."""
    global _coll_writer, _coll_race_warned
    from ..utils.telemetry import TELEMETRY
    if TELEMETRY.level < 1:
        return
    with _coll_lock:
        ident = threading.get_ident()
        if _coll_writer is None:
            _coll_writer = ident
        elif _coll_writer != ident and not _coll_race_warned:
            _coll_race_warned = True
            log_warning("network collectives recorded from multiple "
                        "threads; the reference keeps Network "
                        "single-threaded — counters stay consistent but "
                        "per-kind attribution may interleave")
        st = _collectives.setdefault(
            kind, {"calls": 0, "bytes": 0, "seconds": 0.0})
        idx = int(st["calls"])
        st["calls"] += int(calls)
        st["bytes"] += int(nbytes)
        st["seconds"] += float(seconds)
        if enter_mono is not None:
            win = _coll_window.setdefault(kind, [])
            win.append((idx, round(float(enter_mono), 6),
                        round(float(seconds), 6)))
            if len(win) > _COLL_WINDOW_CAP:
                del win[: len(win) - _COLL_WINDOW_CAP]
    if enter_mono is not None and TELEMETRY.level >= 2:
        # span for the fleet trace merge: flow arrows join the per-rank
        # net/<kind> spans of the same (kind, seq) across lanes
        now = time.perf_counter()
        TELEMETRY.record_span(f"net/{kind}", now - float(seconds),
                              float(seconds), tid="net",
                              args={"seq": idx, "bytes": int(nbytes)})


def take_collective_window() -> Dict[str, list]:
    """Drain and return this rank's attribution window:
    ``{kind: [(call_index, enter_mono, seconds), ...]}``.  Samples
    recorded after this call land in the next window, so synchronized
    callers (obs/fleet.py syncs at iteration barriers) see aligned
    windows on every rank."""
    with _coll_lock:
        out = {k: list(v) for k, v in _coll_window.items() if v}
        _coll_window.clear()
    return out


def collective_stats() -> Dict[str, Dict[str, float]]:
    """{kind: {calls, bytes, seconds}} copy (rounded for JSON)."""
    with _coll_lock:
        return {k: {"calls": int(v["calls"]), "bytes": int(v["bytes"]),
                    "seconds": round(v["seconds"], 6)}
                for k, v in _collectives.items()}


def collective_summary() -> str:
    """One-line rendering for the phase summary; empty when no
    collective ran."""
    stats = collective_stats()
    if not stats:
        return ""
    parts = [f"{k}={v['calls']}x/{v['bytes'] / 1e6:.1f}MB/"
             f"{v['seconds']:.3f}s" for k, v in sorted(stats.items())]
    return "net " + " ".join(parts)


def reset_collective_stats() -> None:
    global _coll_writer, _coll_race_warned
    with _coll_lock:
        _collectives.clear()
        _coll_window.clear()
        _coll_writer = None
        _coll_race_warned = False


def _device_fingerprint(devices) -> tuple:
    """Identity + order of a device list — what the mesh's collective
    layout assumptions are actually keyed on."""
    return tuple((getattr(d, "process_index", 0), getattr(d, "id", i))
                 for i, d in enumerate(devices))


def init(num_machines: int = 0) -> "jax.sharding.Mesh":
    """Build (or rebuild) the 1-D device mesh over the `machines` axis.

    Always re-queries ``jax.devices()`` so a second init after
    ``dispose()`` — possibly under a NEW ``jax.distributed`` world size —
    builds a fresh mesh instead of reusing stale device ordering."""
    global _mesh, _mesh_fingerprint
    devices = jax.devices()
    if num_machines <= 0:
        num_machines = len(devices)
    if num_machines > len(devices):
        log_warning(f"num_machines={num_machines} > available devices "
                    f"({len(devices)}); clamping")
        num_machines = len(devices)
    _mesh = jax.sharding.Mesh(np.asarray(devices[:num_machines]),
                              (MACHINES_AXIS,))
    _mesh_fingerprint = _device_fingerprint(devices)
    log_info(f"Initialized TPU collective mesh with {num_machines} devices")
    return _mesh


def init_from_machines(machines: str, num_machines: int = 1) -> None:
    """Reference-API shim: LGBM_NetworkInit(machines, port, ...) — the
    machine list is advisory on TPU (the runtime already knows the slice)."""
    init(num_machines)


def init_with_functions(reduce_scatter_fn: Callable, allgather_fn: Callable,
                        rank: int, num_machines: int) -> None:
    """External-collective injection seam (network.h:123,
    LGBM_NetworkInitWithFunctions c_api.cpp:1572) — used by tests to fake
    multi-machine runs in one process."""
    global _injected
    _injected = {"reduce_scatter": reduce_scatter_fn,
                 "allgather": allgather_fn,
                 "rank": rank, "num_machines": num_machines}


def injected() -> Optional[dict]:
    return _injected


def mesh() -> "jax.sharding.Mesh":
    """The process-wide mesh, rebuilt if the visible device set changed
    since it was created (e.g. a fresh ``jax.distributed`` world came up
    after ``dispose()``) — collectives over a mesh of dead/reordered
    devices would silently misroute."""
    global _mesh
    if _mesh is None:
        return init()
    if _device_fingerprint(jax.devices()) != _mesh_fingerprint:
        log_warning("visible device set changed since the mesh was "
                    "built; rebuilding the collective mesh")
        spanned_all = int(_mesh.devices.size) == len(_mesh_fingerprint)
        return init(0 if spanned_all else int(_mesh.devices.size))
    return _mesh


def num_machines() -> int:
    if _injected is not None:
        return _injected["num_machines"]
    return mesh().devices.size


def rank() -> int:
    if _injected is not None:
        return _injected["rank"]
    return jax.process_index()


def binning_world() -> tuple:
    """(world, rank) for host-level distributed bin finding
    (dataset_loader.cpp:933-1034).  Machine count here means PROCESSES
    (hosts) — a single process driving 8 local devices gains nothing from
    sharding host-side binning, so the mesh size is deliberately not used.

    jax.process_count() would INITIALIZE the backend; dataset loading is
    pure host work and must not block on a device runtime (a down TPU
    tunnel turns backend init into a retry loop), so multi-process is only
    consulted when jax.distributed was explicitly initialized."""
    if _injected is not None:
        return _injected["num_machines"], _injected["rank"]
    try:
        from jax._src import distributed
        client = distributed.global_state.client
    except (ImportError, AttributeError):
        # private-API drift: silently reporting world=1 on a real
        # multi-process run would desynchronize bin mappers across hosts,
        # so if any multi-process launch marker is in the environment this
        # is fatal, not a warning
        import os

        def _multi(var: str) -> bool:
            val = os.environ.get(var, "")
            if not val:
                return False
            if var in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
                try:
                    return int(val) > 1   # 1-node/1-rank runs are serial
                except ValueError:
                    return True
            if var == "TPU_WORKER_HOSTNAMES":
                return "," in val         # single-host pod slice is serial
            return True                    # coordinator address present

        markers = [v for v in (
            "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
            "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE",
        ) if _multi(v)]
        if markers:
            raise LightGBMError(
                "cannot determine the multi-process world for distributed "
                "bin finding (jax distributed-state API unavailable) but "
                f"multi-process launch markers are set ({markers}); "
                "refusing to fit bin mappers per-host — use "
                "network.init_with_functions to inject the topology")
        log_warning("could not inspect jax.distributed state; assuming a "
                    "single-process run for bin finding")
        return 1, 0
    if client is None:
        return 1, 0
    return jax.process_count(), jax.process_index()


def allgather_obj(obj):
    """Allgather a picklable object across binning ranks; returns the list
    of every rank's object (self included), rank-ordered.

    Uses the injected allgather when tests fake a multi-machine run
    (init_with_functions), else the coordination-service KV transport
    when a ``jax.distributed`` world is up (works on every backend and
    turns a dead peer into an error naming the missing rank — see
    ``distributed.kv_allgather_bytes``), else
    jax.experimental.multihost_utils over DCN, else identity.

    Transient failures are retried under the configured policy
    (``collective_retries=`` extra attempts, exponential backoff,
    per-attempt budget from ``collective_timeout_s=``; default retry
    once), each retry recorded as a ``collective_retry`` fault event:
    host-level allgather runs over DCN during data loading, where a
    single hiccup should not kill a long job.  Exhausting the attempts
    propagates — a dead link is not transient.  The retry path is
    exercised deterministically via the ``collective/allgather`` fault
    site, probed per attempt."""
    from ..utils.retry import retry_call
    from ..utils.telemetry import TELEMETRY
    retries, _timeout_s, backoff_s = collective_policy()

    def _on_retry(_k, e):
        TELEMETRY.fault_event("collective_retry",
                              site="collective/allgather", detail=str(e))

    return retry_call(lambda: _allgather_obj_once(obj),
                      attempts=1 + retries, backoff_s=backoff_s,
                      fatal=(LightGBMError,), on_retry=_on_retry,
                      label="allgather_obj")


def _allgather_obj_once(obj):
    import pickle

    from ..utils.faults import FAULTS
    from . import distributed
    FAULTS.maybe_raise("collective/allgather")   # probed per attempt
    distributed.probe_slow()                     # injected straggler delay
    blob = pickle.dumps(obj)
    t0 = time.perf_counter()
    enter = time.monotonic()
    if _injected is not None:
        out = [pickle.loads(b) for b in _injected["allgather"](blob)]
        record_collective("allgather_obj", len(blob),
                          time.perf_counter() - t0, enter_mono=enter)
        return out
    if distributed.is_active():
        # coordinator KV transport: backend-agnostic (XLA's CPU backend
        # has no cross-process computations) with real per-call
        # deadlines and missing-rank attribution
        blobs = distributed.kv_allgather_bytes(
            blob, timeout_s=collective_policy()[1], label="allgather_obj")
        out = [pickle.loads(b) for b in blobs]
        record_collective("allgather_obj",
                          sum(len(b) for b in blobs),
                          time.perf_counter() - t0, enter_mono=enter)
        return out
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils
    arr = np.frombuffer(blob, np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([arr.size], np.int64))
    maxn = int(np.max(sizes))
    pad = np.zeros(maxn, np.uint8)
    pad[: arr.size] = arr
    gathered = multihost_utils.process_allgather(pad)
    out = [pickle.loads(gathered[i, : int(sizes[i])].tobytes())
           for i in range(gathered.shape[0])]
    record_collective("allgather_obj", maxn, time.perf_counter() - t0,
                      enter_mono=enter)
    return out


def probe_dispatch_collective(kind: Optional[str]) -> None:
    """Deterministic fault probe at the eager dispatch seam of an
    in-jit device collective (the grower's reduce-scatter/allgather/psum
    runs INSIDE compiled programs where an injected Python exception
    cannot fire, and donated carries cannot be re-dispatched — so the
    fault site probes just before dispatch).  The site is named after
    the canonical data-parallel histogram reduce-scatter and fires for
    whichever grower collective is active.  Retried under the
    configured policy like any transient DCN hiccup; a spec that never
    heals (``x*``) exhausts the attempts and propagates."""
    site = "collective/reduce_scatter" if kind else None
    from ..utils.faults import FAULTS, KNOWN_SITES
    if site not in KNOWN_SITES or not FAULTS.enabled:
        return
    from ..utils.retry import retry_call
    from ..utils.telemetry import TELEMETRY
    retries, _timeout_s, backoff_s = collective_policy()

    def _on_retry(_k, e):
        TELEMETRY.fault_event("collective_retry", site=site,
                              detail=str(e))

    retry_call(lambda: FAULTS.maybe_raise(site),
               attempts=1 + retries, backoff_s=backoff_s,
               fatal=(LightGBMError,), on_retry=_on_retry, label=site)


def dispose() -> None:
    """Tear down the mesh/injection AND the collective counters —
    back-to-back runs in one process (tests, notebooks) must not leak
    the previous run's call/byte totals into the next stats() blob.
    Also shuts down a ``jax.distributed`` client that THIS process's
    lifecycle layer created, so a later ``init()`` can bring up a fresh
    world under a new size (an externally initialized world is left
    alone)."""
    global _mesh, _mesh_fingerprint, _injected
    _mesh = None
    _mesh_fingerprint = None
    _injected = None
    reset_collective_stats()
    from . import distributed
    distributed.shutdown_owned()
