from . import network

__all__ = ["network"]
