"""Synthetic multiclass.train/.test (label-first TSV, 5 classes)."""
import numpy as np

rng = np.random.RandomState(42)
centers = rng.normal(size=(5, 20)) * 2
for name, n in (("multiclass.train", 7000), ("multiclass.test", 500)):
    X = rng.normal(size=(n, 20))
    y = np.argmax(X @ centers.T + rng.normal(size=(n, 5)), axis=1)
    np.savetxt(name, np.column_stack([y, X]), fmt="%.6g", delimiter="\t")
print("wrote multiclass.train multiclass.test")
