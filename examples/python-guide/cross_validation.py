"""CV + sklearn wrapper walk (the reference python-guide's
sklearn_example.py + advanced bits, condensed)."""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.RandomState(7)
X = rng.normal(size=(4000, 8))
y = 2 * X[:, 0] - X[:, 1] ** 2 + 0.1 * rng.normal(size=4000)

print("5-fold CV...")
res = lgb.cv({"objective": "regression", "metric": "l2", "verbose": -1},
             lgb.Dataset(X, y), num_boost_round=30, nfold=5)
key = [k for k in res if k.endswith("-mean")][0]
print(f"CV {key}: {res[key][-1]:.5f}")

print("sklearn API...")
est = lgb.LGBMRegressor(n_estimators=30, num_leaves=31)
est.fit(X, y)
print("R^2-ish corr:",
      float(np.corrcoef(est.predict(X), y)[0, 1].round(4)))
