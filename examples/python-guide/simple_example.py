"""The reference's python-guide/simple_example.py, on lightgbm_tpu."""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.RandomState(42)
X = rng.normal(size=(5000, 10))
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
X_test, y_test = X[4000:], y[4000:]
lgb_train = lgb.Dataset(X[:4000], y[:4000])
lgb_eval = lgb_train.create_valid(X_test, y_test)

params = {"boosting_type": "gbdt", "objective": "binary",
          "metric": ["binary_logloss", "auc"], "num_leaves": 31,
          "learning_rate": 0.05, "verbose": 0}
print("Starting training...")
gbm = lgb.train(params, lgb_train, num_boost_round=20,
                valid_sets=[lgb_eval], early_stopping_rounds=5)
print("Saving model...")
gbm.save_model("model.txt")
print("Starting predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration)
acc = float(np.mean((y_pred > 0.5) == y_test))
print(f"Accuracy of prediction: {acc:.4f}")
