"""Synthetic regression.train/.test (7000/500 x 28, label first, TSV)."""
import numpy as np

rng = np.random.RandomState(42)
for name, n in (("regression.train", 7000), ("regression.test", 500)):
    X = rng.normal(size=(n, 28))
    y = 2 * X[:, 0] - X[:, 1] ** 2 + np.sin(3 * X[:, 2]) \
        + 0.2 * rng.normal(size=n)
    np.savetxt(name, np.column_stack([y, X]), fmt="%.6g", delimiter="\t")
print("wrote regression.train regression.test")
