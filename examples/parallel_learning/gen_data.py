"""Same data as binary_classification; the point here is the learner."""
import numpy as np

rng = np.random.RandomState(42)
for name, n in (("binary.train", 7000), ("binary.test", 500)):
    X = rng.normal(size=(n, 28))
    logit = 2 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
    y = (logit + rng.normal(size=n) > 0).astype(int)
    np.savetxt(name, np.column_stack([y, X]), fmt="%.6g", delimiter="\t")
print("wrote binary.train binary.test")
