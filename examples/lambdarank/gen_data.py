"""Synthetic rank.train/.test + .query files (the reference's lambdarank
example layout: LibSVM-ish label-first rows + one query-size per line)."""
import numpy as np

rng = np.random.RandomState(42)
for name, n_q in (("rank.train", 200), ("rank.test", 40)):
    rows = []
    sizes = []
    for _ in range(n_q):
        s = int(rng.randint(10, 30))
        sizes.append(s)
        X = rng.normal(size=(s, 30))
        score = X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=s) * 0.5
        order = np.argsort(np.argsort(score))
        y = np.minimum(4, (5 * order) // s)
        for i in range(s):
            feats = " ".join(f"{j + 1}:{X[i, j]:.5g}" for j in range(30))
            rows.append(f"{int(y[i])} {feats}")
    with open(name, "w") as fh:
        fh.write("\n".join(rows) + "\n")
    with open(name + ".query", "w") as fh:
        fh.write("\n".join(str(s) for s in sizes) + "\n")
print("wrote rank.train rank.test (+ .query)")
