"""Synthetic stand-in for the reference's binary.train/.test (7000/500
rows x 28 features, label first, TSV — the HIGGS-style file layout)."""
import numpy as np

rng = np.random.RandomState(42)
for name, n in (("binary.train", 7000), ("binary.test", 500)):
    X = rng.normal(size=(n, 28))
    logit = 2 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
    y = (logit + rng.normal(size=n) > 0).astype(int)
    np.savetxt(name, np.column_stack([y, X]), fmt="%.6g", delimiter="\t")
print("wrote binary.train binary.test")
