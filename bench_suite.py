"""Benchmark suite: BASELINE.json configs 2-5 (the headline binary
config stays in bench.py, whose single-JSON-line driver contract this
file must not disturb).

Each config runs in its own subprocess with a hard timeout (bench.py's
outage-robustness pattern), emits one QUALITY-GATED JSON line, and the
collection is written to BENCH_SUITE.json:

  * goss_regression       — L2 + boosting=goss (examples/regression;
                            no published reference number, gate = heldout
                            L2 halves the label variance)
  * multiclass_cat        — softmax + categorical features
                            (examples/multiclass_classification)
  * lambdarank_msltr      — MS LTR-shaped proxy (2.27M docs, 136 feats,
                            ~31k queries); reference: 215.320 s / 500
                            iters, NDCG@10 0.527371
                            (docs/Experiments.rst:101-146).  Labels are
                            synthetic (zero-egress box), so the quality
                            gate is a calibrated NDCG@10 floor on THIS
                            generator, not the published number; the
                            published time is still the vs_baseline
                            denominator.
  * feature_parallel      — tree_learner=feature on the 8-virtual-device
                            CPU mesh (the ICI path compiled and executed;
                            one real chip means no measured multi-chip
                            scaling claim) with a serial-parity gate.
  * spill_ab              — the same regression config trained twice:
                            data_in_hbm=resident vs forced host-spill
                            (out-of-core row-block streaming,
                            docs/ROBUSTNESS.md rung 4).  One record with
                            the spill wall as the gated value plus the
                            resident wall and peak-HBM deltas; quality_ok
                            additionally requires the two models to be
                            byte-identical (sha256 of model_to_string).

Usage:  python bench_suite.py [config ...]    (default: all four)
        python bench_suite.py --gate [config ...]
                              (also run tools/bench_gate.py over the
                              appended trajectory; exit 1 on wall/HBM/
                              quality regressions vs trailing history)
"""

import json
import os
import subprocess
import sys
import time

RESULT_TAG = "SUITE_RESULT_JSON:"
REPO = os.path.dirname(os.path.abspath(__file__))

# (config, platform, rows, warmup, measure, timeout_s); CPU fallback
# tiers run tiny and are stamped {"fallback": true} like bench.py's
TIERS = {
    "goss_regression": [("tpu", 2_000_000, 2, 4, 2400),
                        ("cpu", 10_000, 1, 2, 900)],
    "multiclass_cat": [("tpu", 1_000_000, 2, 4, 2400),
                       ("cpu", 10_000, 1, 2, 900)],
    # 4200s: the cold lambdarank compile at 2.27M rows blew the usual
    # 2700s budget (r5 on-chip log, 2026-08-01)
    "lambdarank_msltr": [("tpu", 2_270_000, 2, 4, 4200),
                         ("cpu", 20_000, 1, 2, 900)],
    # the mesh is 8 VIRTUAL CPU devices sharing one host core, so this
    # config is a correctness/liveness gate (serial parity), not a
    # timing claim — tiers stay tiny and the record says virtual_mesh
    "feature_parallel": [("cpu-mesh", 20_000, 1, 2, 1800),
                         ("cpu-mesh", 5_000, 1, 2, 900)],
    # two children per tier (resident + forced spill), so the per-child
    # timeout stays the usual single-run budget
    "spill_ab": [("tpu", 1_000_000, 2, 4, 2400),
                 ("cpu", 10_000, 1, 2, 900)],
}

# published reference wall-clocks for vs_baseline (500 iters, CPU,
# docs/Experiments.rst:101-116); None = no published number
REF_500_ITERS_S = {
    "goss_regression": None,
    "multiclass_cat": None,
    "lambdarank_msltr": 215.320,
    "feature_parallel": None,
    "spill_ab": None,
}
REF_ROWS = {"lambdarank_msltr": 2_270_296}
TOTAL_ITERS_REF = 500


def _gen_goss(rng, n):
    import numpy as np
    X = rng.normal(size=(n, 28)).astype(np.float32)
    y = (2.0 * X[:, 0] - X[:, 1] ** 2 + np.sin(3 * X[:, 2])
         + 0.3 * X[:, 3] * X[:, 4] + 0.2 * rng.normal(size=n))
    return X, y.astype(np.float64), {}


def _gen_multiclass(rng, n):
    import numpy as np
    X = rng.normal(size=(n, 28)).astype(np.float32)
    # 8 categorical columns, cardinality 16
    cats = rng.randint(0, 16, size=(n, 8))
    X[:, 20:28] = cats
    logits = np.stack([
        X[:, 0] + (cats[:, 0] % 5 == k) * 1.5
        + 0.5 * X[:, k % 4] * (1 if k % 2 else -1)
        for k in range(5)], axis=1)
    # 2x logit scale keeps Bayes error low enough that the 25-iteration
    # quality gate separates a working learner from a broken one
    # (calibrated: 0.78 at 25 iters vs ln(5)=1.609 untrained)
    y = np.argmax(2.0 * logits + rng.gumbel(size=(n, 5)), axis=1)
    return X, y.astype(np.float64), {
        "categorical_feature": list(range(20, 28)),
        "params": {"objective": "multiclass", "num_class": 5},
    }


def _gen_rank(rng, n):
    import numpy as np
    F = 136
    # MS LTR shape: ~72 docs/query
    sizes = []
    left = n
    while left > 0:
        s = min(int(rng.randint(40, 120)), left)
        sizes.append(s)
        left -= s
    group = np.asarray(sizes)
    X = rng.normal(size=(n, F)).astype(np.float32)
    score = (X[:, 0] + 0.7 * X[:, 1] - 0.5 * X[:, 2]
             + 0.3 * X[:, 3] * X[:, 4] + rng.normal(size=n) * 0.7)
    # per-query graded relevance 0-4 by score quintile
    y = np.zeros(n)
    pos = 0
    for s in sizes:
        sl = slice(pos, pos + s)
        order = np.argsort(np.argsort(score[sl]))
        y[sl] = np.minimum(4, (5 * order) // max(s, 1))
        pos += s
    return X, y, {"group": group,
                  "params": {"objective": "lambdarank",
                             "label_gain": ",".join(
                                 str((1 << i) - 1) for i in range(32))}}


def _ndcg_at_10(pred, y, group):
    import numpy as np
    pos, total, nq = 0, 0.0, 0
    disc = 1.0 / np.log2(np.arange(2, 13))
    for s in group:
        sl = slice(pos, pos + s)
        ys, ps = y[sl], pred[sl]
        k = min(10, s)
        top = np.argsort(-ps, kind="stable")[:k]
        dcg = float((((2.0 ** ys[top]) - 1) * disc[:k]).sum())
        ideal = np.sort(ys)[::-1][:k]
        idcg = float((((2.0 ** ideal) - 1) * disc[:k]).sum())
        if idcg > 0:
            total += dcg / idcg
            nq += 1
        pos += s
    return total / max(nq, 1)


def _impl_label(bst, requested: str) -> str:
    """bench.py:142-151's labeling contract: report the grower that
    ACTUALLY ran, and mark a pinned impl that fell back to fused so the
    scoreboard never attributes fused numbers to it."""
    req = str(requested).strip().lower()
    if getattr(bst.gbdt, "_use_segment", False):
        return "frontier" if req == "frontier" else "segment"
    label = "fused"
    if req not in ("auto", "fused"):
        label += f" (requested {req})"
    return label


def run_child(config: str, platform: str, n_rows: int, warmup: int,
              measure: int) -> None:
    import jax
    if platform.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache(REPO)
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    gen = {"goss_regression": _gen_goss, "multiclass_cat": _gen_multiclass,
           "lambdarank_msltr": _gen_rank,
           "feature_parallel": _gen_goss,
           "spill_ab": _gen_goss}[config]
    X, y, extra = gen(rng, n_rows)
    params = {"learning_rate": 0.1, "num_leaves": 255, "max_bin": 63,
              "min_sum_hessian_in_leaf": 100.0, "verbose": -1,
              "objective": "regression",
              # same A/B hooks as bench.py: LIGHTGBM_TPU_IMPL pins the
              # grower, LIGHTGBM_TPU_BOOST_CHUNK pins the chunk size
              # (0 = auto; GOSS/mesh configs self-clamp to 1)
              "tpu_tree_impl": os.environ.get("LIGHTGBM_TPU_IMPL",
                                              "auto"),
              "tpu_boost_chunk": int(os.environ.get(
                  "LIGHTGBM_TPU_BOOST_CHUNK", "0"))}
    params.update(extra.get("params", {}))
    # fused-K ladder hook (tools/onchip_r7.py): pins the frontier batch
    # width, same knob perf_probe.py exposes, so the K∈{4,8,16} A/B
    # cells measure the width they name
    fk = int(os.environ.get("LIGHTGBM_TPU_FRONTIER_K", "0") or 0)
    if fk > 0:
        params["tpu_frontier_width"] = fk
    # spill A/B hook: the parent pins the memory tier per child
    # (runtime-only knob — it never reaches the serialized model)
    dib = os.environ.get("SUITE_DATA_IN_HBM")
    if dib:
        params["data_in_hbm"] = dib
    if config == "goss_regression":
        params["boosting"] = "goss"
    if config == "multiclass_cat":
        params["num_leaves"] = 31
    if config == "feature_parallel":
        params.update({"tree_learner": "feature", "num_leaves": 63})

    ds = lgb.Dataset(X, y, group=extra.get("group"),
                     categorical_feature=extra.get("categorical_feature",
                                                   "auto"))
    t0 = time.time()
    bst = lgb.Booster(params, ds)
    t_setup = time.time() - t0
    chunk = bst.gbdt.boost_chunk_size()

    def run_iters(n: int) -> None:
        done = 0
        while done < n:
            step = min(chunk, n - done)
            if step > 1:
                bst.update_chunk(step)
            else:
                bst.update()
            done += step

    t0 = time.time()
    run_iters(warmup)
    jax.block_until_ready(bst.gbdt.train_score)
    t_warm = time.time() - t0
    from lightgbm_tpu.utils.phase import GLOBAL_TIMER
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    GLOBAL_TIMER.reset()
    TELEMETRY.reset()      # counters/timeline cover only the measured window
    t0 = time.time()
    # memory_session brackets the window with HBM gauge samples (no-op on
    # backends without memory_stats) and owns the optional sampler thread
    with TELEMETRY.memory_session():
        run_iters(measure)
        jax.block_until_ready(bst.gbdt.train_score)
    per_iter = (time.time() - t0) / measure
    # snapshot BEFORE the quality-gate extra iterations below so the
    # blob matches the timed window
    metrics_blob = TELEMETRY.metrics_blob()

    # quality gates are calibrated at a FIXED 25-iteration budget so the
    # same floor applies to every tier (timing above covers only the
    # measured window; a 2+4-iteration model is too early to gate on)
    run_iters(max(0, 25 - warmup - measure))
    pred = bst.predict(X[:200_000])
    quality: dict = {}
    ok = True
    if config in ("goss_regression", "feature_parallel", "spill_ab"):
        l2 = float(np.mean((pred - y[:len(pred)]) ** 2))
        quality["l2"] = round(l2, 5)
        ok = l2 < 0.5 * float(np.var(y))
        if config == "feature_parallel":
            # parity gate vs the serial learner at the same budget
            ps = dict(params)
            ps.pop("tree_learner")
            bs = lgb.Booster(ps, lgb.Dataset(X, y))
            for _ in range(max(25, warmup + measure)):
                bs.update()
            pred_s = bs.predict(X[:200_000])
            dev = float(np.abs(pred - pred_s).max())
            quality["max_dev_vs_serial"] = round(dev, 6)
            scale = float(np.abs(pred_s).max()) + 1e-9
            ok = ok and dev < 5e-3 * max(scale, 1.0)
    elif config == "multiclass_cat":

        p = np.asarray(pred).reshape(-1, 5)
        yy = y[:len(p)].astype(int)
        ll = float(-np.mean(np.log(np.clip(
            p[np.arange(len(p)), yy], 1e-15, 1.0))))
        quality["multi_logloss"] = round(ll, 5)
        ok = ll < 0.9  # untrained = ln(5) ~ 1.609; calibrated floor
    elif config == "lambdarank_msltr":
        g = extra["group"]
        m = 0
        take = 0
        while take < len(g) and m + g[take] <= len(pred):
            m += g[take]
            take += 1
        nd = _ndcg_at_10(np.asarray(pred[:m]), y[:m], g[:take])
        quality["ndcg@10"] = round(nd, 5)
        # calibrated floor for this generator (full separability is
        # impossible: relevance has injected noise; smoke run measured
        # 0.846 at a THIRD of the gate budget)
        ok = nd > 0.80
    backend = jax.default_backend()
    # cheap cross-process identity witness: the spill A/B parent compares
    # the resident and forced-spill children by this digest
    import hashlib
    model_sha = hashlib.sha256(
        bst.model_to_string().encode()).hexdigest()
    print(RESULT_TAG + json.dumps({
        "config": config, "rows": n_rows, "backend": backend,
        "per_iter": round(per_iter, 5), "setup_s": round(t_setup, 2),
        "warmup_s": round(t_warm, 2), "quality": quality,
        "quality_ok": bool(ok),
        "impl": _impl_label(bst, params["tpu_tree_impl"]),
        "chunk": chunk,
        "model_sha": model_sha,
        "metrics": metrics_blob,
    }))


def _cpu_env():
    sys.path.insert(0, REPO)
    from lightgbm_tpu.utils import cpu_subprocess_env
    env = cpu_subprocess_env()
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    return env


def _run_child_record(config: str, platform: str, rows: int, warmup: int,
                      measure: int, timeout_s: float,
                      env: dict) -> dict | None:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           config, platform, str(rows), str(warmup), str(measure)]
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                              capture_output=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"suite: {config}/{platform}/{rows} timed "
                         f"out ({timeout_s}s)\n")
        return None
    sys.stderr.write(proc.stderr.decode(errors="replace")[-2000:])
    if proc.returncode != 0:
        sys.stderr.write(
            f"suite: {config}/{platform}/{rows} rc={proc.returncode}\n")
        return None
    for line in proc.stdout.decode(errors="replace").splitlines():
        if line.startswith(RESULT_TAG):
            return json.loads(line[len(RESULT_TAG):])
    return None


def _peak_hbm(rec: dict) -> int | None:
    return ((rec.get("metrics") or {}).get("memory")
            or {}).get("peak_bytes_in_use")


def _run_spill_ab(probe_ok: bool) -> dict | None:
    """Resident-vs-forced-spill A/B on the same config and data: one
    trajectory record whose gated value is the SPILL wall (so an
    out-of-core streaming regression trips tools/bench_gate.py), with
    the resident wall and the peak-HBM delta riding along.  quality_ok
    also demands byte-identical models — the out-of-core tier's core
    contract."""
    config = "spill_ab"
    for platform, rows, warmup, measure, timeout_s in TIERS[config]:
        if platform == "tpu" and not probe_ok:
            continue
        env = (_cpu_env() if platform.startswith("cpu")
               else dict(os.environ))
        pair = {}
        for tier in ("resident", "spill"):
            e = dict(env)
            e["SUITE_DATA_IN_HBM"] = tier
            pair[tier] = _run_child_record(config, platform, rows,
                                           warmup, measure, timeout_s, e)
        res, spl = pair["resident"], pair["spill"]
        if res is None or spl is None:
            continue
        total_res = res["per_iter"] * TOTAL_ITERS_REF
        total_spl = spl["per_iter"] * TOTAL_ITERS_REF
        bit_identical = (res.get("model_sha") is not None
                         and res.get("model_sha") == spl.get("model_sha"))
        out = {
            "config": config,
            "metric": f"{config}_{spl['rows']}r_500iter_train_time_"
                      f"{spl['backend']}_spill",
            "value": round(total_spl, 2),
            "unit": "s",
            "impl": spl["impl"],
            "chunk": spl.get("chunk", 1),
            "quality": dict(
                spl["quality"],
                spill_wall_ratio=round(total_spl / max(total_res, 1e-9),
                                       3),
                bit_identical=bit_identical),
            "quality_ok": bool(spl["quality_ok"] and res["quality_ok"]
                               and bit_identical),
            "resident_value": round(total_res, 2),
            "metrics": spl.get("metrics"),
        }
        pr, ps = _peak_hbm(res), _peak_hbm(spl)
        if pr is not None and ps is not None:
            out["resident_peak_hbm_bytes"] = int(pr)
            out["spill_peak_hbm_bytes"] = int(ps)
            out["peak_hbm_delta_bytes"] = int(ps) - int(pr)
        if spl["backend"] == "cpu" and platform == "tpu":
            out["fallback"] = True
        if platform.startswith("cpu") and "tpu" in (
                t[0] for t in TIERS[config]):
            out["fallback"] = True
        return out
    return None


def run_config(config: str, probe_ok: bool) -> dict | None:
    if config == "spill_ab":
        return _run_spill_ab(probe_ok)
    for platform, rows, warmup, measure, timeout_s in TIERS[config]:
        if platform == "tpu" and not probe_ok:
            continue
        env = (_cpu_env() if platform.startswith("cpu")
               else dict(os.environ))
        r = _run_child_record(config, platform, rows, warmup, measure,
                              timeout_s, env)
        if r is None:
            continue
        # bench.py:216's promotion contract for the suite: a TPU tier
        # whose auto impl resolved to segment also measures the frontier
        # grower and keeps it when it is faster at held quality, so a
        # default (env-free) run reproduces the scoreboard numbers
        if (platform == "tpu" and r["backend"] == "tpu"
                and r["impl"] == "segment"
                and "LIGHTGBM_TPU_IMPL" not in os.environ):
            env2 = dict(env)
            env2["LIGHTGBM_TPU_IMPL"] = "frontier"
            r2 = _run_child_record(config, platform, rows, warmup,
                                   measure, timeout_s, env2)
            if (r2 is not None and r2["impl"] == "frontier"
                    and r2["quality_ok"]
                    and r2["per_iter"] < r["per_iter"]):
                sys.stderr.write(
                    f"suite A/B [{config}]: frontier "
                    f"{r2['per_iter']:.4f} beats segment "
                    f"{r['per_iter']:.4f} s/iter at held quality\n")
                r = r2
        total = r["per_iter"] * TOTAL_ITERS_REF
        ref = REF_500_ITERS_S.get(config)
        out = {
            "config": config,
            "metric": f"{config}_{r['rows']}r_500iter_train_time_"
                      f"{r['backend']}",
            "value": round(total, 2),
            "unit": "s",
            "impl": r["impl"],
            "chunk": r.get("chunk", 1),
            "quality": r["quality"],
            "quality_ok": r["quality_ok"],
            # the measured window's v2 telemetry blob (phases, transfer
            # bytes, memory/cost envelope) rides along with every record
            "metrics": r.get("metrics"),
        }
        if ref is not None:
            scaled = ref * r["rows"] / REF_ROWS.get(config, r["rows"])
            out["vs_baseline"] = round(total / scaled, 3)
        if r["backend"] == "cpu" and platform == "tpu":
            out["fallback"] = True
        if platform == "cpu-mesh":
            out["virtual_mesh"] = True
        if platform.startswith("cpu") and "tpu" in (
                t[0] for t in TIERS[config]):
            out["fallback"] = True
        return out
    return None


def _append_trajectory(results: list) -> None:
    """One digest line per run appended to BENCH_TRAJECTORY.jsonl — the
    machine-readable perf trajectory across PRs (wall, peak HBM, est.
    FLOPs, and — on device_timing runs — the measured dispatch digest
    of the heaviest seam, which tools/bench_gate.py latency-gates).
    Null-tolerant: v1 blobs / CPU backends / timing-off runs leave the
    memory, cost and timing fields as null rather than breaking the
    append."""
    path = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
    with open(path, "a") as fh:
        for r in results:
            m = r.get("metrics") or {}
            mem = m.get("memory") or {}
            cost = m.get("cost") or {}
            timing = m.get("timing") or {}
            # the heaviest measured seam (by synced wall) is the one a
            # latency regression would show up in first
            tlabels = timing.get("labels") or {}
            tname = max(tlabels, key=lambda k: tlabels[k].get(
                "total_s", 0.0)) if tlabels else None
            tentry = tlabels.get(tname) or {}
            # full per-label digest (count/mean/p99 per jit seam) so a
            # regression in a NON-heaviest seam is still visible in the
            # trajectory, plus the histogram-pass rollup bench_gate.py
            # latency-gates (heaviest label naming the hist kernels)
            dlabels = {k: {"count": v.get("count"),
                           "mean_s": v.get("mean_s"),
                           "p99_s": v.get("p99_s")}
                       for k, v in sorted(tlabels.items())} or None
            hname = max((k for k in tlabels if "hist" in k),
                        key=lambda k: tlabels[k].get("total_s", 0.0),
                        default=None)
            hentry = tlabels.get(hname) or {}
            # spill A/B records carry their resident-vs-spill deltas into
            # the trajectory; absent on every other config
            extra = {k: r[k] for k in ("resident_value",
                                       "resident_peak_hbm_bytes",
                                       "spill_peak_hbm_bytes",
                                       "peak_hbm_delta_bytes") if k in r}
            fh.write(json.dumps({
                "schema": "lightgbm_tpu.trajectory/v1",
                "ts": round(time.time(), 3),
                "config": r.get("config"),
                "metric": r.get("metric"),
                "value": r.get("value"),
                "unit": r.get("unit"),
                "impl": r.get("impl"),
                "chunk": r.get("chunk"),
                "quality_ok": r.get("quality_ok"),
                "peak_hbm_bytes": mem.get("peak_bytes_in_use"),
                "hbm_limit_bytes": mem.get("bytes_limit"),
                "est_flops": cost.get("flops_total"),
                "est_flops_per_s": cost.get("est_flops_per_s"),
                "dispatch_label": tname,
                "dispatch_mean_s": tentry.get("mean_s"),
                "dispatch_p99_s": tentry.get("p99_s"),
                "dispatch_labels": dlabels,
                "hist_pass_label": hname,
                "hist_pass_mean_s": hentry.get("mean_s"),
                "hist_pass_p99_s": hentry.get("p99_s"),
                "measured_flops_per_s": timing.get(
                    "measured_flops_per_s"),
                **extra,
            }) + "\n")


def main():
    configs = [a for a in sys.argv[1:] if not a.startswith("-")] \
        or list(TIERS)
    sys.path.insert(0, REPO)
    import bench
    probe_ok = (not os.environ.get("BENCH_SKIP_TPU")) and bench.probe_tpu()
    results = []
    # A/B ladder runs (tools/onchip_r7.py) suffix their records so each
    # env cell forms its OWN config series in the trajectory —
    # bench_gate's per-config latency baselines never mix a forced
    # variant with the defaults
    tag = os.environ.get("SUITE_CONFIG_TAG", "")
    for config in configs:
        r = run_config(config, probe_ok)
        if r is None:
            r = {"config": config, "metric": f"{config}_failed",
                 "value": -1.0, "unit": "s", "quality_ok": False}
        if tag:
            r["config"] = f"{r['config']}+{tag}"
            r["metric"] = f"{r.get('metric', config)}+{tag}"
        results.append(r)
        print(json.dumps(r), flush=True)
    _append_trajectory(results)
    # subset runs merge into the existing artifact instead of clobbering
    # the other configs' records
    path = os.path.join(REPO, "BENCH_SUITE.json")
    if set(configs) != set(TIERS):
        def config_of(rec):
            if "config" in rec:
                return rec["config"]
            # pre-"config"-field artifacts: longest-prefix fallback
            names = [n for n in TIERS
                     if rec.get("metric", "").startswith(n)]
            return max(names, key=len) if names else rec.get("metric", "")

        try:
            with open(path) as fh:
                old = {config_of(r): r for r in json.load(fh)}
        except (OSError, ValueError):
            old = {}
        for r in results:
            old[config_of(r)] = r
        results = list(old.values())
    with open(path, "w") as fh:
        json.dump(results, fh, indent=1)
    if "--gate" in sys.argv[1:]:
        # perf-regression sentinel: judge the lines just appended
        # against the trailing trajectory (tools/bench_gate.py) after
        # the artifacts are safely on disk
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import bench_gate
        sys.exit(bench_gate.gate(
            os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        run_child(sys.argv[2], sys.argv[3], int(sys.argv[4]),
                  int(sys.argv[5]), int(sys.argv[6]))
    else:
        main()
