"""Round-5 phase B: post-fix on-chip measurements.

Runs after tools/onchip_r5.py finishes.  The r5 plan's bench + defaults
probes all measured with the fused-route gate auto-disabled (the kernels
failed Mosaic compile on an i8->i1 trunci until commit 49a9b23); this
phase re-measures with the i32-mask kernels:

  1. self-checks (expect fused_route True now; logs the failing leg if
     not)
  2. strict + frontier defaults probes — clean A/B against the plan's
     FUSED_ROUTE=0 rows (same code state otherwise)
  3. bench.py re-run: the scoreboard with fused route + warm cache
  4. a profiler trace of the frontier grower for the next attribution
     round (what's left above the ~0.35 s/iter kernel floor)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from onchip import PY, REPO, chip_up, log, run_step, wait_for_chip  # noqa: E402


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip(max_wait_s=10 * 3600):
            log("r5b probe: backend never came up; giving up")
            sys.exit(3)
    elif not chip_up():
        log("r5b probe: backend DOWN; proceeding anyway")

    probe = os.path.join(REPO, "tools", "perf_probe.py")
    bench = os.path.join(REPO, "bench.py")

    run_step("r5b self-checks", [PY, "-c", (
        "from lightgbm_tpu.ops.pallas_histogram import "
        "fused_route_available;"
        "from lightgbm_tpu.ops.pallas_score import scorer_available;"
        "print('fused_route', fused_route_available());"
        "print('scorer', scorer_available())")], 1200)

    run_step("r5b strict fused 10.5M", [PY, probe, "10500000,255,1,3"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1"})
    # frontier auto keeps the unfused pair (fused_route_policy: the
    # K=16 fusion measured slower) — force it so this stays a real A/B
    run_step("r5b frontier fused 10.5M", [PY, probe, "10500000,255,1,3"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_FUSED_ROUTE": "1"})

    run_step("r5b bench rerun", [PY, bench], 9000)

    trace_dir = os.path.join(REPO, ".traces_r5b")
    run_step("r5b frontier trace", [PY, probe, "10500000,255,1,2"], 2400,
             {"LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_PROFILE_DIR": trace_dir})

    log("plan r5b complete")


if __name__ == "__main__":
    main()
