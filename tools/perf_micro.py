"""Microbench of the segment grower's N-scaled primitives at HIGGS size.

Times (a) one histogram_segment kernel over a full-N interval, (b) one
epoch-compaction sort, (c) one routing pass — the three per-row costs that
dominate per_iter at 10.5M rows (tools/perf_probe.py showed the N-term is
~97% of iteration time there).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
F = 28
B = 64


def timeit(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from lightgbm_tpu.ops.pallas_histogram import (
        histogram_segment, pack_channels, pick_block_rows)
    from lightgbm_tpu.models.grower_seg import (_pack_bins_words,
                                                _pack_w8_words)

    rb = pick_block_rows(F, B, N)
    npad = -(-N // rb) * rb
    print(f"N={N} npad={npad} rb={rb} blocks={npad//rb} backend={jax.default_backend()}", flush=True)
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, B, size=(F + (-F) % 4, npad),
                                    dtype=np.int64).astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=npad).astype(np.float32))
    hess = jnp.ones(npad, jnp.float32)
    member = jnp.ones(npad, jnp.float32)
    w8 = pack_channels(grad, hess, member)
    leaf_id = jnp.zeros(npad, jnp.int32)

    # (a) full-N segment histogram
    f = jax.jit(lambda b, w, l: histogram_segment(
        b, w, l, jnp.int32(0), jnp.int32(npad // rb), jnp.int32(0), B, rb))
    t = timeit(f, binsT, w8, leaf_id)
    print(f"hist_full_N: {t*1e3:.1f} ms  ({t/N*1e9:.2f} ns/row)", flush=True)

    # (a2) quarter-interval histogram (typical epoch confinement)
    f4 = jax.jit(lambda b, w, l: histogram_segment(
        b, w, l, jnp.int32(0), jnp.int32(npad // rb // 4), jnp.int32(0), B,
        rb))
    t = timeit(f4, binsT, w8, leaf_id)
    print(f"hist_quarter: {t*1e3:.1f} ms", flush=True)

    # (b) compaction sort (same payload as grower_seg.compact)
    def compact(lid, bT, w):
        ops = ((lid,) + tuple(_pack_bins_words(bT))
               + tuple(_pack_w8_words(w)) + (jnp.arange(npad, dtype=jnp.int32),))
        return lax.sort(ops, num_keys=1, is_stable=True)[0]
    cj = jax.jit(compact)
    t = timeit(cj, leaf_id, binsT, w8, reps=3)
    print(f"compact_sort: {t*1e3:.1f} ms", flush=True)

    # (c) one routing pass (fcol slice + threshold + leaf_id where)
    def route(bT, lid):
        fcol = lax.dynamic_slice_in_dim(bT, 3, 1, axis=0)[0, :]
        go_left = fcol.astype(jnp.int32) <= 31
        in_leaf = lid == 0
        return jnp.where(in_leaf & ~go_left, 7, lid)
    rj = jax.jit(route)
    t = timeit(rj, binsT, leaf_id)
    print(f"route_pass: {t*1e3:.2f} ms  (x254/tree = {t*254*1e3:.0f} ms)",
          flush=True)

    # (d) per-split scan cost proxy: [F, B, 3] best-split pair
    from lightgbm_tpu.ops.split import (FeatureMeta, SplitParams, best_split)
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    hist = jnp.asarray(rng.normal(size=(F, B, 3)).astype(np.float32))
    sp = SplitParams(has_cat=False)
    sj = jax.jit(lambda h: best_split(h, 1.0, float(N), float(N), fmeta, sp,
                                      jnp.ones(F, jnp.float32)))
    t = timeit(sj, hist, reps=20)
    print(f"scan_one: {t*1e3:.2f} ms  (x508/tree = {t*508*1e3:.0f} ms)",
          flush=True)


main()
