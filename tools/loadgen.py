"""Open-loop Poisson load generator for the serve stack.

The closed-loop client in bench_serve.py waits for each reply before
sending the next request, so the micro-batching coalescing window never
sees concurrent traffic (ROADMAP item 1).  This tool drives a
ServeSession the way real traffic does: arrivals are a Poisson process
at a target rate, submitted through the futures API WITHOUT waiting for
replies — the arrival clock never stalls on a slow dispatch, so queue
growth and coalescing behave as they would behind a real frontend.

Each grid cell (arrival rate x serve_max_delay_ms) runs a fixed
duration, records end-to-end latency per completed request via future
callbacks, and emits one record with achieved QPS, p50/p99, the mean
rows-per-batch the coalescing window actually built, and the serve
health stream's view of the same window.  Results merge into
BENCH_SERVE.json next to the closed-loop grid (config names
``loadgen-<size>-r<rate>-d<delay>``) and append trajectory digests that
tools/bench_gate.py gates on p99 like any other serve record.

``--shift`` exercises the drift plane instead of the queue: one session
with ``drift_detect`` armed replays a fixed sweep of training rows
untouched, then replays the same rows with one numerical column
displaced — a population shift the plane must flag (and a control sweep
with no displacement it must NOT flag).  Replies stay bit-checked
against Booster.predict throughout: the drift tap must never perturb
the scores it observes.  ``--smoke`` runs both and asserts the shifted
sweep's ``serve_drift`` record names the shifted column first.

``--swap`` drives open-loop traffic while a background thread refits
and hot-swaps the SAME model N times mid-flight: zero replies may
fail, every reply must be bit-identical to a generation that was live,
and the measured flip pauses (``swap_pause_p99_s``) land in the record
for tools/bench_gate.py to gate alongside ``shed_rate``.

Usage:
  python tools/loadgen.py                 # full sweep -> BENCH_SERVE.json
  python tools/loadgen.py --smoke         # ~2s burst, assertions, no artifacts
  python tools/loadgen.py --rate 200 --delay-ms 5 --duration 3
  python tools/loadgen.py --shift         # drift cells -> trajectory
  python tools/loadgen.py --swap          # hot-swap-under-load cell
"""

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# full-sweep grid: arrival rates (req/s) x coalescing windows (ms).
# Single-row requests: the realistic serving shape the closed-loop
# bench never exercises, and the one where coalescing matters most.
RATES = [50.0, 300.0]
DELAYS_MS = [0.0, 5.0]
DURATION_S = 2.5
# small model: the sweep measures the queue, not the tree walk
MODEL = ("small", dict(rows=5_000, feats=12, iters=30, leaves=31))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _train(np, lgb, spec):
    rng = np.random.RandomState(7)
    X = rng.normal(size=(spec["rows"], spec["feats"])).astype(np.float32)
    X[:, -1] = rng.randint(0, 8, size=spec["rows"])
    X[rng.rand(spec["rows"]) < 0.05, 0] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + X[:, 1]) > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, y, categorical_feature=[spec["feats"] - 1])
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": spec["leaves"]}, ds,
                    num_boost_round=spec["iters"])
    return bst, X


def drive_open_loop(sess, model_id, reqs, rate, duration_s, seed=0,
                    drain_timeout_s=15.0, expected=None):
    """Submit Poisson arrivals at ``rate`` req/s for ``duration_s``
    seconds, never blocking on replies.  Returns (sent, latencies,
    errors, mismatches, wall_s): per-completed-request end-to-end
    seconds measured submit -> future callback.  When ``expected`` is
    given (Booster.predict references aligned with ``reqs``), every
    reply is bit-checked against it — parity under REAL coalescing,
    where the queue slices replies out of concatenated dispatches."""
    import numpy as np

    lat, errors, mismatches = [], [0], [0]
    lock = threading.Lock()
    pending = []

    def _done(fut, t_submit, idx):
        try:
            res = fut.result()
        except Exception:
            with lock:
                errors[0] += 1
            return
        dt = time.perf_counter() - t_submit
        bad = (expected is not None
               and not np.array_equal(res, expected[idx]))
        with lock:
            lat.append(dt)
            if bad:
                mismatches[0] += 1

    rng = random.Random(seed)
    t_start = time.perf_counter()
    t_end = t_start + duration_s
    next_t = t_start
    sent = 0
    while True:
        now = time.perf_counter()
        if now >= t_end:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.002))
            continue
        idx = sent % len(reqs)
        t_submit = time.perf_counter()
        fut = sess.submit(model_id, reqs[idx])
        fut.add_done_callback(
            lambda f, t=t_submit, i=idx: _done(f, t, i))
        pending.append(fut)
        sent += 1
        next_t += rng.expovariate(rate)
    wall = time.perf_counter() - t_start
    deadline = time.monotonic() + drain_timeout_s
    for fut in pending:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            fut.result(timeout=remaining)
        except Exception:
            pass                  # already counted by the callback
    with lock:
        return sent, sorted(lat), errors[0], mismatches[0], wall


def run_cell(bst, X, size, rate, delay_ms, duration_s, max_batch=64,
             health_path="", window_s=1.0, seed=0):
    """One (rate, delay) cell on a fresh session; returns the result
    record (and leaves the health stream, when requested, on disk)."""
    import jax
    import numpy as np

    from lightgbm_tpu.serve import ServeSession
    from lightgbm_tpu.utils.telemetry import TELEMETRY

    reqs = [np.ascontiguousarray(X[i % X.shape[0]].reshape(1, -1))
            for i in range(64)]
    refs = [bst.predict(r) for r in reqs]
    TELEMETRY.reset()
    with ServeSession(max_batch=max_batch, max_delay_ms=delay_ms,
                      health_out=health_path,
                      health_window_s=window_s) as sess:
        mid = sess.load(bst, model_id=size)
        # pre-compile every pow2 bucket a coalesced drain can produce,
        # so the measured window sees steady-state dispatch costs;
        # direct dispatches bypass the queue, so they never contaminate
        # the health stream's request accounting
        b = 1
        while b <= max_batch:
            sess.predict_direct(mid, np.concatenate(
                [reqs[0]] * b) if b > 1 else reqs[0])
            b <<= 1
        # warmup dispatches out of the coalescing/counter measurement
        TELEMETRY.reset()
        TELEMETRY.gauge_set("serve/max_batch", max_batch)
        sent, lat, errors, mismatches, wall = drive_open_loop(
            sess, mid, reqs, rate, duration_s, seed=seed, expected=refs)
        stats = TELEMETRY.stats()
    counters = stats.get("counters", {})
    batches = counters.get("serve/batches", 0)
    rows = counters.get("serve/rows", 0)
    rec = {
        "config": f"loadgen-{size}-r{rate:g}-d{delay_ms:g}",
        "mode": "open-loop",
        "model": size, "backend": jax.default_backend(),
        "rate_target": rate, "delay_ms": delay_ms,
        "max_batch": max_batch,
        "duration_s": round(wall, 3),
        "requests": sent, "completed": len(lat), "errors": errors,
        "qps": round(len(lat) / max(wall, 1e-9), 2),
        "rows_per_batch": round(rows / batches, 3) if batches else None,
        "p50_s": (round(_percentile(lat, 0.50), 6) if lat else None),
        "p99_s": (round(_percentile(lat, 0.99), 6) if lat else None),
        "quality_ok": mismatches == 0,
    }
    serve_win = stats.get("serve")
    if serve_win:
        rec["window"] = serve_win
    return rec


def run_swap_cell(bst, X, name, n_swaps=3, rate=250.0, delay_ms=2.0,
                  duration_s=2.0, max_batch=64, health_path="", seed=0):
    """One hot-swap-under-load cell: open-loop Poisson traffic against
    model ``name`` while a background thread refits the booster and
    pushes ``n_swaps`` atomic hot swaps through the live session.

    Contracts asserted downstream (``--smoke``): zero failed replies
    across every flip, every reply bit-identical to a generation that
    was live during the run, and a bounded flip pause
    (``swap_pause_p99_s``, read from ``registry.swap_pauses``)."""
    import jax
    import numpy as np

    from lightgbm_tpu.serve import ServeSession
    from lightgbm_tpu.utils.telemetry import TELEMETRY

    reqs = [np.ascontiguousarray(X[i % X.shape[0]].reshape(1, -1))
            for i in range(64)]
    allreq = np.concatenate(reqs)
    rng = np.random.RandomState(seed)
    # generation 0's per-request references; the swapper appends each
    # new generation's BEFORE flipping it live, so the membership check
    # below never races the flip
    gens = [bst.predict(allreq)]
    gens_lock = threading.Lock()
    replies = []
    errors = [0]
    rep_lock = threading.Lock()
    TELEMETRY.reset()
    with ServeSession(max_batch=max_batch, max_delay_ms=delay_ms,
                      health_out=health_path,
                      health_window_s=0.5) as sess:
        mid = sess.load(bst, model_id=name)
        sess.predict_direct(mid, allreq[:1])         # compile
        # warm the flip path too (first .at[row].set compiles); an
        # identity swap, so generation-0 references stay valid
        sess.swap(mid, bst, gated=False)
        warm_pauses = len(sess.registry.swap_pauses)
        swaps_done = [0]
        stop = threading.Event()

        def swapper():
            # pace swaps across the traffic window but always complete
            # all n_swaps — the tail ones land during the drain, still
            # under load.  stop's only job is the pacing wait.
            gap = duration_s / (n_swaps + 1)
            for _ in range(n_swaps):
                stop.wait(gap)
                Xr = X[rng.choice(X.shape[0], 400, replace=False)]
                yr = ((np.nan_to_num(Xr[:, 0]) + Xr[:, 1]) > 0.5
                      ).astype(np.float64)
                bst.refit(Xr, yr, decay_rate=0.4)
                with gens_lock:
                    gens.append(bst.predict(allreq))
                sess.swap(mid, bst, gated=False)
                swaps_done[0] += 1

        def _done(fut, t_submit, idx):
            try:
                res = fut.result()
            except Exception:
                with rep_lock:
                    errors[0] += 1
                return
            dt = time.perf_counter() - t_submit
            with rep_lock:
                replies.append((idx, np.asarray(res).ravel(), dt))

        sw = threading.Thread(target=swapper, name="loadgen-swapper")
        sw.start()
        arr = random.Random(seed)
        t_start = time.perf_counter()
        t_end = t_start + duration_s
        next_t, sent, pending = t_start, 0, []
        try:
            while True:
                now = time.perf_counter()
                if now >= t_end:
                    break
                if now < next_t:
                    time.sleep(min(next_t - now, 0.002))
                    continue
                idx = sent % len(reqs)
                t_submit = time.perf_counter()
                fut = sess.submit(mid, reqs[idx])
                fut.add_done_callback(
                    lambda f, t=t_submit, i=idx: _done(f, t, i))
                pending.append(fut)
                sent += 1
                next_t += arr.expovariate(rate)
        finally:
            stop.set()
            sw.join(timeout=30)
        wall = time.perf_counter() - t_start
        deadline = time.monotonic() + 15.0
        for fut in pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                fut.result(timeout=remaining)
            except Exception:
                pass              # already counted by the callback
        pauses = sorted(sess.registry.swap_pauses[warm_pauses:])
    counters = TELEMETRY.stats().get("counters", {})
    mismatches = 0
    with rep_lock, gens_lock:
        lat = sorted(dt for _, _, dt in replies)
        for idx, res, _ in replies:
            if not any(np.array_equal(res, g[idx:idx + 1])
                       for g in gens):
                mismatches += 1
    shed = counters.get("serve/shed_requests", 0)
    return {
        "config": f"loadgen-swap-{name}",
        "mode": "hot-swap", "backend": jax.default_backend(),
        "rate_target": rate, "delay_ms": delay_ms,
        "duration_s": round(wall, 3),
        "requests": sent, "completed": len(lat), "errors": errors[0],
        "qps": round(len(lat) / max(wall, 1e-9), 2),
        "swaps": swaps_done[0],
        "swap_pause_p99_s": (round(_percentile(pauses, 0.99), 6)
                             if pauses else None),
        "swap_pause_max_s": (round(pauses[-1], 6) if pauses else None),
        "shed_rate": round(shed / max(sent, 1), 6),
        "p50_s": (round(_percentile(lat, 0.50), 6) if lat else None),
        "p99_s": (round(_percentile(lat, 0.99), 6) if lat else None),
        "quality_ok": mismatches == 0,
    }


SHIFT_COL = 2          # numerical column displaced by the shift sweep
SHIFT_OFFSET = 6.0     # far outside the N(0,1) training range


def run_shift_cell(bst, X, name, shift_col=SHIFT_COL, offset=SHIFT_OFFSET,
                   health_path="", threshold=0.2, n_rows=256, seed=0):
    """One drift cell: a fixed sweep of distinct training rows through
    the real queue path with ``drift_detect`` armed, replayed untouched
    and then with ``shift_col`` displaced by ``offset`` (``offset=0``
    is the control: same traffic, no shift, no drift expected).  Every
    reply is bit-checked against Booster.predict — the drift tap rides
    the serve path but must never perturb it.  Returns the result
    record; the DriftGate verdict is read live before close, and the
    health stream (when requested) carries the ``serve_drift``
    records."""
    import jax
    import numpy as np

    from lightgbm_tpu.serve import ServeSession
    from lightgbm_tpu.utils.telemetry import TELEMETRY

    rng = np.random.RandomState(seed)
    idx = rng.choice(X.shape[0], size=min(n_rows, X.shape[0]),
                     replace=False)
    base = np.ascontiguousarray(X[idx])
    shifted = base.copy()
    shifted[:, shift_col] = np.nan_to_num(
        shifted[:, shift_col]) + offset
    reqs = [np.ascontiguousarray(r.reshape(1, -1))
            for phase in (base, shifted) for r in phase]
    allref = bst.predict(np.concatenate(reqs))
    errors = mismatches = completed = 0
    TELEMETRY.reset()
    with ServeSession(max_batch=32, max_delay_ms=2.0,
                      health_out=health_path, health_window_s=0.5,
                      drift_detect=True,
                      drift_psi_threshold=threshold) as sess:
        mid = sess.load(bst, model_id=name)
        futs = [sess.submit(mid, r) for r in reqs]
        for i, fut in enumerate(futs):
            try:
                res = fut.result(timeout=60)
            except Exception:
                errors += 1
                continue
            completed += 1
            if not np.array_equal(np.asarray(res).ravel(),
                                  allref[i:i + 1]):
                mismatches += 1
        live = sess.drift_gate.stats(mid) or {}
        drifted = sess.drift_gate.drifted(mid)
    top = (live.get("top") or [{}])[0]
    return {
        "config": f"loadgen-shift-{name}",
        "mode": "drift-shift", "backend": jax.default_backend(),
        "shift_col": shift_col, "offset": offset,
        "threshold": threshold,
        "requests": len(reqs), "completed": completed,
        "errors": errors,
        "quality_ok": mismatches == 0,
        "psi_max": live.get("psi_max"),
        "score_js": live.get("score_js"),
        "drift_rows": live.get("rows"),
        "drifted": drifted,
        "top_feature": top.get("feature"),
    }


def merge_bench_serve(records, path=None):
    """Fold new cells into BENCH_SERVE.json next to the closed-loop
    grid: same-config records are replaced, everything else kept."""
    path = path or os.path.join(REPO, "BENCH_SERVE.json")
    existing = []
    if os.path.exists(path):
        try:
            with open(path) as fh:
                existing = json.load(fh)
        except ValueError:
            existing = []
    new_names = {r["config"] for r in records}
    merged = [r for r in existing
              if r.get("config") not in new_names] + records
    with open(path, "w") as fh:
        json.dump(merged, fh, indent=1)
    return path


def append_trajectory(records, path=None):
    path = path or os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
    with open(path, "a") as fh:
        for r in records:
            fh.write(json.dumps({
                "schema": "lightgbm_tpu.trajectory/v1",
                "ts": round(time.time(), 3),
                "config": r["config"],
                "backend": r.get("backend"),
                "qps": r.get("qps"),
                "rows_per_batch": r.get("rows_per_batch"),
                "p50_s": r.get("p50_s"),
                "p99_s": r.get("p99_s"),
                "quality_ok": r.get("quality_ok"),
                # drift/swap cells only; absent keys keep older gate
                # versions and mixed trajectories shape-stable
                **{k: r[k] for k in ("psi_max", "drift_ok",
                                     "swap_pause_p99_s", "shed_rate")
                   if r.get(k) is not None},
            }) + "\n")


def _check_health_stream(path, completed):
    """The smoke's health-stream contract: every line parses (the
    O_APPEND writer never tears), the lifecycle kinds are present, the
    windows account for every completed request, and every latency
    quantile pair is finite and ordered."""
    problems = []
    recs = []
    with open(path, "rb") as fh:
        for ln, raw in enumerate(fh.read().split(b"\n")):
            if not raw.strip():
                continue
            try:
                recs.append(json.loads(raw))
            except ValueError:
                problems.append(f"torn/unparseable line {ln + 1}")
    kinds = [r.get("kind") for r in recs]
    for want in ("serve_start", "serve_window", "serve_summary"):
        if want not in kinds:
            problems.append(f"missing {want} record")
    wins = [r for r in recs if r.get("kind") == "serve_window"]
    win_requests = sum(r.get("requests", 0) for r in wins)
    if win_requests != completed:
        problems.append(f"windows account for {win_requests} requests, "
                        f"{completed} completed")
    summaries = [r for r in recs if r.get("kind") == "serve_summary"]
    if summaries and summaries[-1].get("requests") != completed:
        problems.append(
            f"summary says {summaries[-1].get('requests')} requests, "
            f"{completed} completed")
    import math

    def ordered(d):
        p50, p99 = d.get("p50_s"), d.get("p99_s")
        return (isinstance(p50, (int, float)) and math.isfinite(p50)
                and isinstance(p99, (int, float)) and math.isfinite(p99)
                and p50 <= p99)

    saw_stages = set()
    for w in wins:
        if w.get("requests") and not ordered(w):
            problems.append(f"window e2e quantiles not finite/ordered: "
                            f"{w.get('p50_s')} vs {w.get('p99_s')}")
        for name, d in (w.get("stages") or {}).items():
            saw_stages.add(name)
            if not ordered(d):
                problems.append(f"stage {name} quantiles not "
                                f"finite/ordered in a window")
    missing = {"t_queue", "t_coalesce", "t_dispatch",
               "t_reply"} - saw_stages
    if missing:
        problems.append(f"stage distributions never observed: "
                        f"{sorted(missing)}")
    return problems


def _stream_drift_records(path):
    """serve_drift records from a health stream, oldest first."""
    out = []
    with open(path, "rb") as fh:
        for raw in fh.read().split(b"\n"):
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("kind") == "serve_drift":
                out.append(rec)
    return out


def shift_sweep(bst, X, tmpdir=None, threshold=0.2):
    """Shifted + control drift cells.  Judges each cell's verdict via
    the HEALTH STREAM (the interface monitors and the refit loop
    consume), sets ``drift_ok`` on the records, and returns
    (records, problems)."""
    tmp = tmpdir or tempfile.mkdtemp(prefix="loadgen_shift_")
    feat = bst.feature_name()[SHIFT_COL]
    shift = run_shift_cell(
        bst, X, "shift", threshold=threshold, seed=11,
        health_path=os.path.join(tmp, "shift.serve.health.jsonl"))
    control = run_shift_cell(
        bst, X, "control", offset=0.0, threshold=threshold, seed=12,
        health_path=os.path.join(tmp, "control.serve.health.jsonl"))
    problems = []
    for rec in (shift, control):
        if rec["errors"] or rec["completed"] != rec["requests"]:
            problems.append(f"{rec['config']}: {rec['errors']} errors, "
                            f"{rec['completed']}/{rec['requests']} done")
        if not rec["quality_ok"]:
            problems.append(f"{rec['config']}: replies diverged from "
                            f"Booster.predict with the drift tap on")
    sdrift = _stream_drift_records(
        os.path.join(tmp, "shift.serve.health.jsonl"))
    shift_ok = True
    if not sdrift:
        shift_ok = False
        problems.append("shift stream: no serve_drift record emitted")
    else:
        last = sdrift[-1]
        if not last.get("drifted"):
            shift_ok = False
            problems.append(
                f"shift stream: shifted sweep not flagged "
                f"(psi_max={last.get('psi_max')} < {threshold})")
        top = (last.get("top") or [{}])[0].get("feature")
        if top != feat:
            shift_ok = False
            problems.append(f"shift stream: top drifting feature "
                            f"{top!r}, expected {feat!r}")
    cdrift = _stream_drift_records(
        os.path.join(tmp, "control.serve.health.jsonl"))
    control_ok = True
    if any(r.get("drifted") for r in cdrift):
        control_ok = False
        problems.append("control stream: unshifted sweep flagged as "
                        "drifted (false positive)")
    if cdrift and not all(
            isinstance(r.get("psi_max"), (int, float))
            and r["psi_max"] < threshold for r in cdrift):
        control_ok = False
        problems.append(
            f"control stream: psi_max "
            f"{[r.get('psi_max') for r in cdrift]} not under "
            f"threshold {threshold}")
    shift["drift_ok"] = shift_ok and shift["quality_ok"]
    control["drift_ok"] = control_ok and control["quality_ok"]
    return [shift, control], problems


def smoke():
    """~2s burst with assertions; exit 1 on any violated contract.
    The CI leg behind tools/verify_t1.sh --serve-smoke."""
    import numpy as np

    import lightgbm_tpu as lgb

    bst, X = _train(np, lgb, dict(rows=1_500, feats=8, iters=8,
                                  leaves=15))
    tmp = tempfile.mkdtemp(prefix="loadgen_smoke_")
    problems = []
    # cell 1: fast arrivals into an open coalescing window MUST batch
    hot = run_cell(bst, X, "smoke", rate=300.0, delay_ms=25.0,
                   duration_s=1.4, max_batch=64,
                   health_path=os.path.join(tmp, "hot.serve.health.jsonl"),
                   window_s=0.4)
    # cell 2: a trickle with no window degenerates to ~1 row/batch
    trickle = run_cell(bst, X, "smoke", rate=15.0, delay_ms=0.0,
                       duration_s=1.0, max_batch=64,
                       health_path=os.path.join(
                           tmp, "trickle.serve.health.jsonl"),
                       window_s=0.4)
    for rec in (hot, trickle):
        print("LOADGEN_RESULT_JSON:" + json.dumps(rec), flush=True)
        if rec["errors"] or rec["completed"] != rec["requests"]:
            problems.append(f"{rec['config']}: {rec['errors']} errors, "
                            f"{rec['completed']}/{rec['requests']} done")
        if not rec["quality_ok"]:
            problems.append(f"{rec['config']}: serve output diverged "
                            f"from Booster.predict")
    if not (hot["rows_per_batch"] and hot["rows_per_batch"] > 1.5):
        problems.append(f"coalescing never engaged at 300 req/s: "
                        f"rows_per_batch={hot['rows_per_batch']}")
    if not (trickle["rows_per_batch"]
            and trickle["rows_per_batch"] < 1.5):
        problems.append(f"trickle traffic unexpectedly batched: "
                        f"rows_per_batch={trickle['rows_per_batch']}")
    problems += [f"hot stream: {p}" for p in _check_health_stream(
        os.path.join(tmp, "hot.serve.health.jsonl"), hot["completed"])]
    problems += [f"trickle stream: {p}" for p in _check_health_stream(
        os.path.join(tmp, "trickle.serve.health.jsonl"),
        trickle["completed"])]
    # drift cells: the shifted sweep must be flagged with the shifted
    # column named first, the control sweep must stay quiet, and
    # replies stay bit-identical with the drift tap armed
    drift_recs, drift_problems = shift_sweep(bst, X, tmpdir=tmp)
    for rec in drift_recs:
        print("LOADGEN_RESULT_JSON:" + json.dumps(rec), flush=True)
    problems += drift_problems
    # hot-swap cell: traffic + 3 background swaps, zero failed replies,
    # every reply bit-identical to a live generation, flip pause bounded
    swap_rec = run_swap_cell(
        bst, X, "smoke", n_swaps=3, rate=200.0, duration_s=1.6,
        health_path=os.path.join(tmp, "swap.serve.health.jsonl"))
    print("LOADGEN_RESULT_JSON:" + json.dumps(swap_rec), flush=True)
    problems += swap_problems(swap_rec, n_swaps=3)
    for p in problems:
        sys.stderr.write(f"loadgen smoke: FAIL {p}\n")
    print(f"loadgen smoke: {'FAIL' if problems else 'ok'} "
          f"(hot {hot['rows_per_batch']} rows/batch at "
          f"{hot['qps']} qps, trickle {trickle['rows_per_batch']}, "
          f"shift psi_max {drift_recs[0]['psi_max']} vs control "
          f"{drift_recs[1]['psi_max']}, {swap_rec['swaps']} swaps with "
          f"pause p99 {swap_rec['swap_pause_p99_s']}s)")
    return 1 if problems else 0


def swap_problems(rec, n_swaps, pause_bound_s=1.0):
    """The hot-swap cell's contracts, as gate-able problem strings."""
    problems = []
    if rec["errors"] or rec["completed"] != rec["requests"]:
        problems.append(f"{rec['config']}: {rec['errors']} failed "
                        f"replies, {rec['completed']}/{rec['requests']} "
                        f"done (hot swap must be zero-downtime)")
    if not rec["quality_ok"]:
        problems.append(f"{rec['config']}: a reply matched NO live "
                        f"generation (snapshot pinning broke)")
    if rec["swaps"] != n_swaps:
        problems.append(f"{rec['config']}: {rec['swaps']}/{n_swaps} "
                        f"swaps completed")
    if rec["swap_pause_p99_s"] is None \
            or rec["swap_pause_p99_s"] > pause_bound_s:
        problems.append(f"{rec['config']}: flip pause p99 "
                        f"{rec['swap_pause_p99_s']}s exceeds "
                        f"{pause_bound_s}s")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="open-loop Poisson serve load sweep "
                    "-> BENCH_SERVE.json")
    ap.add_argument("--smoke", action="store_true",
                    help="~2s burst with coalescing + health-stream + "
                         "drift assertions, no artifacts")
    ap.add_argument("--shift", action="store_true",
                    help="drift cells only: shifted + control sweeps "
                         "with drift_detect armed -> trajectory")
    ap.add_argument("--swap", action="store_true",
                    help="hot-swap cell: open-loop traffic while the "
                         "model is refitted and swapped mid-flight")
    ap.add_argument("--swaps", type=int, default=3,
                    help="--swap mode: background hot swaps per cell")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="single-cell mode: arrival rate req/s")
    ap.add_argument("--delay-ms", type=float, default=0.0,
                    help="single-cell mode: serve_max_delay_ms")
    ap.add_argument("--duration", type=float, default=DURATION_S,
                    help=f"seconds per cell (default {DURATION_S})")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--no-artifacts", action="store_true",
                    help="print records only; do not touch "
                         "BENCH_SERVE.json / the trajectory")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache(REPO)
    if args.smoke:
        return smoke()

    import numpy as np

    import lightgbm_tpu as lgb

    if args.swap:
        bst, X = _train(np, lgb, dict(rows=1_500, feats=8, iters=8,
                                      leaves=15))
        rec = run_swap_cell(bst, X, "small", n_swaps=args.swaps,
                            duration_s=max(args.duration, 1.5))
        print(json.dumps(rec), flush=True)
        problems = swap_problems(rec, n_swaps=args.swaps)
        for p in problems:
            sys.stderr.write(f"loadgen swap: FAIL {p}\n")
        if not args.no_artifacts:
            merge_bench_serve([rec])
            append_trajectory([rec])
            print("loadgen: merged 1 swap cell into BENCH_SERVE.json")
        return 1 if problems else 0

    if args.shift:
        bst, X = _train(np, lgb, dict(rows=1_500, feats=8, iters=8,
                                      leaves=15))
        records, problems = shift_sweep(bst, X)
        for rec in records:
            print(json.dumps(rec), flush=True)
        for p in problems:
            sys.stderr.write(f"loadgen shift: FAIL {p}\n")
        if not args.no_artifacts:
            merge_bench_serve(records)
            append_trajectory(records)
            print(f"loadgen: merged {len(records)} drift cell(s) into "
                  f"BENCH_SERVE.json")
        return 1 if problems else 0

    size, spec = MODEL
    bst, X = _train(np, lgb, spec)
    cells = ([(args.rate, args.delay_ms)] if args.rate > 0
             else [(r, d) for r in RATES for d in DELAYS_MS])
    records = []
    for i, (rate, delay) in enumerate(cells):
        rec = run_cell(bst, X, size, rate, delay, args.duration,
                       max_batch=args.max_batch, seed=i)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if not records:
        return 1
    coalesced = [r for r in records
                 if r.get("rows_per_batch") and r["rows_per_batch"] > 1.0]
    if not coalesced:
        sys.stderr.write("loadgen: WARNING no cell engaged the "
                         "coalescing window (rows_per_batch <= 1 "
                         "everywhere)\n")
    if not args.no_artifacts:
        merge_bench_serve(records)
        append_trajectory(records)
        print(f"loadgen: merged {len(records)} cell(s) into "
              f"BENCH_SERVE.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
