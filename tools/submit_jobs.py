"""Drive the multi-tenant training scheduler from a job-spec file.

The spec grammar is documented in lightgbm_tpu/sched/spec.py and
docs/SCHEDULING.md: top-level ``key = value`` lines set scheduler
knobs (``sched_policy=``, ``sched_quantum_chunks=``,
``sched_health_out=``, ``compile_cache=``, ...) and per-job defaults;
each ``job = NAME`` section overrides them for one tenant.  This tool
parses the spec, submits every job, runs the scheduler to completion
and prints the ``sched_summary``; exit 1 when any job failed or was
rejected by admission control, 0 otherwise.

``--smoke`` ignores the spec argument and runs a self-contained
3-tenant workload (binary + multiclass + lambdarank) in a temp
directory with a health stream, then asserts the stream is
well-formed: exactly one ``sched_start`` and one ``sched_summary``,
every record JSON with a ``kind``, one ``job_done`` per tenant, and
``sched_slice`` iteration counts consistent with each job's terminal
record.  This is the ``verify_t1.sh --sched-smoke`` leg.

Usage:
  python tools/submit_jobs.py jobs.spec
  python tools/submit_jobs.py jobs.spec --policy fair --quantum 2
  python tools/submit_jobs.py --smoke
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SMOKE_KINDS = ("sched_start", "sched_admit", "sched_slice",
               "sched_preempt_job", "job_done", "sched_summary")


def run_spec(path, overrides):
    from lightgbm_tpu.sched import run_spec_file
    out = run_spec_file(path, overrides=overrides)
    print(json.dumps(out, indent=2, sort_keys=True))
    bad = out.get("failed", 0) or out.get("rejected")
    return 1 if bad else 0


def _write_smoke_data(d):
    """Three small datasets: binary, 3-class, and a 2-group ranking
    set with a query file — one per tenant of the smoke workload."""
    import numpy as np
    r = np.random.RandomState(7)

    def feats(n):
        return r.rand(n, 5)

    xb = feats(240)
    yb = (xb[:, 0] + 0.25 * r.rand(240) > 0.55).astype(int)
    np.savetxt(os.path.join(d, "binary.csv"),
               np.column_stack([yb, xb]), delimiter=",", fmt="%.6f")
    xm = feats(240)
    ym = (np.digitize(xm[:, 1], [0.33, 0.66])).astype(int)
    np.savetxt(os.path.join(d, "multiclass.csv"),
               np.column_stack([ym, xm]), delimiter=",", fmt="%.6f")
    xr = feats(200)
    yr = (np.digitize(xr[:, 2] + 0.1 * r.rand(200),
                      [0.4, 0.7])).astype(int)
    np.savetxt(os.path.join(d, "rank.csv"),
               np.column_stack([yr, xr]), delimiter=",", fmt="%.6f")
    with open(os.path.join(d, "rank.csv.query"), "w") as fh:
        fh.write("100\n100\n")


def _smoke_spec(d):
    spec = os.path.join(d, "jobs.spec")
    stream = os.path.join(d, "sched.health.jsonl")
    with open(spec, "w") as fh:
        fh.write(f"""\
sched_policy = fair
sched_quantum_chunks = 2
sched_health_out = {stream}
num_iterations = 8
num_leaves = 7
min_data_in_leaf = 5
verbosity = -1

job = churn
data = binary.csv
objective = binary
output_model = churn.txt
weight = 2

job = intent
data = multiclass.csv
objective = multiclass
num_class = 3
output_model = intent.txt

job = ranker
data = rank.csv
objective = lambdarank
output_model = ranker.txt
""")
    return spec, stream


def _check_stream(stream, expect_jobs):
    """Well-formedness assertions over the smoke health stream."""
    assert os.path.exists(stream), f"no health stream at {stream}"
    records = []
    with open(stream) as fh:
        for ln, raw in enumerate(fh, 1):
            raw = raw.strip()
            if not raw:
                continue
            rec = json.loads(raw)      # every line must parse
            assert "kind" in rec, f"line {ln}: record without kind"
            assert rec["kind"] in SMOKE_KINDS, \
                f"line {ln}: unknown kind {rec['kind']!r}"
            records.append(rec)
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "sched_start", "stream must open with sched_start"
    assert kinds[-1] == "sched_summary", \
        "stream must close with sched_summary"
    assert kinds.count("sched_start") == 1
    assert kinds.count("sched_summary") == 1
    admits = [r for r in records if r["kind"] == "sched_admit"]
    assert {a["job"] for a in admits} == set(expect_jobs), \
        f"admission records missing a job: {admits}"
    dones = {r["job"]: r for r in records if r["kind"] == "job_done"}
    assert set(dones) == set(expect_jobs), \
        f"job_done missing for {set(expect_jobs) - set(dones)}"
    for name, rec in dones.items():
        assert not rec.get("failed"), f"{name} failed: {rec}"
    slices = [r for r in records if r["kind"] == "sched_slice"]
    assert len(slices) >= len(expect_jobs), "too few slice records"
    last_iter = {}
    for r in slices:
        # per-job iteration counters must be monotone across slices
        prev = last_iter.get(r["job"], 0)
        assert r["iter"] >= prev, \
            f"{r['job']}: iter went backwards {prev} -> {r['iter']}"
        last_iter[r["job"]] = r["iter"]
    for name, rec in dones.items():
        assert last_iter.get(name) == rec["iter"], \
            f"{name}: slice iter {last_iter.get(name)} != " \
            f"job_done iter {rec['iter']}"
    summary = records[-1]
    assert summary.get("done") == len(expect_jobs)
    assert summary.get("failed", 0) == 0
    assert summary.get("fairness_index") is not None
    return len(records)


def run_smoke():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from lightgbm_tpu.sched import run_spec_file
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    TELEMETRY.reset()
    with tempfile.TemporaryDirectory(prefix="sched_smoke_") as d:
        _write_smoke_data(d)
        spec, stream = _smoke_spec(d)
        out = run_spec_file(spec)
        names = ("churn", "intent", "ranker")
        assert out.get("done") == 3, f"expected 3 done jobs: {out}"
        assert out.get("failed", 0) == 0, f"smoke job failed: {out}"
        assert not out.get("rejected"), f"smoke job rejected: {out}"
        for name in names:
            job = out["jobs"][name]
            assert job["state"] == "done", (name, job)
            assert job["iterations"] == 8, (name, job)
        for model in ("churn.txt", "intent.txt", "ranker.txt"):
            assert os.path.exists(os.path.join(d, model)), \
                f"missing model {model}"
        n = _check_stream(stream, names)
        print(f"sched smoke OK: 3 jobs done over {out['slices']} "
              f"slices, fairness {out['fairness_index']}, "
              f"{n} well-formed stream records")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="submit a spec file of training jobs to the "
                    "multi-tenant scheduler")
    ap.add_argument("spec", nargs="?",
                    help="job spec file (see docs/SCHEDULING.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-contained 3-tenant smoke "
                         "workload and assert stream well-formedness")
    ap.add_argument("--policy", default="",
                    help="override sched_policy= from the spec")
    ap.add_argument("--quantum", type=int, default=0,
                    help="override sched_quantum_chunks= from the spec")
    ap.add_argument("--health-out", default="",
                    help="override sched_health_out= from the spec")
    args = ap.parse_args(argv)
    if args.smoke:
        return run_smoke()
    if not args.spec:
        ap.error("a spec file is required unless --smoke")
    overrides = {}
    if args.policy:
        overrides["sched_policy"] = args.policy
    if args.quantum > 0:
        overrides["sched_quantum_chunks"] = args.quantum
    if args.health_out:
        overrides["sched_health_out"] = args.health_out
    return run_spec(args.spec, overrides)


if __name__ == "__main__":
    sys.exit(main())
