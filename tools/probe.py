"""Unified perf-probe CLI for the live backend (round-3 verdict: one
probe tool instead of nine scratch scripts).  Subcommands:

    python tools/probe.py train "rows,leaves,warmup,measure" ...
        End-to-end per-iteration time (same as tools/perf_probe.py;
        LIGHTGBM_TPU_SEG_STATS=1 adds scan/compaction counters).
    python tools/probe.py micro [N]
        Device-time microbench of the segment grower's N-scaled
        primitives (histogram / compaction sort / routing / scan) using
        in-jit repetition — (t(K)-t(1))/(K-1) is pure device compute,
        immune to the tunneled backend's dispatch/RPC overhead.
    python tools/probe.py sort [N]
        Compaction-strategy comparison: 13-operand lax.sort vs
        sort-(key,index)+gather, plus each part alone.
    python tools/probe.py compile [variant ...]
        AOT trace/compile-stage timing (variants: seg seg_nocompact
        fused kernel scan).
    python tools/probe.py trace [rows] [leaves]
        Capture a jax-profiler trace of 2 iterations and print the
        per-op device-time table from the xplane protobuf.
    python tools/probe.py parse-profile <logdir>
        Summarize an existing xplane dump.

Measurement rules learned the hard way on the tunneled TPU (rounds 2-3):
large fetches run ~15 MB/s so reduce outputs to scalars before fetching;
block_until_ready alone under-syncs; identical chained dispatches can be
deduped, so every repetition must consume the previous output.
"""

import glob
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

F_HIGGS = 28
B_HIGGS = 64


# --------------------------------------------------------------- train

def cmd_train(argv):
    from tools.perf_probe import run
    for spec in argv:
        r, l, w, m = (int(x) for x in spec.split(","))
        run(r, l, w, m)


# --------------------------------------------------------------- micro

def _chained_timer(K):
    """timed(make_fn, label): make_fn(reps) builds fn(binsT, w8, leaf_id)
    whose body runs `reps` chained repetitions; reports per-op device
    time from the K-vs-1 difference."""
    def timed(make_fn, label, args, scale=1.0):
        import jax
        f1 = jax.jit(make_fn(1))
        fK = jax.jit(make_fn(K))
        np.asarray(f1(*args)).sum()          # compile + first run
        np.asarray(fK(*args)).sum()
        ts = []
        for f in (f1, fK):
            t0 = time.perf_counter()
            np.asarray(f(*args)).sum()
            ts.append(time.perf_counter() - t0)
        per = (ts[1] - ts[0]) / (K - 1)
        print(f"{label}: {per*1e3:.2f} ms/op (t1={ts[0]*1e3:.1f} "
              f"tK={ts[1]*1e3:.1f}) -> x{scale:.0f}/tree = "
              f"{per * scale * 1e3:.0f} ms", flush=True)
        return per
    return timed


def cmd_micro(argv):
    N = int(argv[0]) if argv else 10_500_000
    K = 9
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbm_tpu.models.grower_seg import (_pack_bins_words,
                                                _pack_w8_words)
    from lightgbm_tpu.ops.pallas_histogram import (histogram_segment,
                                                   pack_channels,
                                                   pick_block_rows)
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams, best_split
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache()

    F, B = F_HIGGS, B_HIGGS
    rb = pick_block_rows(F, B, N)
    npad = -(-N // rb) * rb
    nblk = npad // rb
    print(f"N={N} rb={rb} blocks={nblk} backend={jax.default_backend()}",
          flush=True)
    rng = np.random.RandomState(0)
    F4 = F + (-F) % 4
    binsT = jnp.asarray(rng.randint(0, B, size=(F4, npad),
                                    dtype=np.int64).astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=npad).astype(np.float32))
    w8 = pack_channels(grad, jnp.ones(npad, jnp.float32),
                       jnp.ones(npad, jnp.float32))
    leaf_id = jnp.asarray(rng.randint(0, 2, size=npad).astype(np.int32))
    args = (binsT, w8, leaf_id)
    timed = _chained_timer(K)

    def mk_hist(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                h = histogram_segment(bT, w, lid, jnp.int32(0),
                                      jnp.int32(nblk), i % 2, B, rb)
                return acc + h
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros((F4, B, 8), jnp.float32))
        return fn
    # sum of smaller-child intervals/tree ~ 10N with default compaction
    timed(mk_hist, "hist_full_N", args, scale=10.0)

    # K=16 frontier kernel over the same full-N pass: the one-hot build
    # is shared across the 16 output-channel groups, so per-row cost
    # should approach the strict kernel's (NOT 16x) while producing 16
    # leaves' histograms — the MXU-utilization fix being measured
    from lightgbm_tpu.ops.pallas_histogram import histogram_frontier
    Kf = 16
    all_blocks = jnp.arange(nblk, dtype=jnp.int32)
    targets16 = jnp.arange(Kf, dtype=jnp.int32) % 2

    def mk_frontier(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                h = histogram_frontier(bT, w, lid, all_blocks,
                                       jnp.int32(nblk),
                                       targets16 + (i % 2), B, rb)
                return acc + h[0]
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros((F4, B, 8), jnp.float32))
        return fn
    timed(mk_frontier, f"hist_frontier_K{Kf}_full_N", args, scale=1.0)

    def mk_sort(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                ops = ((lid_c + i,) + tuple(_pack_bins_words(bT))
                       + tuple(_pack_w8_words(w)))
                return lax.sort(ops, num_keys=1, is_stable=True)[0]
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_sort, "compact_sort", args, scale=4.0)

    # once-per-tree unpermute: random scatter vs 2-operand sort (the
    # growers use the sort form; this pair quantifies the difference)
    perm = jnp.asarray(rng.permutation(npad).astype(np.int32))

    def mk_unperm_scatter(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                return jnp.zeros(npad, jnp.int32).at[perm].set(lid_c + i)
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_unperm_scatter, "unpermute_scatter", args, scale=1.0)

    def mk_unperm_sort2(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                return lax.sort((perm, lid_c + i), num_keys=1)[1]
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_unperm_sort2, "unpermute_sort2", args, scale=1.0)

    # score update's [L]-table gather by a full-N index vector, vs the
    # one-hot-matmul pallas scorer that replaced it (ops/pallas_score)
    lv = jnp.asarray(rng.normal(size=256).astype(np.float32))

    def mk_table_gather(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                return acc + lv[jnp.minimum(lid + i, 255)]
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros(npad, jnp.float32))
        return fn
    timed(mk_table_gather, "score_table_gather", args, scale=1.0)

    from lightgbm_tpu.ops.pallas_score import score_gather_add

    def mk_score_kernel(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                return score_gather_add(acc, jnp.minimum(lid + i, 255), lv)
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros(npad, jnp.float32))
        return fn
    timed(mk_score_kernel, "score_onehot_kernel", args, scale=1.0)

    # per-skipped-grid-step cost: a 1-block interval dispatched on the
    # full-size grid pays (blocks-1) skipped steps; against the 1-block
    # grid the delta isolates the per-step overhead the bucket ladder
    # trades against compile variants
    from lightgbm_tpu.ops.pallas_histogram import _histogram_segment_fixed

    def mk_skip(grid):
        def mk(reps):
            def fn(bT, w, lid):
                def body(i, acc):
                    h = _histogram_segment_fixed(
                        bT, w, lid, jnp.int32(0), jnp.int32(1), i % 2, B,
                        rb, grid)
                    return acc + h
                return lax.fori_loop(0, reps, body,
                                     jnp.zeros((F4, B, 8), jnp.float32))
            return fn
        return mk
    timed(mk_skip(nblk), f"hist_1blk_on_{nblk}grid", args, scale=1.0)
    timed(mk_skip(1), "hist_1blk_on_1grid", args, scale=1.0)

    def mk_route(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                fcol = lax.dynamic_slice_in_dim(bT, i % F, 1, axis=0)[0, :]
                go_left = fcol.astype(jnp.int32) <= 31
                in_leaf = lid_c == i % 7
                return jnp.where(in_leaf & ~go_left, i % 7 + 1, lid_c)
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_route, "route_pass", args, scale=254.0)

    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    sp = SplitParams(has_cat=False)

    def mk_scan(reps):
        def fn(bT, w, lid):
            h0 = histogram_segment(bT, w, lid, jnp.int32(0), jnp.int32(1),
                                   jnp.int32(0), B, rb)
            hist = jnp.stack([h0[..., 0] + h0[..., 1],
                              h0[..., 2] + h0[..., 3],
                              h0[..., 4]], axis=-1)[:F]

            def body(i, acc):
                info = best_split(hist + acc * 1e-9, 1.0, float(N),
                                  float(N), fmeta, sp,
                                  jnp.ones(F, jnp.float32))
                return acc + info.gain
            return lax.fori_loop(0, reps, body, jnp.float32(0.0))
        return fn
    timed(mk_scan, "scan_one", args, scale=508.0)


# ---------------------------------------------------------------- sort

def cmd_sort(argv):
    N = int(argv[0]) if argv else 10_500_000
    K = 5
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbm_tpu.models.grower_seg import (_pack_bins_words,
                                                _pack_w8_words)
    from lightgbm_tpu.ops.pallas_histogram import (pack_channels,
                                                   pick_block_rows)
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache()

    rb = pick_block_rows(F_HIGGS, B_HIGGS, N)
    npad = -(-N // rb) * rb
    print(f"N={N} npad={npad} backend={jax.default_backend()}", flush=True)
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, 64, size=(32, npad),
                                    dtype=np.int64).astype(np.uint8))
    w8 = pack_channels(jnp.asarray(rng.normal(size=npad).astype(np.float32)),
                       jnp.ones(npad, jnp.float32),
                       jnp.ones(npad, jnp.float32))
    lid0 = jnp.asarray(rng.randint(0, 256, size=npad).astype(np.int32))
    args = (binsT, w8, lid0)
    timed = _chained_timer(K)

    def reshuffle(lid, i):
        # cheap pseudo-random re-key so every chained sort does real work
        return ((lid * 1103515245 + i * 12345) & 0xFF).astype(jnp.int32)

    def mk_full(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                ops = ((reshuffle(lid_c, i),) + tuple(_pack_bins_words(bT))
                       + tuple(_pack_w8_words(w))
                       + (jnp.arange(npad, dtype=jnp.int32),))
                return lax.sort(ops, num_keys=1, is_stable=True)[0]
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_full, "sort13", args)

    def mk_pair(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                keys = reshuffle(lid_c, i)
                _, perm = lax.sort(
                    (keys, jnp.arange(npad, dtype=jnp.int32)),
                    num_keys=1, is_stable=True)
                b2 = jnp.take(bT, perm, axis=1)
                w2 = jnp.take(w, perm, axis=1)
                return lid_c + b2[0].astype(jnp.int32) + \
                    w2[4].astype(jnp.int32)
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_pair, "sort2+gather", args)

    def mk_pair_only(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                keys = reshuffle(lid_c, i)
                s, perm = lax.sort(
                    (keys, jnp.arange(npad, dtype=jnp.int32)),
                    num_keys=1, is_stable=True)
                return lid_c + s + perm
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_pair_only, "sort2_only", args)

    def mk_gather(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                perm = (jnp.arange(npad, dtype=jnp.int32) * 7 + i) % npad
                b2 = jnp.take(bT, perm, axis=1)
                w2 = jnp.take(w, perm, axis=1)
                return acc + b2[0].astype(jnp.int32) + \
                    w2[4].astype(jnp.int32)
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_gather, "gather_only", args)


# ------------------------------------------------------------- compile

def cmd_compile(argv):
    import jax
    import jax.numpy as jnp

    variants = argv or ["seg", "kernel", "scan", "fused"]
    N, F, B, L, RB = 65536, 28, 64, 255, 8192
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    member = jnp.ones(N, jnp.float32)
    key = jax.random.PRNGKey(0)
    from lightgbm_tpu.models.grower import GrowerParams
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    fmask = jnp.ones(F, jnp.float32)
    params = GrowerParams(num_leaves=L, hist_backend="pallas",
                          split=SplitParams(min_sum_hessian_in_leaf=100.0,
                                            has_cat=False))

    def stage_time(name, make_lowered):
        t0 = time.perf_counter()
        lowered = make_lowered()
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        print(f"{name}: trace={t1-t0:.1f}s compile={t2-t1:.1f}s",
              flush=True)
        return compiled

    if "seg" in variants:
        from lightgbm_tpu.models.grower_seg import make_grow_tree_segment
        grow = make_grow_tree_segment(B, params, RB)
        stage_time("segment grower", lambda: grow.lower(
            binsT, g, g, member, fmeta, fmask, key))

    if "frontier" in variants:
        from lightgbm_tpu.models.grower_frontier import (
            make_grow_tree_frontier)
        grow = make_grow_tree_frontier(B, params, RB, batch_k=16)
        stage_time("frontier grower (K=16)", lambda: grow.lower(
            binsT, g, g, member, fmeta, fmask, key))

    if "seg_nocompact" in variants:
        import unittest.mock as _mock

        import lightgbm_tpu.models.grower_seg as gs
        with _mock.patch.object(gs, "COMPACT_WASTE", 2.0**30):
            grow = gs.make_grow_tree_segment(B, params, RB)
            stage_time("segment grower (compaction unreachable; cond "
                       "still traced)", lambda: grow.lower(
                           binsT, g, g, member, fmeta, fmask, key))

    if "fused" in variants:
        from lightgbm_tpu.models.grower import make_grow_tree
        grow = make_grow_tree(B, params)
        stage_time("fused grower (pallas hist)", lambda: grow.lower(
            binsT, g, g, member, fmeta, fmask, key))

    if "kernel" in variants:
        from lightgbm_tpu.ops.pallas_histogram import (histogram_segment,
                                                       pack_channels)
        w8 = pack_channels(g, g, member)
        lid = jnp.zeros(N, jnp.int32)

        @jax.jit
        def seg(binsT, w8, lid):
            return histogram_segment(binsT, w8, lid, jnp.int32(0),
                                     jnp.int32(2), jnp.int32(0), B, RB)

        stage_time("segment kernel alone",
                   lambda: seg.lower(binsT, w8, lid))

    if "scan" in variants:
        from lightgbm_tpu.ops.split import best_split

        @jax.jit
        def scan2(hist2):
            return jax.vmap(
                lambda h: best_split(h, jnp.float32(1.0), jnp.float32(2.0),
                                     jnp.float32(1e5), fmeta,
                                     params.split, fmask))(hist2)

        hist2 = jnp.ones((2, F, B, 3), jnp.float32)
        stage_time("vmapped pair best_split", lambda: scan2.lower(hist2))


# --------------------------------------------------------------- trace

TRACE_DIR = "/tmp/lgbtpu_trace"


def cmd_trace(argv):
    N = int(argv[0]) if argv else 10_500_000
    L = int(argv[1]) if len(argv) > 1 else 255
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    rng = np.random.RandomState(42)
    X = rng.normal(size=(N, 28)).astype(np.float32)
    y = (2 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
         + rng.normal(size=N) * 0.5 > 0).astype(np.float64)
    cfg = Config(objective="binary", num_leaves=L, max_bin=63,
                 learning_rate=0.1, min_sum_hessian_in_leaf=100.0,
                 verbosity=-1,
                 tpu_tree_impl=os.environ.get("LIGHTGBM_TPU_IMPL", "auto"))
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = GBDT(cfg, ds, obj)
    for _ in range(2):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    jax.profiler.start_trace(TRACE_DIR)
    for _ in range(2):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    jax.profiler.stop_trace()
    _summarize_xplane(TRACE_DIR)


def _summarize_xplane(trace_dir):
    # the tensorboard_plugin_profile wheel in this image ships no
    # python protobufs; tensorflow's tsl copy of xplane_pb2 parses the
    # same .xplane.pb files
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {trace_dir}"
    path = max(paths, key=os.path.getmtime)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())
    for plane in xs.planes:
        if "tpu" not in plane.name.lower():
            continue
        tot = defaultdict(float)
        cnt = defaultdict(int)
        for line in plane.lines:
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                tot[name] += ev.duration_ps / 1e12
                cnt[name] += 1
        items = sorted(tot.items(), key=lambda kv: -kv[1])
        total = sum(tot.values())
        print(f"== plane {plane.name}: lines={len(plane.lines)} "
              f"total={total:.3f}s (2 iters; includes overlap)")
        for name, sec in items[:40]:
            print(f"  {sec:8.3f}s x{cnt[name]:<7} {name[:110]}")


def cmd_parse_profile(argv):
    _summarize_xplane(argv[0] if argv else TRACE_DIR)


# ---------------------------------------------------------------- main

COMMANDS = {
    "train": cmd_train,
    "micro": cmd_micro,
    "sort": cmd_sort,
    "compile": cmd_compile,
    "trace": cmd_trace,
    "parse-profile": cmd_parse_profile,
}

if __name__ == "__main__":
    if len(sys.argv) < 2 or sys.argv[1] not in COMMANDS:
        print(__doc__)
        sys.exit(2)
    COMMANDS[sys.argv[1]](sys.argv[2:])
