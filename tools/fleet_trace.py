"""Merge per-rank Chrome traces onto one skew-corrected fleet timeline.

Every rank of a multi-host run exports its own Chrome trace
(``LIGHTGBM_TPU_TRACE_JSON``; utils/telemetry.chrome_trace).  Each
file's event timestamps are microseconds since that PROCESS's telemetry
epoch on that HOST's clock — overlaying them naively puts rank 1's
iteration 40 under rank 0's iteration 2.  This tool rebases them onto
one timeline:

  * each v6 trace carries a ``mono_epoch`` anchor in ``otherData`` (the
    telemetry epoch pinned on the host monotonic clock), so an event's
    host-monotonic instant is ``mono_epoch + ts/1e6``;
  * the ``dist_clock`` health record (obs/clockskew.py, in every rank's
    health stream) carries the measured per-rank monotonic offsets onto
    rank 0's clock, bounded by ping RTT — adding ``offset_s`` yields
    the fleet instant;
  * the earliest fleet instant across all ranks becomes t=0 of the
    merged trace.

The merged file gives each rank its own process lane (``pid`` = rank,
with ``process_name``/``process_sort_index`` metadata) and draws flow
arrows between the per-rank spans of the same logical collective —
``net/*`` spans share a ``seq`` argument (the collective call index,
identical across ranks because every rank issues collectives in the
same order), so arrow N runs from the first rank to enter collective N
to the last: the straggler is the rank every arrow points at.

v5 traces (no ``mono_epoch``) still merge — their lanes are flagged
``unanchored`` and keep their own zero, which is only correct for
single-host fleets.

Usage:
  python tools/fleet_trace.py rundir/ -o fleet.trace.json
  python tools/fleet_trace.py r0.trace.json r1.trace.json \\
      --offsets-from rundir/ -o fleet.trace.json

``rundir/`` is scanned for ``*.trace.json`` per-rank traces and
``*.jsonl`` health streams (the newest ``dist_clock`` record wins).
Open the output in Perfetto / chrome://tracing like any other trace.
"""

import argparse
import glob
import json
import os
import re
import sys

FLEET_TRACE_SCHEMA = "lightgbm_tpu.fleet_trace/v1"

# trace-event phases that carry a point timestamp we must rebase
_POINT_PHASES = ("X", "C", "i", "I", "s", "t", "f", "b", "e", "n")


def _rank_of(trace, path, fallback):
    """Rank for a per-rank trace: otherData.rank (v6 multi-host), a
    rankN hint in the filename, else the file's position."""
    other = trace.get("otherData") or {}
    if isinstance(other.get("rank"), int):
        return int(other["rank"])
    m = re.search(r"rank(\d+)", os.path.basename(path))
    if m:
        return int(m.group(1))
    return fallback


def load_offsets_from_streams(paths):
    """Newest ``dist_clock`` offset table found across health streams:
    ``{rank: {"offset_s", "bound_s", "rtt_s"}}`` (the table is
    allgathered, so any rank's stream carries the whole fleet)."""
    best = None
    for path in paths:
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            line = line.strip()
            if not line or b'"dist_clock"' not in line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") != "dist_clock":
                continue
            table = rec.get("offsets") or {}
            key = rec.get("mono_ts") or rec.get("t") or 0.0
            if best is None or key >= best[0]:
                best = (key, {int(r): dict(v) for r, v in table.items()})
    return best[1] if best else {}


def _offset_s(offsets, rank):
    entry = offsets.get(rank) if offsets else None
    return float(entry["offset_s"]) if entry else 0.0


def merge_traces(traces, offsets=None):
    """Pure merge core: ``traces`` is ``[(rank, trace_dict), ...]``;
    ``offsets`` the clockskew table (may be empty/None — single-host
    fleets share one clock).  Returns the merged Chrome trace dict."""
    offsets = offsets or {}
    lanes = []          # (rank, mono_epoch|None, events)
    anchored = []
    for rank, trace in traces:
        other = trace.get("otherData") or {}
        epoch = other.get("mono_epoch")
        mono = (float(epoch) + _offset_s(offsets, rank)
                if isinstance(epoch, (int, float)) else None)
        lanes.append((rank, mono, trace.get("traceEvents") or []))
        if mono is not None:
            anchored.append(mono)
    # t=0 of the merged trace = the earliest anchored epoch, so every
    # lane starts at a small positive offset and relative gaps between
    # ranks are real (startup skew included)
    base = min(anchored) if anchored else 0.0

    merged = []
    net_spans = {}      # (name, seq) -> [(fleet_ts, rank, tid)]
    for rank, mono, events in lanes:
        shift_us = 0.0 if mono is None else (mono - base) * 1e6
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank{rank}" +
                                ("" if mono is not None
                                 else " (unanchored)")}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in events:
            ev = dict(ev)
            ev["pid"] = rank
            if ev.get("ph") in _POINT_PHASES and "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            merged.append(ev)
            if (ev.get("ph") == "X"
                    and str(ev.get("name", "")).startswith("net/")):
                seq = (ev.get("args") or {}).get("seq")
                if seq is not None:
                    net_spans.setdefault(
                        (ev["name"], int(seq)), []).append(
                            (ev["ts"], rank, ev.get("tid", 0)))

    # one flow arrow per logical collective, first-entering rank ->
    # last (the straggler every arrow converges on)
    flow_id = 0
    for (name, seq), hits in sorted(net_spans.items()):
        if len(hits) < 2:
            continue
        hits.sort()
        flow_id += 1
        for i, (ts, rank, tid) in enumerate(hits):
            ph = "s" if i == 0 else ("f" if i == len(hits) - 1 else "t")
            ev = {"name": name, "cat": "fleet-flow", "ph": ph,
                  "id": flow_id, "pid": rank, "tid": tid, "ts": ts}
            if ph == "f":
                ev["bp"] = "e"      # bind the arrow head to the
            merged.append(ev)       # enclosing (straggler's) span

    # stable time order per lane (metadata events carry no ts: sort
    # them first so Perfetto names lanes before drawing into them)
    merged.sort(key=lambda ev: (ev.get("ph") != "M",
                                float(ev.get("ts", 0.0)),
                                ev.get("pid", 0)))
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": FLEET_TRACE_SCHEMA,
            "ranks": sorted(r for r, _m, _e in lanes),
            "base_mono_s": round(base, 6),
            "offsets": {str(r): v for r, v in sorted(offsets.items())},
            "flows": flow_id,
        },
    }


def _collect_inputs(paths):
    """Expand dirs into (trace_files, stream_files); pass files
    through by extension."""
    traces, streams = [], []
    for p in paths:
        if os.path.isdir(p):
            traces.extend(sorted(glob.glob(os.path.join(
                p, "*.trace.json"))))
            streams.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        elif p.endswith(".jsonl"):
            streams.append(p)
        else:
            traces.append(p)
    return traces, streams


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-rank Chrome traces onto one "
                    "skew-corrected fleet timeline")
    ap.add_argument("paths", nargs="+",
                    help="per-rank *.trace.json files and/or a run "
                         "directory holding them (plus the health "
                         "streams the clock offsets come from)")
    ap.add_argument("--offsets-from", default=None,
                    help="health stream file/dir to read the "
                         "dist_clock offset table from (default: the "
                         "*.jsonl streams found next to the traces)")
    ap.add_argument("-o", "--out", default="fleet.trace.json",
                    help="merged trace destination "
                         "(default fleet.trace.json)")
    args = ap.parse_args(argv)

    trace_files, stream_files = _collect_inputs(args.paths)
    if args.offsets_from:
        _ignored, extra = _collect_inputs([args.offsets_from])
        stream_files = extra or [args.offsets_from]
    if not trace_files:
        print("fleet_trace: no *.trace.json inputs found")
        return 2

    traces = []
    for i, path in enumerate(trace_files):
        try:
            with open(path) as fh:
                trace = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"fleet_trace: skipping unreadable trace {path}: {e}")
            continue
        traces.append((_rank_of(trace, path, i), trace))
    if not traces:
        print("fleet_trace: no readable traces")
        return 2

    offsets = load_offsets_from_streams(stream_files)
    merged = merge_traces(traces, offsets)
    with open(args.out, "w") as fh:
        json.dump(merged, fh)
    other = merged["otherData"]
    print(f"fleet_trace: {len(traces)} rank(s) -> {args.out} "
          f"({len(merged['traceEvents'])} events, "
          f"{other['flows']} collective flow arrow(s), "
          f"offsets for {len(offsets)} rank(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
