"""Summarize a lightgbm_tpu metrics JSON blob for perf rounds.

Input: a metrics dict as produced by ``TELEMETRY.metrics_blob()`` /
``Booster.get_stats()`` — the blob the CLI writes for ``metrics_out=``,
``bench.py`` / ``bench_suite.py`` embed under ``"metrics"``, and
``engine.train`` attaches as ``booster.train_stats``.  The current
``lightgbm_tpu.metrics/v7`` schema and the older v6/v5/v4/v3/v2/v1
blobs are all accepted: every section is optional and renders as
``n/a`` when absent.

Usage:
  python tools/trace_report.py metrics.json          # a raw blob
  python tools/trace_report.py BENCH_r05.json        # a bench record
                                                     # (reads .metrics)
  python tools/trace_report.py --diff a.json b.json  # phase/counter/
                                                     # memory/cost/
                                                     # timing deltas

Prints top phases, transfer bytes, compile counters/seconds, network
collective counters, the iteration count, (v2) the HBM memory envelope
and XLA cost-analysis utilization digest, (v3) the run-health stream
digest, (v4) the measured dispatch-timing table with
measured-vs-estimated utilization, (v6) the fleet plane's collective
wait-vs-work split with the straggler histogram, and (v7) the drift
plane's per-model PSI / score-JS verdicts — the digest VERDICT /
PERF_NOTES rounds quote instead of regex-parsing stderr tails.
"""

import json
import sys


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


def _fmt_rate(n: float, unit: str) -> str:
    n = float(n)
    for prefix in ("", "K", "M", "G", "T"):
        if abs(n) < 1000.0 or prefix == "T":
            return f"{n:.2f}{prefix}{unit}"
        n /= 1000.0
    return f"{n:.2f}T{unit}"


def summarize(stats: dict, top: int = 6) -> str:
    """Multi-line human-readable digest of one metrics blob."""
    lines = []
    mode = stats.get("mode", "?")
    lines.append(f"telemetry summary [version={stats.get('version', 'n/a')} "
                 f"level={stats.get('level', 'n/a')} mode={mode}]")

    phases = stats.get("phases") or {}
    if phases:
        total = sum(p.get("seconds", 0.0) for p in phases.values())
        ranked = sorted(phases.items(),
                        key=lambda kv: -kv[1].get("seconds", 0.0))[:top]
        parts = [f"{name}={p.get('seconds', 0.0):.3f}s/{p.get('count', 0)}"
                 for name, p in ranked]
        lines.append(f"  phases ({mode}) total={total:.3f}s: "
                     + " ".join(parts))
    else:
        lines.append("  phases: n/a")

    counters = stats.get("counters") or {}
    fetch_b = counters.get("transfer/fetch_bytes", 0)
    fetch_n = counters.get("transfer/fetch_calls", 0)
    h2d_b = counters.get("transfer/h2d_bytes", 0)
    if fetch_n or h2d_b:
        lines.append(f"  transfers: d2h {_fmt_bytes(fetch_b)} in "
                     f"{int(fetch_n)} fetches, h2d {_fmt_bytes(h2d_b)}")
    compiles = {k: v for k, v in counters.items()
                if k.startswith("compile/")}
    if compiles:
        lines.append(
            "  compile: "
            f"{int(compiles.get('compile/backend_compiles', 0))} backend "
            f"compiles ({compiles.get('compile/backend_compile_seconds', 0.0):.2f}s), "
            f"{int(compiles.get('compile/retraces', 0))} retraces "
            f"({compiles.get('compile/retrace_seconds', 0.0):.2f}s), "
            f"cache {int(compiles.get('compile/cache_hits', 0))} hits / "
            f"{int(compiles.get('compile/cache_misses', 0))} misses")
    seg = {k: v for k, v in counters.items() if k.startswith("seg/")}
    if seg:
        lines.append(f"  segment grower: "
                     f"{int(seg.get('seg/scanned_blocks', 0))} blocks "
                     f"scanned, {int(seg.get('seg/compactions', 0))} "
                     f"compactions")
    # histogram variant counters (packed accumulator / round-carry
    # staging).  n/a-safe: absent entirely on the f32/unstaged paths —
    # rendering zero rates there would read as "variant ran and did
    # nothing", so only live counters print
    hist = {k: v for k, v in counters.items() if k.startswith("hist/")}
    if hist:
        parts = []
        if hist.get("hist/quant_rescales"):
            parts.append(f"{int(hist['hist/quant_rescales'])} quant "
                         f"rescales ({int(hist.get('hist/quant_clips', 0))}"
                         f" saturated lanes)")
        looks = hist.get("hist/stage_lookups")
        if looks:
            hits = int(hist.get("hist/stage_hits", 0))
            parts.append(f"stage hits {hits}/{int(looks)} "
                         f"({hits / max(int(looks), 1):.0%})")
        if parts:
            lines.append("  histogram variants: " + ", ".join(parts))

    network = stats.get("network") or {}
    if network:
        parts = [f"{k}={v.get('calls', 0)}x/"
                 f"{_fmt_bytes(v.get('bytes', 0))}/"
                 f"{v.get('seconds', 0.0):.3f}s"
                 for k, v in sorted(network.items())]
        lines.append("  network: " + " ".join(parts))

    gauges = stats.get("gauges") or {}
    if gauges:
        parts = [f"{k}={v:g}" for k, v in sorted(gauges.items())]
        lines.append("  gauges: " + " ".join(parts))

    timeline = stats.get("timeline") or []
    if timeline:
        iters = sum(e.get("count", 1) for e in timeline)
        span = (timeline[-1].get("t", 0.0)
                - (timeline[0].get("t", 0.0) if len(timeline) > 1 else 0.0))
        lines.append(f"  timeline: {iters} iterations in "
                     f"{len(timeline)} marks over {span:.3f}s")

    spans = stats.get("spans") or {}
    if spans.get("recorded"):
        lines.append(f"  spans: {spans['recorded']} recorded, "
                     f"{spans.get('dropped', 0)} dropped "
                     f"(capacity {spans.get('capacity')})")

    lines.extend(_memory_lines(stats))
    lines.extend(_cost_lines(stats))
    lines.extend(_utilization_lines(stats))
    lines.extend(_timing_lines(stats))
    lines.extend(_fault_lines(stats))
    lines.extend(_health_lines(stats))
    lines.extend(_fleet_lines(stats))
    lines.extend(_drift_lines(stats))
    return "\n".join(lines)


def _memory_lines(stats: dict, top: int = 4) -> list:
    mem = stats.get("memory")
    if not mem:
        return ["  memory: n/a (backend reports no memory stats, "
                "or v1 blob)"]
    peak = mem.get("peak_bytes_in_use", 0)
    line = (f"  memory: peak {_fmt_bytes(peak)}, now "
            f"{_fmt_bytes(mem.get('bytes_in_use', 0))}, largest alloc "
            f"{_fmt_bytes(mem.get('largest_alloc', 0))}")
    limit = mem.get("bytes_limit")
    if limit:
        line += (f", limit {_fmt_bytes(limit)} "
                 f"({100.0 * peak / limit:.1f}% peak)")
    out = [line]
    phases = mem.get("phases") or {}
    if phases:
        ranked = sorted(phases.items(),
                        key=lambda kv: -kv[1].get("bytes_in_use_max",
                                                  0))[:top]
        parts = [f"{name}<={_fmt_bytes(p.get('bytes_in_use_max', 0))}"
                 f"/{p.get('samples', 0)}" for name, p in ranked]
        out.append("  memory by phase (max in-use/samples): "
                   + " ".join(parts))
    sampler = mem.get("sampler")
    if sampler:
        out.append(f"  memory sampler: {sampler.get('samples', 0)} samples "
                   f"@ {sampler.get('interval_ms', 0):g}ms")
    return out


def _cost_lines(stats: dict, top: int = 6) -> list:
    cost = stats.get("cost")
    if not cost:
        return ["  cost: n/a (no compiled-seam cost analysis in blob)"]
    labels = cost.get("labels") or {}
    ranked = sorted(labels.items(),
                    key=lambda kv: -kv[1].get("flops_total", 0.0))[:top]
    out = [f"  cost ({len(labels)} seams, "
           f"{cost.get('window_seconds', 0.0):.3f}s window): "
           f"{_fmt_rate(cost.get('flops_total', 0.0), 'FLOP')} total, "
           f"{_fmt_bytes(cost.get('bytes_total', 0.0))} accessed"]
    for name, e in ranked:
        out.append(
            f"    {name}: {e.get('calls', 0)} calls x "
            f"{_fmt_rate(e.get('flops', 0.0), 'FLOP')}/"
            f"{_fmt_bytes(e.get('bytes_accessed', 0.0))} "
            f"= {_fmt_rate(e.get('flops_total', 0.0), 'FLOP')} "
            f"({e.get('compiles', 0)} compiles)")
    return out


def _fault_lines(stats: dict, top: int = 8) -> list:
    faults = stats.get("faults")
    if not faults:
        return ["  faults: n/a (no injections or recoveries this run)"]
    counts = faults.get("counts") or {}
    parts = [f"{k}={int(v)}" for k, v in sorted(counts.items())]
    out = ["  faults: " + (" ".join(parts) if parts else "(events only)")]
    for ev in (faults.get("events") or [])[-top:]:
        desc = ev.get("kind", "?")
        if ev.get("site"):
            desc += f" @ {ev['site']}"
        if ev.get("iter") is not None:
            desc += f" iter {ev['iter']}"
        if ev.get("detail"):
            desc += f" ({ev['detail']})"
        out.append(f"    t={ev.get('t', 0.0):.3f}s {desc}")
    return out


def _health_lines(stats: dict) -> list:
    health = stats.get("health")
    if not health:
        return ["  health: n/a (no health_out stream this run, "
                "or pre-v3 blob)"]
    by_kind = health.get("by_kind") or {}
    parts = [f"{k}={int(v)}" for k, v in sorted(by_kind.items())]
    line = (f"  health: {int(health.get('records', 0))} records -> "
            f"{health.get('path', '?')}"
            + (f" [{' '.join(parts)}]" if parts else ""))
    last = health.get("last_iter")
    if isinstance(last, dict) and last.get("iter") is not None:
        line += f", last iter {int(last['iter'])}"
        if last.get("chunk"):
            line += f" (chunk={int(last['chunk'])})"
    nonfinite = health.get("nonfinite_total")
    out = [line]
    if nonfinite:
        out.append(f"  health ALERT: {int(nonfinite)} non-finite "
                   f"gradient/hessian values recorded")
    return out


def _fleet_lines(stats: dict) -> list:
    fleet = stats.get("fleet")
    if not fleet:
        return ["  fleet: n/a (single-host run, fleet_obs_sync_iters=0,"
                " or pre-v6 blob)"]
    out = [f"  fleet: {int(fleet.get('windows', 0))} attributed "
           f"window(s), sync every "
           f"{fleet.get('sync_iters', '?')} iteration(s)"]
    per_rank = fleet.get("per_rank") or {}
    for rank, slot in sorted(per_rank.items(),
                             key=lambda kv: str(kv[0])):
        frac = slot.get("wait_fraction")
        out.append(
            f"    rank{rank}: wait {slot.get('wait_s', 0.0):.3f}s / "
            f"work {slot.get('work_s', 0.0):.3f}s over "
            f"{int(slot.get('calls', 0))} collective call(s)"
            + (f" ({frac:.0%} waiting)"
               if isinstance(frac, (int, float)) else ""))
    hist = fleet.get("straggler_hist") or {}
    if hist:
        worst = max(hist, key=hist.get)
        out.append("    stragglers: "
                   + " ".join(f"rank{r}={n}x"
                              for r, n in sorted(hist.items()))
                   + f" — rank{worst} slowest most often")
    return out


def _drift_lines(stats: dict) -> list:
    drift = stats.get("drift")
    if not drift:
        return ["  drift: n/a (drift_detect off, no synced window,"
                " or pre-v7 blob)"]
    models = drift.get("models") or {}
    out = [f"  drift: {len(models)} model(s) vs training baseline,"
           f" psi threshold {drift.get('psi_threshold', '?')}"]
    for mid, rec in sorted(models.items()):
        js = rec.get("score_js")
        top = " ".join(f"{e.get('feature', '?')}={e.get('psi', 0):.3f}"
                       for e in (rec.get("top") or [])[:3])
        out.append(
            f"    {mid}: psi_max={rec.get('psi_max', 0):.3f}"
            + (f" score_js={js:.3f}" if isinstance(js, (int, float))
               else "")
            + f" over {rec.get('rows', '?')} row(s)"
            + (f"  [{top}]" if top else "")
            + ("  !! DRIFT" if rec.get("drifted") else ""))
    return out


def _utilization_lines(stats: dict) -> list:
    cost = stats.get("cost") or {}
    fps = cost.get("est_flops_per_s")
    bps = cost.get("est_bytes_per_s")
    if fps is None and bps is None:
        return []
    parts = []
    if fps is not None:
        parts.append(f"est {_fmt_rate(fps, 'FLOP/s')}")
    if bps is not None:
        parts.append(f"est {_fmt_rate(bps, 'B/s')} accessed")
    mem = stats.get("memory") or {}
    limit = mem.get("bytes_limit")
    if limit:
        parts.append(f"peak HBM {100.0 * mem.get('peak_bytes_in_use', 0) / limit:.1f}% of {_fmt_bytes(limit)}")
    return ["  utilization: " + ", ".join(parts)
            + "  (static XLA estimates over the wall window; an upper "
            "bound on achieved rates)"]


def _timing_lines(stats: dict, top: int = 6) -> list:
    timing = stats.get("timing")
    if not timing or not timing.get("enabled"):
        out = ["  timing: n/a (device_timing off, or pre-v4 blob)"]
        prof = (timing or {}).get("profile")
        if prof:
            out.append(_profile_line(prof))
        return out
    labels = timing.get("labels") or {}
    ranked = sorted(labels.items(),
                    key=lambda kv: -kv[1].get("total_s", 0.0))[:top]
    out = [f"  timing (measured wall-to-ready, {len(labels)} seams): "
           f"{timing.get('total_s', 0.0):.3f}s device-synced"]
    for name, e in ranked:
        line = (f"    {name}: {e.get('count', 0)} x "
                f"{e.get('mean_s', 0.0) * 1e3:.3f}ms mean "
                f"(p50 {e.get('p50_s', 0.0) * 1e3:.3f} / "
                f"p99 {e.get('p99_s', 0.0) * 1e3:.3f} / "
                f"max {e.get('max_s', 0.0) * 1e3:.3f}ms)")
        if e.get("gap_mean_s") is not None:
            line += f", gap {e['gap_mean_s'] * 1e3:.3f}ms mean"
        out.append(line)
    # measured vs estimated: static XLA FLOPs over the MEASURED seconds
    # next to the wall-window estimate — the gap is dispatch overhead +
    # how far the estimate's upper bound sits from achieved rates
    mfps = timing.get("measured_flops_per_s")
    efps = (stats.get("cost") or {}).get("est_flops_per_s")
    if mfps is not None:
        line = f"  utilization (measured): {_fmt_rate(mfps, 'FLOP/s')}"
        mbps = timing.get("measured_bytes_per_s")
        if mbps is not None:
            line += f", {_fmt_rate(mbps, 'B/s')} accessed"
        if efps:
            line += (f"  [{100.0 * mfps / efps:.1f}% of the "
                     "wall-window estimate]")
        out.append(line)
    prof = timing.get("profile")
    if prof:
        out.append(_profile_line(prof))
    return out


def _profile_line(prof: dict) -> str:
    line = f"  profile: {prof.get('kind', '?')} -> {prof.get('dir', '?')}"
    window = prof.get("window")
    if window:
        line += f" (iterations [{window[0]}, {window[1]})"
        req = prof.get("requested")
        if req and list(req) != list(window):
            line += f", requested [{req[0]}, {req[1]})"
        line += ")"
    return line


# ------------------------------------------------------------------ diff
def _phase_map(stats: dict) -> dict:
    return {k: v.get("seconds", 0.0)
            for k, v in (stats.get("phases") or {}).items()}


def _mem_scalars(stats: dict) -> dict:
    mem = stats.get("memory") or {}
    return {k: mem[k] for k in ("peak_bytes_in_use", "bytes_in_use",
                                "largest_alloc") if k in mem}


def _cost_scalars(stats: dict) -> dict:
    cost = stats.get("cost") or {}
    out = {k: cost[k] for k in ("flops_total", "bytes_total",
                                "est_flops_per_s") if k in cost}
    for name, e in (cost.get("labels") or {}).items():
        out[f"{name}.calls"] = e.get("calls", 0)
        out[f"{name}.flops_total"] = e.get("flops_total", 0.0)
    return out


def _timing_scalars(stats: dict) -> dict:
    timing = stats.get("timing") or {}
    out = {}
    if timing.get("total_s") is not None:
        out["total_s"] = timing["total_s"]
    for k in ("measured_flops_per_s", "measured_bytes_per_s"):
        if timing.get(k) is not None:
            out[k] = timing[k]
    for name, e in (timing.get("labels") or {}).items():
        out[f"{name}.mean_s"] = e.get("mean_s", 0.0)
        out[f"{name}.p99_s"] = e.get("p99_s", 0.0)
    return out


def _drift_scalars(stats: dict) -> dict:
    out = {}
    for mid, rec in ((stats.get("drift") or {}).get("models")
                     or {}).items():
        out[f"{mid}.psi_max"] = rec.get("psi_max", 0.0)
        if rec.get("score_js") is not None:
            out[f"{mid}.score_js"] = rec["score_js"]
        out[f"{mid}.rows"] = float(rec.get("rows", 0))
    return out


def _diff_section(title: str, a: dict, b: dict, fmt) -> list:
    keys = sorted(set(a) | set(b))
    if not keys:
        return [f"  {title}: n/a"]
    out = [f"  {title}:"]
    for k in keys:
        va, vb = a.get(k), b.get(k)
        if va is None:
            out.append(f"    {k}: n/a -> {fmt(vb)}")
        elif vb is None:
            out.append(f"    {k}: {fmt(va)} -> n/a")
        else:
            delta = vb - va
            if not delta and va == vb:
                continue
            pct = f" ({100.0 * delta / va:+.1f}%)" if va else ""
            out.append(f"    {k}: {fmt(va)} -> {fmt(vb)} "
                       f"[{'+' if delta >= 0 else ''}{fmt(delta)}{pct}]")
    if len(out) == 1:
        out.append("    (no change)")
    return out


def diff(a: dict, b: dict) -> str:
    """Human-readable deltas between two metrics blobs (a -> b)."""
    lines = [f"metrics diff [v{a.get('version', '?')} -> "
             f"v{b.get('version', '?')}]"]
    sec = lambda v: f"{v:.3f}s"
    num = lambda v: f"{v:g}"
    lines.extend(_diff_section("phases (seconds)", _phase_map(a),
                               _phase_map(b), sec))
    ca = {k: float(v) for k, v in (a.get("counters") or {}).items()}
    cb = {k: float(v) for k, v in (b.get("counters") or {}).items()}
    lines.extend(_diff_section("counters", ca, cb, num))
    lines.extend(_diff_section("memory (bytes)", _mem_scalars(a),
                               _mem_scalars(b), _fmt_bytes))
    lines.extend(_diff_section("cost", _cost_scalars(a),
                               _cost_scalars(b), num))
    lines.extend(_diff_section("timing (measured)", _timing_scalars(a),
                               _timing_scalars(b), num))
    lines.extend(_diff_section("drift", _drift_scalars(a),
                               _drift_scalars(b), num))
    return "\n".join(lines)


def _load(path: str) -> dict:
    with open(path) as fh:
        blob = json.load(fh)
    # accept a bench record wrapping the blob under "metrics"
    if "phases" not in blob and isinstance(blob.get("metrics"), dict):
        blob = blob["metrics"]
    return blob


def main(argv) -> int:
    if len(argv) == 3 and argv[0] == "--diff":
        print(diff(_load(argv[1]), _load(argv[2])))
        return 0
    if len(argv) != 1 or argv[0].startswith("--"):
        print(__doc__)
        return 2
    print(summarize(_load(argv[0])))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
