"""Summarize a lightgbm_tpu metrics JSON blob for perf rounds.

Input: a metrics dict as produced by ``TELEMETRY.metrics_blob()`` /
``Booster.get_stats()`` — the blob the CLI writes for ``metrics_out=``,
``bench.py`` embeds under ``"metrics"``, and ``engine.train`` attaches
as ``booster.train_stats``.

Usage:
  python tools/trace_report.py metrics.json          # a raw blob
  python tools/trace_report.py BENCH_r05.json        # a bench record
                                                     # (reads .metrics)

Prints top phases, transfer bytes, compile counters/seconds, network
collective counters and the iteration count — the digest VERDICT /
PERF_NOTES rounds quote instead of regex-parsing stderr tails.
"""

import json
import sys


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


def summarize(stats: dict, top: int = 6) -> str:
    """Multi-line human-readable digest of one metrics blob."""
    lines = []
    mode = stats.get("mode", "?")
    lines.append(f"telemetry summary [level={stats.get('level')} "
                 f"mode={mode}]")

    phases = stats.get("phases") or {}
    if phases:
        total = sum(p.get("seconds", 0.0) for p in phases.values())
        ranked = sorted(phases.items(),
                        key=lambda kv: -kv[1].get("seconds", 0.0))[:top]
        parts = [f"{name}={p['seconds']:.3f}s/{p.get('count', 0)}"
                 for name, p in ranked]
        lines.append(f"  phases ({mode}) total={total:.3f}s: "
                     + " ".join(parts))

    counters = stats.get("counters") or {}
    fetch_b = counters.get("transfer/fetch_bytes", 0)
    fetch_n = counters.get("transfer/fetch_calls", 0)
    h2d_b = counters.get("transfer/h2d_bytes", 0)
    if fetch_n or h2d_b:
        lines.append(f"  transfers: d2h {_fmt_bytes(fetch_b)} in "
                     f"{int(fetch_n)} fetches, h2d {_fmt_bytes(h2d_b)}")
    compiles = {k: v for k, v in counters.items()
                if k.startswith("compile/")}
    if compiles:
        lines.append(
            "  compile: "
            f"{int(compiles.get('compile/backend_compiles', 0))} backend "
            f"compiles ({compiles.get('compile/backend_compile_seconds', 0.0):.2f}s), "
            f"{int(compiles.get('compile/retraces', 0))} retraces "
            f"({compiles.get('compile/retrace_seconds', 0.0):.2f}s), "
            f"cache {int(compiles.get('compile/cache_hits', 0))} hits / "
            f"{int(compiles.get('compile/cache_misses', 0))} misses")
    seg = {k: v for k, v in counters.items() if k.startswith("seg/")}
    if seg:
        lines.append(f"  segment grower: "
                     f"{int(seg.get('seg/scanned_blocks', 0))} blocks "
                     f"scanned, {int(seg.get('seg/compactions', 0))} "
                     f"compactions")

    network = stats.get("network") or {}
    if network:
        parts = [f"{k}={v['calls']}x/{_fmt_bytes(v['bytes'])}/"
                 f"{v['seconds']:.3f}s"
                 for k, v in sorted(network.items())]
        lines.append("  network: " + " ".join(parts))

    gauges = stats.get("gauges") or {}
    if gauges:
        parts = [f"{k}={v:g}" for k, v in sorted(gauges.items())]
        lines.append("  gauges: " + " ".join(parts))

    timeline = stats.get("timeline") or []
    if timeline:
        iters = sum(e.get("count", 1) for e in timeline)
        span = timeline[-1]["t"] - (timeline[0]["t"]
                                    if len(timeline) > 1 else 0.0)
        lines.append(f"  timeline: {iters} iterations in "
                     f"{len(timeline)} marks over {span:.3f}s")

    spans = stats.get("spans") or {}
    if spans.get("recorded"):
        lines.append(f"  spans: {spans['recorded']} recorded, "
                     f"{spans.get('dropped', 0)} dropped "
                     f"(capacity {spans.get('capacity')})")
    return "\n".join(lines)


def main(argv) -> int:
    if len(argv) != 1:
        print(__doc__)
        return 2
    with open(argv[0]) as fh:
        blob = json.load(fh)
    # accept a bench record wrapping the blob under "metrics"
    if "phases" not in blob and isinstance(blob.get("metrics"), dict):
        blob = blob["metrics"]
    print(summarize(blob))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
