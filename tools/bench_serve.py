"""Serving benchmark: BENCH_SERVE.json + trajectory records.

Measures the lightgbm_tpu/serve stack the way bench_suite.py measures
training: each model size runs in its own subprocess (hard timeout, one
JSON result line per grid cell), the parent collects the grid into
BENCH_SERVE.json and appends one digest line per cell to
BENCH_TRAJECTORY.jsonl, where tools/bench_gate.py gates the p99 against
the trailing median (+20%).

The grid is (model size) x (batch bucket) x (serve_max_delay_ms):
requests of exactly one bucket's rows are pushed through the
micro-batching queue one at a time, so ``p50_s``/``p99_s`` are
END-TO-END request latencies (queue wait + padded compiled dispatch +
host f64 gather) and ``qps`` is requests/s (``rows_per_s`` = qps x
bucket rows).  The delay knob shows up directly: d0 dispatches
immediately, d4 holds the queue open ~4ms hoping for co-batchable
traffic that a closed-loop client never sends — the visible p50 gap IS
the latency-vs-throughput tradeoff the knob buys.

Every cell also re-checks the core serving contract: the serve result
must be bit-identical to ``Booster.predict`` on the same rows
(quality_ok), so a latency improvement can never silently buy itself
out of correctness.

Usage:
  python tools/bench_serve.py             # full grid -> BENCH_SERVE.json
  python tools/bench_serve.py --gate      # + bench_gate over trajectory
  python tools/bench_serve.py --smoke     # tiny single cell, no artifacts
"""

import argparse
import json
import os
import subprocess
import sys
import time

RESULT_TAG = "SERVE_RESULT_JSON:"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BUCKETS = [16, 64]
DELAYS_MS = [0.0, 4.0]

# model size -> (rows, feats, iters, leaves, child timeout s).  The
# "large" cell is sized to stay trainable on a single-core CI box
# inside its timeout; on a real accelerator both cells are quick.
SIZES = {
    "small": (20_000, 20, 60, 31, 900),
    "large": (30_000, 30, 100, 63, 2400),
}
SMOKE_SIZE = ("smoke", (2_000, 10, 10, 15, 300))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def run_child(size: str, smoke: bool) -> None:
    sys.path.insert(0, REPO)
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache(REPO)
    import jax
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import ServeSession
    from lightgbm_tpu.utils.telemetry import TELEMETRY

    if smoke:
        rows, feats, iters, leaves, _ = SMOKE_SIZE[1]
        buckets, delays, n_requests = [16], [0.0], 8
    else:
        rows, feats, iters, leaves, _ = SIZES[size]
        buckets, delays, n_requests = BUCKETS, DELAYS_MS, 60

    rng = np.random.RandomState(11)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    # two categorical columns + a NaN-missing column keep the measured
    # path the same one the parity tests bit-check
    X[:, -1] = rng.randint(0, 12, size=rows)
    X[:, -2] = rng.randint(0, 6, size=rows)
    X[rng.rand(rows) < 0.05, 0] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + X[:, 1] + (X[:, -1] % 3 == 0))
         > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, y, categorical_feature=[feats - 2, feats - 1])
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": leaves}, ds, num_boost_round=iters)
    backend = jax.default_backend()

    for bucket in buckets:
        reqs = [np.ascontiguousarray(
            X[rng.randint(0, rows, size=bucket)]) for _ in range(16)]
        refs = [bst.predict(r) for r in reqs[:4]]
        for delay in delays:
            TELEMETRY.reset()
            with ServeSession(max_batch=bucket,
                              max_delay_ms=delay) as sess:
                mid = sess.load(bst, model_id=size)
                for r in reqs[:2]:               # compile + warm
                    sess.predict(mid, r)
                lat = []
                t0 = time.perf_counter()
                for i in range(n_requests):
                    r = reqs[i % len(reqs)]
                    t = time.perf_counter()
                    sess.predict(mid, r)
                    lat.append(time.perf_counter() - t)
                wall = time.perf_counter() - t0
                ok = all(np.array_equal(ref, sess.predict(mid, rq))
                         for ref, rq in zip(refs, reqs))
            lat.sort()
            qps = n_requests / max(wall, 1e-9)
            print(RESULT_TAG + json.dumps({
                "config": f"serve-{size}-b{bucket}-d{delay:g}",
                "model": size, "backend": backend,
                "trees": iters, "leaves": leaves, "features": feats,
                "bucket": bucket, "delay_ms": delay,
                "requests": n_requests,
                "qps": round(qps, 2),
                "rows_per_s": round(qps * bucket, 1),
                "p50_s": round(_percentile(lat, 0.50), 6),
                "p99_s": round(_percentile(lat, 0.99), 6),
                "quality_ok": bool(ok),
                "metrics": TELEMETRY.metrics_blob(),
            }), flush=True)


def _child_env():
    sys.path.insert(0, REPO)
    import bench
    if (not os.environ.get("BENCH_SKIP_TPU")) and bench.probe_tpu():
        return dict(os.environ)
    from lightgbm_tpu.utils import cpu_subprocess_env
    return cpu_subprocess_env()


def _run_size(size: str, timeout_s: float, env: dict,
              smoke: bool = False) -> list:
    cmd = [sys.executable, os.path.abspath(__file__), "--child", size]
    if smoke:
        cmd.append("--smoke")
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                              capture_output=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench_serve: {size} timed out ({timeout_s}s)\n")
        return []
    sys.stderr.write(proc.stderr.decode(errors="replace")[-2000:])
    if proc.returncode != 0:
        sys.stderr.write(f"bench_serve: {size} rc={proc.returncode}\n")
        return []
    out = []
    for line in proc.stdout.decode(errors="replace").splitlines():
        if line.startswith(RESULT_TAG):
            out.append(json.loads(line[len(RESULT_TAG):]))
    return out


def _append_trajectory(records: list) -> None:
    """Serve digest lines for tools/bench_gate.py: no training
    ``value``/``unit`` — the gated fields are ``p99_s`` (latency gate)
    and ``quality_ok`` (bit-identity flip gate)."""
    path = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
    with open(path, "a") as fh:
        for r in records:
            fh.write(json.dumps({
                "schema": "lightgbm_tpu.trajectory/v1",
                "ts": round(time.time(), 3),
                "config": r["config"],
                "backend": r.get("backend"),
                "qps": r.get("qps"),
                "rows_per_s": r.get("rows_per_s"),
                "p50_s": r.get("p50_s"),
                "p99_s": r.get("p99_s"),
                "quality_ok": r.get("quality_ok"),
            }) + "\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serve-path latency/QPS grid -> BENCH_SERVE.json")
    ap.add_argument("--smoke", action="store_true",
                    help="one tiny cell, no artifacts (CI liveness leg)")
    ap.add_argument("--gate", action="store_true",
                    help="run tools/bench_gate.py over the trajectory "
                         "after appending")
    args = ap.parse_args(argv)
    env = _child_env()
    if args.smoke:
        recs = _run_size(SMOKE_SIZE[0], SMOKE_SIZE[1][4], env, smoke=True)
        for r in recs:
            print(json.dumps(r if "metrics" not in r
                             else {k: v for k, v in r.items()
                                   if k != "metrics"}), flush=True)
        if not recs or not all(r.get("quality_ok") for r in recs):
            sys.stderr.write("bench_serve: smoke FAILED\n")
            return 1
        print("bench_serve: smoke ok")
        return 0
    records = []
    for size in SIZES:
        records.extend(_run_size(size, SIZES[size][4], env))
    for r in records:
        print(json.dumps({k: v for k, v in r.items() if k != "metrics"}),
              flush=True)
    if not records:
        sys.stderr.write("bench_serve: no records produced\n")
        return 1
    with open(os.path.join(REPO, "BENCH_SERVE.json"), "w") as fh:
        json.dump(records, fh, indent=1)
    _append_trajectory(records)
    if args.gate:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_gate
        return bench_gate.gate(os.path.join(REPO,
                                            "BENCH_TRAJECTORY.jsonl"))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(sys.argv[2], "--smoke" in sys.argv[3:])
        sys.exit(0)
    sys.exit(main())
