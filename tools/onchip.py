"""Unattended on-chip measurement plan (PERF_NOTES §"On-chip plan").

The axon backend has been down for rounds 3-4; the moment it answers,
this driver runs the whole ordered measurement sequence without
supervision and appends everything to ONCHIP_LOG.md:

  0. device probe (cheap; exits 3 when the backend is still down)
  1. strict-grower seg-stats probe at 10.5M rows (scan-waste model)
  2. frontier-grower A/B of the same probe
  3. COMPACT_WASTE sweep (strict grower — the driver default)
  4. kernel microbenches (probe.py micro)
  5. bench.py (the scoreboard number; internally A/Bs impls)

Usage:
    python tools/onchip.py            # run everything
    python tools/onchip.py --if-up    # exit fast when the chip is down
Each step has its own timeout and failures don't stop later steps.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "ONCHIP_LOG.md")
PY = sys.executable


def log(text: str) -> None:
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(LOG, "a") as fh:
        fh.write(f"\n[{stamp}] {text}\n")
    print(f"[{stamp}] {text}", flush=True)


def _tails(stdout, stderr) -> str:
    """Separate stdout/stderr tails: stdout carries the measurements
    (PROBE lines, BENCH JSON) and must never be crowded out by noisy
    stderr."""
    def _s(x):
        if isinstance(x, bytes):
            x = x.decode(errors="replace")
        return x or ""
    return (f"stdout tail:\n```\n{_s(stdout)[-3000:]}\n```\n"
            f"stderr tail:\n```\n{_s(stderr)[-3000:]}\n```")


def run_step(name: str, cmd, timeout_s: int, env_extra=None) -> bool:
    env = dict(os.environ)
    env.update(env_extra or {})
    log(f"## {name}\n    cmd: {' '.join(cmd)}  env+: {env_extra or {}}")
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout_s,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        # keep whatever the child already printed — one-shot chip data
        log(f"{name}: TIMEOUT after {timeout_s}s\n"
            + _tails(e.stdout, e.stderr))
        return False
    dt = time.time() - t0
    log(f"{name}: rc={proc.returncode} in {dt:.0f}s\n"
        + _tails(proc.stdout, proc.stderr))
    return proc.returncode == 0


def chip_up(timeout_s: int = 420) -> bool:
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print(d)")
    try:
        proc = subprocess.run([PY, "-c", code], timeout=timeout_s,
                              capture_output=True, text=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def wait_for_chip(max_wait_s: int = 10800) -> bool:
    """Poll until the backend answers (it flaps: up 03:16-04:04, down
    04:04+ on 2026-07-31).  Returns False after ``max_wait_s``."""
    t0 = time.time()
    while time.time() - t0 < max_wait_s:
        if chip_up():
            return True
        log(f"probe: backend still down after {time.time() - t0:.0f}s; "
            "retrying in 300s")
        time.sleep(300)
    return False


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip():
            log("probe: backend never came up; giving up")
            sys.exit(3)
        log("probe: backend UP — running plan 4b")
    elif not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("probe: backend DOWN; proceeding anyway (no --if-up)")
    else:
        log("probe: backend UP — running plan 4b")

    probe = os.path.join(REPO, "tools", "perf_probe.py")
    probe_cli = os.path.join(REPO, "tools", "probe.py")

    # Plan 4b: chase the ~0.8 s/iter residual both growers share.
    # 1. microbenches incl. the new op-class probes (unpermute scatter vs
    # sort2, score-table gather, per-skipped-grid-step cost)
    run_step("micro 10.5M (4b)", [PY, probe_cli, "micro", "10500000"],
             2400)

    # 2. profiler trace of 2 strict iterations — the op-level breakdown
    # that settles where the residual actually goes
    run_step("trace strict 10.5M", [PY, probe_cli, "trace", "10500000"],
             2700)

    # 3. fewer sorts now that the sort measures ~190ms in context
    run_step("strict WASTE=6 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_COMPACT_WASTE": "6.0"})

    # 4. frontier with the sort-unpermute fix + grid counters
    run_step("frontier stats 10.5M", [PY, probe, "10500000,255,1,4"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier"})

    # 5. frontier, fewer compactions (it scans less per split)
    run_step("frontier WASTE=6 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_COMPACT_WASTE": "6.0"})

    # 6. dynamic-grid lowering check (interpret-green is not
    # lowering-green): one tiny segment+frontier call on the real chip
    dyn_check = (
        "import numpy as np, jax.numpy as jnp\n"
        "from lightgbm_tpu.ops.pallas_histogram import (histogram_segment,"
        " histogram_frontier, pack_channels)\n"
        "rng = np.random.RandomState(0); F, B, rb = 8, 16, 512\n"
        "n = rb * 4\n"
        "bT = jnp.asarray(rng.randint(0, B, (F, n)).astype(np.uint8))\n"
        "w8 = pack_channels(jnp.ones(n), jnp.ones(n), jnp.ones(n))\n"
        "lid = jnp.zeros(n, jnp.int32)\n"
        "o = histogram_segment(bT, w8, lid, jnp.int32(0), jnp.int32(2),"
        " jnp.int32(0), B, rb)\n"
        "print('seg dyn sum', float(o.sum()))\n"
        "bl = jnp.arange(4, dtype=jnp.int32)\n"
        "tg = jnp.zeros(4, jnp.int32)\n"
        "of = histogram_frontier(bT, w8, lid, bl, jnp.int32(4), tg, B, rb)\n"
        "print('frontier dyn sum', float(of.sum()))\n")
    dyn_ok = run_step("dyn-grid lowering check", [PY, "-c", dyn_check],
                      900, {"LIGHTGBM_TPU_DYN_GRID": "1"})

    if dyn_ok:
        # 7. dyn-grid A/B: no bucket ladder, exact grids
        run_step("strict DYN_GRID 10.5M", [PY, probe, "10500000,255,1,2"],
                 2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                        "LIGHTGBM_TPU_DYN_GRID": "1"})
        run_step("frontier DYN_GRID 10.5M",
                 [PY, probe, "10500000,255,1,2"], 2100,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_IMPL": "frontier",
                  "LIGHTGBM_TPU_DYN_GRID": "1"})

    # 8. u8 one-hot compare experiment (the kernel's measured bound is
    # the one-hot build; u8 lanes may vectorize 4x denser)
    run_step("strict ONEHOT=u8 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_ONEHOT_DTYPE": "u8"})

    # 8b. wide-K frontier with compaction effectively off: ~10 full-N
    # rounds/tree and ZERO sorts (the sort term is ~0.7 s/iter at the
    # current default).  K=64 may blow VMEM — K=32 is the fallback probe.
    for k in ("64", "32"):
        run_step(f"frontier K={k} no-compact 10.5M",
                 [PY, probe, "10500000,255,1,2"], 2100,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_IMPL": "frontier",
                  "LIGHTGBM_TPU_FRONTIER_K": k,
                  "LIGHTGBM_TPU_COMPACT_WASTE": "50.0"})

    # 9. scoreboard with the unpermute fix (internally A/Bs impls)
    run_step("bench (4b)", [PY, os.path.join(REPO, "bench.py")], 9000)

    log("plan 4b complete")


if __name__ == "__main__":
    main()
