"""Unattended on-chip measurement plan (PERF_NOTES §"On-chip plan").

The axon backend has been down for rounds 3-4; the moment it answers,
this driver runs the whole ordered measurement sequence without
supervision and appends everything to ONCHIP_LOG.md:

  0. device probe (cheap; exits 3 when the backend is still down)
  1. strict-grower seg-stats probe at 10.5M rows (scan-waste model)
  2. frontier-grower A/B of the same probe
  3. COMPACT_WASTE sweep (strict grower — the driver default)
  4. kernel microbenches (probe.py micro)
  5. bench.py (the scoreboard number; internally A/Bs impls)

Usage:
    python tools/onchip.py            # run everything
    python tools/onchip.py --if-up    # exit fast when the chip is down
Each step has its own timeout and failures don't stop later steps.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "ONCHIP_LOG.md")
PY = sys.executable


def log(text: str) -> None:
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(LOG, "a") as fh:
        fh.write(f"\n[{stamp}] {text}\n")
    print(f"[{stamp}] {text}", flush=True)


def _tails(stdout, stderr) -> str:
    """Separate stdout/stderr tails: stdout carries the measurements
    (PROBE lines, BENCH JSON) and must never be crowded out by noisy
    stderr."""
    def _s(x):
        if isinstance(x, bytes):
            x = x.decode(errors="replace")
        return x or ""
    return (f"stdout tail:\n```\n{_s(stdout)[-3000:]}\n```\n"
            f"stderr tail:\n```\n{_s(stderr)[-3000:]}\n```")


def run_step(name: str, cmd, timeout_s: int, env_extra=None) -> bool:
    env = dict(os.environ)
    env.update(env_extra or {})
    log(f"## {name}\n    cmd: {' '.join(cmd)}  env+: {env_extra or {}}")
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout_s,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        # keep whatever the child already printed — one-shot chip data
        log(f"{name}: TIMEOUT after {timeout_s}s\n"
            + _tails(e.stdout, e.stderr))
        return False
    dt = time.time() - t0
    log(f"{name}: rc={proc.returncode} in {dt:.0f}s\n"
        + _tails(proc.stdout, proc.stderr))
    return proc.returncode == 0


def chip_up(timeout_s: int = 420) -> bool:
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print(d)")
    try:
        proc = subprocess.run([PY, "-c", code], timeout=timeout_s,
                              capture_output=True, text=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    if not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("probe: backend DOWN; proceeding anyway (no --if-up)")
    else:
        log("probe: backend UP — running the measurement plan")

    probe = os.path.join(REPO, "tools", "perf_probe.py")
    probe_cli = os.path.join(REPO, "tools", "probe.py")

    # 1. strict grower, scan-waste counters
    run_step("seg-stats strict 10.5M",
             [PY, probe, "10500000,255,1,4"], 2700,
             {"LIGHTGBM_TPU_SEG_STATS": "1"})

    # 2. frontier A/B
    run_step("seg-stats frontier 10.5M",
             [PY, probe, "10500000,255,1,4"], 2700,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier"})

    # 3. COMPACT_WASTE sweep (short runs)
    for waste in ("1.0", "3.0"):
        run_step(f"COMPACT_WASTE={waste} strict 10.5M",
                 [PY, probe, "10500000,255,1,2"], 2100,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_COMPACT_WASTE": waste})

    # 4. kernel microbenches
    run_step("micro 10.5M", [PY, probe_cli, "micro", "10500000"], 1800)

    # 5. the scoreboard bench (probes + tiers + internal impl A/B)
    run_step("bench run 1 (cold cache)",
             [PY, os.path.join(REPO, "bench.py")], 9000)

    # 6. second bench run: the round-3 open question — does the
    # persistent compilation cache cut warmup below 60 s?
    run_step("bench run 2 (warm cache)",
             [PY, os.path.join(REPO, "bench.py")], 9000)

    log("plan complete — BENCH JSON lines are in the bench steps' "
        "stdout tails; compare warmup between the two runs for the "
        "compile-cache question")


if __name__ == "__main__":
    main()
