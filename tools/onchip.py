"""Unattended on-chip measurement plan (PERF_NOTES §"On-chip plan").

The axon backend has been down for rounds 3-4; the moment it answers,
this driver runs the whole ordered measurement sequence without
supervision and appends everything to ONCHIP_LOG.md:

  0. device probe (cheap; exits 3 when the backend is still down)
  1. strict-grower seg-stats probe at 10.5M rows (scan-waste model)
  2. frontier-grower A/B of the same probe
  3. COMPACT_WASTE sweep (strict grower — the driver default)
  4. kernel microbenches (probe.py micro)
  5. bench.py (the scoreboard number; internally A/Bs impls)

Usage:
    python tools/onchip.py            # run everything
    python tools/onchip.py --if-up    # exit fast when the chip is down
Each step has its own timeout and failures don't stop later steps.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "ONCHIP_LOG.md")
PY = sys.executable


def log(text: str) -> None:
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(LOG, "a") as fh:
        fh.write(f"\n[{stamp}] {text}\n")
    print(f"[{stamp}] {text}", flush=True)


def _tails(stdout, stderr) -> str:
    """Separate stdout/stderr tails: stdout carries the measurements
    (PROBE lines, BENCH JSON) and must never be crowded out by noisy
    stderr."""
    def _s(x):
        if isinstance(x, bytes):
            x = x.decode(errors="replace")
        return x or ""
    return (f"stdout tail:\n```\n{_s(stdout)[-3000:]}\n```\n"
            f"stderr tail:\n```\n{_s(stderr)[-3000:]}\n```")


def run_step(name: str, cmd, timeout_s: int, env_extra=None) -> bool:
    env = dict(os.environ)
    env.update(env_extra or {})
    log(f"## {name}\n    cmd: {' '.join(cmd)}  env+: {env_extra or {}}")
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout_s,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        # keep whatever the child already printed — one-shot chip data
        log(f"{name}: TIMEOUT after {timeout_s}s\n"
            + _tails(e.stdout, e.stderr))
        return False
    dt = time.time() - t0
    log(f"{name}: rc={proc.returncode} in {dt:.0f}s\n"
        + _tails(proc.stdout, proc.stderr))
    return proc.returncode == 0


def chip_up(timeout_s: int = 420) -> bool:
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print(d)")
    try:
        proc = subprocess.run([PY, "-c", code], timeout=timeout_s,
                              capture_output=True, text=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def wait_for_chip(max_wait_s: int = 28800) -> bool:
    """Poll until the backend answers (it flaps: up 03:16-04:04, down
    04:04+ on 2026-07-31).  Returns False after ``max_wait_s``."""
    t0 = time.time()
    while time.time() - t0 < max_wait_s:
        if chip_up():
            return True
        log(f"probe: backend still down after {time.time() - t0:.0f}s; "
            "retrying in 300s")
        time.sleep(300)
    return False


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip():
            log("probe: backend never came up; giving up")
            sys.exit(3)
        log("probe: backend UP — running plan 4b")
    elif not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("probe: backend DOWN; proceeding anyway (no --if-up)")
    else:
        log("probe: backend UP — running plan 4b")

    probe = os.path.join(REPO, "tools", "perf_probe.py")
    probe_cli = os.path.join(REPO, "tools", "probe.py")

    # Plan 4c: measure the post-fix state (windowed route + epoch loops
    # + dyn-grid/WASTE=6 defaults + one-hot-matmul scorer).  Last clean
    # numbers: strict 1.39 (bench, partial fixes), frontier 1.12
    # (WASTE=6, pre-epoch).  Baseline 0.477.
    # 1-2. both growers at current defaults — the headline A/B
    run_step("strict post-fix 10.5M", [PY, probe, "10500000,255,1,3"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1"})
    run_step("frontier post-fix 10.5M", [PY, probe, "10500000,255,1,3"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier"})

    # 2b. bf16 one-hot build: legal 16-bit iota, 2 values/lane — may
    # halve the compare cost that bounds the kernel (u8 failed to lower)
    run_step("frontier ONEHOT=bf16 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_ONEHOT_DTYPE": "bf16"})

    # 3. trace of 2 strict iterations (parser fixed: tsl protobuf) —
    # what is the bound NOW?
    run_step("trace strict 10.5M", [PY, probe_cli, "trace", "10500000"],
             2700)

    # 4. finer blocks: granularity floor under scanned N-eq now that
    # skipped steps are gone (PERF_NOTES "next levers" #2)
    run_step("frontier ROW_CHUNK=8192 10.5M",
             [PY, probe, "10500000,255,1,2"], 2100,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_ROW_CHUNK": "8192"})
    run_step("strict ROW_CHUNK=8192 10.5M",
             [PY, probe, "10500000,255,1,2"], 2100,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_ROW_CHUNK": "8192"})

    # 5. push the sort trade further now that scans are all that's left
    run_step("frontier WASTE=10 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_COMPACT_WASTE": "10.0"})
    run_step("strict WASTE=10 10.5M", [PY, probe, "10500000,255,1,2"],
             2100, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_COMPACT_WASTE": "10.0"})

    # 6. scoreboard (internally A/Bs impls with the quality guard)
    run_step("bench (4c)", [PY, os.path.join(REPO, "bench.py")], 9000)

    log("plan 4c complete")


if __name__ == "__main__":
    main()
