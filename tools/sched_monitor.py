"""Live/post-hoc terminal summary of a scheduler-health JSONL stream.

The stream is the append-only file a multi-tenant Scheduler writes for
``sched_health_out=`` (see lightgbm_tpu/sched/scheduler.py, schema
``lightgbm_tpu.health/v1``): ``sched_start``, ``sched_admit``
decisions (admitted/queued/rejected with working-set estimates),
per-quantum ``sched_slice`` records (job, slice index, iteration
progress, wall/device seconds, latest metrics), ``sched_preempt_job``
events, per-tenant ``job_done`` terminals, and a closing
``sched_summary`` with fairness / queue-latency accounting.

One-shot mode renders the stream as it stands — running OR closed.
``--follow`` tails the file exactly like run_monitor.py (byte-offset
incremental reads), re-rendering every ``--interval`` seconds until
the ``sched_summary`` record lands (exit 0) or ``--timeout`` seconds
pass without one (exit 3).  Staleness detection reuses
streamtail.stream_stale: an unfinished stream whose file has no new
line within 2x its own median inter-record gap gets a LOUD flag — the
signature of a wedged tenant holding the whole scheduler loop.

Usage:
  python tools/sched_monitor.py jobs.sched.health.jsonl
  python tools/sched_monitor.py jobs.sched.health.jsonl --follow
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import streamtail  # noqa: E402  (shared tail loop)
from streamtail import (  # noqa: E402  (shared staleness detector)
    STALL_GAP_FACTOR, stream_stale)

_stream_age_s = streamtail.stream_age_s


class SchedStreamState(streamtail.JsonlFolder):
    """Folded view of a sched health stream; feed()
    (streamtail.JsonlFolder) accepts raw JSONL bytes incrementally and
    tolerates a torn trailing line."""

    TAIL_KEEP = 64

    def __init__(self):
        super().__init__()
        self.start = None
        self.admits = []
        self.slices = 0                 # sched_slice records seen
        self.jobs = {}                  # name -> last slice/done view
        self.preempts = []
        self.done = []                  # job_done records in order
        self.recent = []                # (t, kind, job) tail

    def on_record(self, rec: dict) -> None:
        kind = rec.get("kind")
        self.recent.append((rec.get("t"), kind, rec.get("job")))
        del self.recent[: -self.TAIL_KEEP]
        if kind == "sched_start":
            self.start = rec
        elif kind == "sched_admit":
            self.admits.append(rec)
        elif kind == "sched_slice":
            self.slices += 1
            view = self.jobs.setdefault(rec.get("job", "?"), {})
            view.update(rec)
        elif kind == "sched_preempt_job":
            self.preempts.append(rec)
        elif kind == "job_done":
            self.done.append(rec)
            view = self.jobs.setdefault(rec.get("job", "?"), {})
            view.update(rec)
            view["terminal"] = ("failed" if rec.get("failed")
                                else "done")
        elif kind == "sched_summary":
            self.summary = rec


# streamtail's staleness helpers expect a state with .recent tuples
# carrying a leading timestamp and a .summary attribute —
# SchedStreamState satisfies both, so stream_stale works unchanged.


def render(state: SchedStreamState, path: str,
           age_s=None) -> str:
    lines = []
    if state.summary is not None:
        status = "closed"
    elif state.start is not None or state.records:
        status = "running"
    else:
        status = "empty"
    schema = (state.start or {}).get("schema", "?")
    lines.append(f"sched-health {os.path.basename(path)} [{status}] "
                 f"schema={schema} records={state.records}")
    if state.start:
        budget = state.start.get("hbm_budget_bytes")
        lines.append(
            f"  scheduler: policy={state.start.get('policy', '?')} "
            f"quantum={state.start.get('quantum_chunks', '?')} chunks "
            f"max_jobs={state.start.get('max_jobs', '?')} "
            f"budget={budget if budget is not None else 'n/a'}")
    if state.admits:
        by = {}
        for a in state.admits:
            by[a.get("decision", "?")] = by.get(a.get("decision", "?"),
                                                0) + 1
        parts = [f"{k}={v}" for k, v in sorted(by.items())]
        lines.append("  admissions: " + " ".join(parts))
        for a in state.admits:
            if a.get("decision") == "rejected":
                lines.append(f"    REJECTED {a.get('job', '?')}: "
                             f"{a.get('detail', '')[:80]}")
    if state.jobs:
        lines.append(f"  jobs ({len(state.jobs)}), "
                     f"{state.slices} slice(s) streamed:")
        for name in sorted(state.jobs):
            v = state.jobs[name]
            term = v.get("terminal")
            if term == "failed":
                lines.append(f"    {name}: FAILED at iteration "
                             f"{v.get('iter', '?')} — "
                             f"{v.get('error', '?')[:70]}")
                continue
            it, total = v.get("iter", 0), v.get("total")
            line = f"    {name}: iter {it}"
            if total:
                line += f"/{int(total)} ({100.0 * it / total:.0f}%)"
            if term == "done":
                line += (f" [done] {v.get('slices', '?')} slices, "
                         f"queue wait {v.get('queue_wait_s', 0):.2f}s")
            else:
                line += (f" [running] slice {v.get('slice', '?')}, "
                         f"device {v.get('device_s', 0):.3f}s")
            metrics = v.get("metrics")
            if metrics:
                top = sorted(metrics.items())[:2]
                line += " " + " ".join(f"{k}={val:g}"
                                       for k, val in top)
            lines.append(line)
    else:
        lines.append("  no slice records yet")
    if state.preempts:
        last = state.preempts[-1]
        lines.append(f"  preemptions: {len(state.preempts)}, last "
                     f"{last.get('job', '?')}@{last.get('iter', '?')} "
                     f"({last.get('reason', '?')})")
    hit = stream_stale(state, age_s)
    if hit is not None:
        lines.append(
            f"  !! STALE: no new record for {hit[0]:.1f}s, over "
            f"{STALL_GAP_FACTOR:g}x the stream's median inter-record "
            f"gap {hit[1]:.2f}s — a tenant slice is likely wedged")
    if state.summary is not None:
        s = state.summary
        fairness = s.get("fairness_index")
        lines.append(
            f"  summary: {s.get('done', '?')} done / "
            f"{s.get('failed', 0)} failed over {s.get('slices', '?')} "
            f"slices, fairness "
            f"{fairness if fairness is not None else 'n/a'}, "
            f"cross-job cache hits "
            f"{s.get('cross_job_cache_hits', 0)}, "
            f"wall {s.get('wall_s', 0):.2f}s")
    return "\n".join(lines)


def follow(path, interval, timeout, out=sys.stdout):
    """Tail the stream until sched_summary lands.  Returns 0 on a
    closed stream, 2 when the file never appears, 3 on timeout."""
    return streamtail.follow_stream(
        path, SchedStreamState,
        lambda state, p: render(state, p, age_s=_stream_age_s(p)),
        interval, timeout, out,
        name="sched_monitor",
        timeout_msg="sched_monitor: timeout waiting for the "
                    "sched_summary record (scheduler still alive?)\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="summarize a lightgbm_tpu scheduler-health JSONL "
                    "stream, live or post-hoc")
    ap.add_argument("path")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing until sched_summary lands")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll period in seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="--follow gives up after this many seconds "
                         "(0 = wait forever)")
    args = ap.parse_args(argv)
    if args.follow:
        return follow(args.path, max(0.05, args.interval), args.timeout)
    if not os.path.exists(args.path):
        print(f"sched_monitor: no such stream: {args.path}")
        return 2
    state = SchedStreamState()
    with open(args.path, "rb") as fh:
        state.feed(fh.read())
    print(render(state, args.path, age_s=_stream_age_s(args.path)))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)
