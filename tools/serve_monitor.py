"""Live/post-hoc terminal summary of a serve-health JSONL stream.

The stream is the append-only file a ServeSession writes for
``serve_health_out=`` / ``LIGHTGBM_TPU_SERVE_HEALTH_JSONL`` (see
lightgbm_tpu/serve/health.py, schema ``lightgbm_tpu.health/v1``):
``serve_start``, periodic ``serve_window`` records (QPS, stage and
end-to-end p50/p99, coalesce fill ratio, pad ratio, queue depth),
``serve_admit`` decisions, ``serve_drift`` records (per-model PSI /
score-JS vs the training baseline when the session runs with
``drift_detect=true`` — a model at or over the gate threshold renders
the loud ``!! DRIFT`` banner), ``serve_fault`` events, and a terminal
``serve_summary``.

One-shot mode renders the stream as it stands — serving OR closed.
``--follow`` tails the file exactly like run_monitor.py (byte-offset
incremental reads), re-rendering every ``--interval`` seconds until the
``serve_summary`` record lands (exit 0) or ``--timeout`` seconds pass
without one (exit 3).

Usage:
  python tools/serve_monitor.py svc.serve.health.jsonl
  python tools/serve_monitor.py svc.serve.health.jsonl --follow
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import streamtail  # noqa: E402  (shared tail loop)


class ServeStreamState(streamtail.JsonlFolder):
    """Folded view of a serve health stream; feed()
    (streamtail.JsonlFolder) accepts raw JSONL bytes incrementally and
    tolerates a torn trailing line."""

    WINDOW_KEEP = 12

    def __init__(self):
        super().__init__()
        self.start = None
        self.windows = []               # newest WINDOW_KEEP kept
        self.admits = []
        self.faults = []
        self.drifts = {}                # model_id -> newest serve_drift
        self.total_requests = 0
        self.total_rows = 0

    def on_record(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "serve_start":
            self.start = rec
        elif kind == "serve_window":
            self.total_requests += rec.get("requests", 0)
            self.total_rows += rec.get("rows", 0)
            self.windows.append(rec)
            del self.windows[: -self.WINDOW_KEEP]
        elif kind == "serve_drift":
            self.drifts[rec.get("model", "?")] = rec
        elif kind == "serve_admit":
            self.admits.append(rec)
        elif kind == "serve_fault":
            self.faults.append(rec)
        elif kind == "serve_summary":
            self.summary = rec


def _ms(v):
    return f"{v * 1e3:.2f}ms" if isinstance(v, (int, float)) else "?"


def render(state: ServeStreamState, path: str) -> str:
    lines = []
    if state.summary is not None:
        status = "closed"
    elif state.start is not None:
        status = "serving"
    else:
        status = "empty"
    schema = (state.start or {}).get("schema", "?")
    lines.append(f"serve-health {os.path.basename(path)} [{status}] "
                 f"schema={schema} records={state.records}")
    if state.start:
        lines.append(f"  session: pid={state.start.get('pid', '?')} "
                     f"max_batch={state.start.get('max_batch', '?')} "
                     f"max_delay_ms={state.start.get('max_delay_ms', '?')}"
                     f" window_s={state.start.get('window_s', '?')}")
    live = [w for w in state.windows if w.get("requests")]
    if live:
        w = live[-1]
        line = (f"  window@{w.get('t', 0):.1f}s: {w.get('qps', 0):g} qps"
                f" ({w.get('requests', 0)} req, {w.get('rows', 0)} rows)"
                f" e2e p50={_ms(w.get('p50_s'))} p99={_ms(w.get('p99_s'))}")
        lines.append(line)
        parts = []
        if w.get("rows_per_batch") is not None:
            parts.append(f"rows/batch={w['rows_per_batch']:g}")
        if w.get("fill_ratio") is not None:
            parts.append(f"fill={w['fill_ratio']:.0%}")
        if w.get("pad_ratio") is not None:
            parts.append(f"pad={w['pad_ratio']:.0%}")
        if w.get("queue_depth") is not None:
            parts.append(f"depth={w['queue_depth']}")
        if w.get("coalesce_slack_ms") is not None:
            parts.append(f"slack={w['coalesce_slack_ms']:g}ms")
        if parts:
            lines.append("  coalescing: " + " ".join(parts))
        stages = w.get("stages") or {}
        if stages:
            lines.append("  stages: " + " ".join(
                f"{name}[{_ms(d.get('p50_s'))}/{_ms(d.get('p99_s'))}]"
                for name, d in stages.items()))
        if w.get("models"):
            lines.append("  models: " + " ".join(
                f"{m}={r}" for m, r in sorted(w["models"].items())))
    elif state.windows:
        lines.append(f"  idle: last {len(state.windows)} window(s) "
                     f"served no requests")
    else:
        lines.append("  no windows yet")
    for mid, d in sorted(state.drifts.items()):
        top = " ".join(f"{e.get('feature', '?')}={e.get('psi', 0):.3f}"
                       for e in (d.get("top") or [])[:3])
        js = d.get("score_js")
        lines.append(f"  drift {mid}: psi_max={d.get('psi_max', 0):.3f}"
                     + (f" score_js={js:.3f}" if js is not None else "")
                     + f" rows={d.get('rows', '?')}"
                     + (f"  [{top}]" if top else ""))
    drifted = sorted(m for m, d in state.drifts.items() if d.get("drifted"))
    if drifted:
        d = state.drifts[drifted[0]]
        lines.append(f"  !! DRIFT: {', '.join(drifted)} at/over "
                     f"psi threshold {d.get('threshold', '?')} — "
                     f"refit trigger armed (DriftGate.drifted)")
    if state.total_requests:
        lines.append(f"  lifetime: {state.total_requests} requests / "
                     f"{state.total_rows} rows across the stream")
    if state.admits:
        last = state.admits[-1].get("detail", "")
        lines.append(f"  admissions: {len(state.admits)}, last: "
                     f"{last[:90]}")
    if state.faults:
        lines.append(f"  FAULTS: {len(state.faults)}, last: "
                     f"{state.faults[-1].get('error', '?')}")
    if state.summary is not None:
        s = state.summary
        lines.append(f"  summary: {s.get('requests', '?')} requests, "
                     f"{s.get('batches', '?')} batches, "
                     f"{s.get('faults', 0)} fault(s), "
                     f"{s.get('pending_failed', 0)} failed at close")
    return "\n".join(lines)


def follow(path, interval, timeout, out=sys.stdout):
    """Tail the stream until serve_summary lands.  Returns 0 on a
    closed stream, 2 when the file never appears, 3 on timeout."""
    return streamtail.follow_stream(
        path, ServeStreamState, render, interval, timeout, out,
        name="serve_monitor",
        timeout_msg="serve_monitor: timeout waiting for the "
                    "serve_summary record (session still alive?)\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="summarize a lightgbm_tpu serve-health JSONL "
                    "stream, live or post-hoc")
    ap.add_argument("path")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing until serve_summary lands")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll period in seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="--follow gives up after this many seconds "
                         "(0 = wait forever)")
    args = ap.parse_args(argv)
    if args.follow:
        return follow(args.path, max(0.05, args.interval), args.timeout)
    if not os.path.exists(args.path):
        print(f"serve_monitor: no such stream: {args.path}")
        return 2
    state = ServeStreamState()
    with open(args.path, "rb") as fh:
        state.feed(fh.read())
    print(render(state, args.path))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)
