#!/usr/bin/env bash
# Fault-injection matrix: run the fault-tolerance suite once per chunked
# dispatch mode (tpu_boost_chunk 1 = per-iteration, 4 = fused chunks), each
# in a clean process so degraded chunk caps / armed sites cannot leak
# between configurations.
#
# Each chunk mode runs twice: once for the core sites (chunk/oom,
# grad/nonfinite, snapshot/io, train/kill, collective/allgather) and once
# for the out-of-core sites (oocore/h2d, oocore/admit), so the FULL
# memory-pressure escalation ladder — halve -> halve -> spill -> give-up
# (docs/ROBUSTNESS.md) — is exercised in CI-shaped form with per-group
# process isolation.
#
# A third pass runs the multi-host suite (tests/test_distributed.py,
# including its slow-marked 2-process fleets) over the collective/* and
# dist/* sites at world=2: hardened allgather retries, barrier timeouts
# naming the dead rank, and the dist/preempt drain -> synchronized
# snapshot -> bit-exact resume cycle.
#
# A fourth pass runs the serving suite (tests/test_serve.py) over the
# serve/compile and serve/enqueue sites: an armed site must surface as a
# NAMED give-up on the affected request futures — never a hang — and the
# queue must keep serving afterwards.
#
# A serve-swap pass runs the hot-swap/overload suite
# (tests/test_serve_swap.py) over the serve/swap, serve/shed, serve/oom
# and serve/refit sites: an armed flip fault must reject the swap and
# leave the OLD model serving bit-identically, a forced shed must
# surface as a named ServeOverloadError, an injected RESOURCE_EXHAUSTED
# must be retried at half batch with bit-identical replies, and a
# faulted refit attempt must leave the refit loop alive.
#
# A fifth pass runs the scheduler suite (tests/test_sched.py) over the
# sched/slice and sched/snapshot sites: a fault in one tenant's slice or
# preemption snapshot must retry once then fail THAT JOB ONLY — the
# scheduler and every sibling tenant run to completion.
#
# A sixth pass runs the fleet observability suite (tests/test_fleet_obs.py)
# over the dist/slow delay site at world=2: the armed rank must surface as
# the NAMED straggler in the dist_window health records and the wait/work
# split must account for the injected delay — a fault that slows a rank
# is attributed, never silently absorbed.
#
#   tools/fault_matrix.sh [extra pytest args...]
#
# FAULT_MATRIX_CHUNK is deliberately NOT LIGHTGBM_TPU_-prefixed: the test
# conftest scrubs that namespace at import, and this knob must survive to
# narrow the chunk parametrization inside tests/test_faults.py.
set -euo pipefail
cd "$(dirname "$0")/.."

status=0
for chunk in 1 4; do
  for group in core oocore; do
    if [ "${group}" = "oocore" ]; then
      kexpr="oocore"
    else
      kexpr="not oocore"
    fi
    echo "=== fault matrix: tpu_boost_chunk=${chunk} sites=${group} ==="
    if ! FAULT_MATRIX_CHUNK="${chunk}" JAX_PLATFORMS=cpu \
        python -m pytest tests/test_faults.py -q -p no:cacheprovider \
        -k "${kexpr}" "$@"; then
      status=1
    fi
  done
done

echo "=== fault matrix: multi-host (world=2) sites=collective/*,dist/* ==="
if ! JAX_PLATFORMS=cpu \
    python -m pytest tests/test_distributed.py -q -p no:cacheprovider \
    "$@"; then
  status=1
fi

echo "=== fault matrix: serve sites=serve/compile,serve/enqueue ==="
if ! JAX_PLATFORMS=cpu \
    python -m pytest tests/test_serve.py -q -p no:cacheprovider \
    -k "fault" "$@"; then
  status=1
fi

echo "=== fault matrix: serve-swap sites=serve/swap,serve/shed,serve/oom,serve/refit ==="
if ! JAX_PLATFORMS=cpu \
    python -m pytest tests/test_serve_swap.py -q -p no:cacheprovider \
    -k "fault or shed or oom or wedged" "$@"; then
  status=1
fi

echo "=== fault matrix: fleet sites=dist/slow (world=2) ==="
if ! JAX_PLATFORMS=cpu \
    python -m pytest tests/test_fleet_obs.py -q -p no:cacheprovider \
    "$@"; then
  status=1
fi

echo "=== fault matrix: sched sites=sched/slice,sched/snapshot ==="
if ! JAX_PLATFORMS=cpu \
    python -m pytest tests/test_sched.py -q -p no:cacheprovider \
    -k "fault" "$@"; then
  status=1
fi
exit ${status}
