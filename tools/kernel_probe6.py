"""Chained probes of remaining per-iteration suspects at 1M rows, plus
AOT compile-stage timing of the segment grower."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")
N = 1_048_576
F, B = 28, 64


def chain_time(step, state, iters=20, label=""):
    state = step(*state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state = step(*state)
    jax.block_until_ready(state)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label}: {dt*1e3:.2f} ms")
    return dt


def main():
    rng = np.random.RandomState(0)
    lid = jnp.asarray(rng.randint(0, 255, size=N).astype(np.int32))
    order = jnp.asarray(rng.permutation(N).astype(np.int32))

    # 1. the final inverse-permute scatter
    @jax.jit
    def inv_scatter(lid, order):
        out = jnp.zeros(N, jnp.int32).at[order].set(lid)
        return out, order

    chain_time(inv_scatter, (lid, order), iters=10,
               label="scatter zeros.at[order].set(lid) 1M")

    # 2. 254 sequential routing steps in one fori_loop (no hist, no scan)
    binsT = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.uint8))

    @jax.jit
    def route_loop(lid, binsT):
        def body(step, lid):
            f = step % F
            fcol = lax.dynamic_slice_in_dim(binsT, f, 1, axis=0)[0]
            go_left = fcol.astype(jnp.int32) <= (step % 31)
            in_leaf = lid == (step % 17)
            return jnp.where(in_leaf & ~go_left, step + 300, lid)
        return lax.fori_loop(0, 254, body, lid), binsT

    chain_time(route_loop, (lid, binsT), iters=5,
               label="254x routing steps (fori_loop)")

    # 3. 254 sequential best_split pair-scans on tiny hists
    from lightgbm_tpu.ops.split import (FeatureMeta, SplitParams, best_split)
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    sp = SplitParams(has_cat=False)
    fmask = jnp.ones(F, jnp.float32)

    @jax.jit
    def scan_loop(hist0):
        def body(step, carry):
            hist, acc = carry
            infos, gains = jax.vmap(
                lambda h: best_split(h, jnp.float32(100.0),
                                     jnp.float32(200.0), jnp.float32(5e5),
                                     fmeta, sp, fmask)
            )(hist), None
            g = infos.gain.sum()
            return (hist * (1.0 + 1e-9 * g), acc + g)
        return lax.fori_loop(0, 254, body, (hist0, jnp.float32(0.0)))

    hist0 = jnp.asarray(np.abs(rng.normal(size=(2, F, B, 3))
                               ).astype(np.float32)) * 10
    chain_time(lambda h, a: scan_loop(h), (hist0, 0), iters=5,
               label="254x vmapped pair best_split (has_cat=False)")

    sp_cat = SplitParams(has_cat=True)

    @jax.jit
    def scan_loop_cat(hist0):
        def body(step, carry):
            hist, acc = carry
            infos, _ = jax.vmap(
                lambda h: best_split(h, jnp.float32(100.0),
                                     jnp.float32(200.0), jnp.float32(5e5),
                                     fmeta, sp_cat, fmask)
            )(hist), None
            g = infos.gain.sum()
            return (hist * (1.0 + 1e-9 * g), acc + g)
        return lax.fori_loop(0, 254, body, (hist0, jnp.float32(0.0)))

    chain_time(lambda h, a: scan_loop_cat(h), (hist0, 0), iters=5,
               label="254x vmapped pair best_split (has_cat=True)")

    # 4. 4x compaction sort at 1M (12-word payload)
    words = [jnp.asarray(rng.randint(-2**31, 2**31 - 1, size=N,
                                     dtype=np.int64).astype(np.int32))
             for _ in range(12)]

    @jax.jit
    def four_sorts(lid, *pay):
        for _ in range(4):
            out = lax.sort((lid,) + pay, num_keys=1, is_stable=True)
            lid, pay = out[1], out[2:] + (out[0],)
        return (lid,) + pay

    chain_time(four_sorts, (lid, *words), iters=5, label="4x 12-word sort 1M")

    # 5. 254 segment-kernel launches with ~1.5-block intervals, rb=32768
    from lightgbm_tpu.ops.pallas_histogram import (histogram_segment,
                                                   pack_channels)
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    w8 = pack_channels(g, g * g + 0.5, jnp.ones(N, jnp.float32))
    for rb in (8192, 32768):
        nblk = N // rb

        @jax.jit
        def seg_loop(w8, lid):
            def body(step, acc):
                lo = step % (nblk - 2)
                out = histogram_segment(binsT, w8, lid, lo, 2,
                                        step % 255, B, rb)
                return acc + out[0, 0, 0]
            return lax.fori_loop(0, 254, body, jnp.float32(0.0)), lid

        chain_time(seg_loop, (w8, lid), iters=3,
                   label=f"254x segment launches 2-block intervals rb={rb}")

    # 6. AOT compile-stage timing of the grower
    from lightgbm_tpu.models.grower import GrowerParams
    from lightgbm_tpu.models.grower_seg import make_grow_tree_segment
    from lightgbm_tpu.ops.split import SplitParams as SP
    params = GrowerParams(num_leaves=255, hist_backend="pallas",
                          split=SP(min_sum_hessian_in_leaf=100.0,
                                   has_cat=False))
    grow = make_grow_tree_segment(B, params, 8192)
    member = jnp.ones(N, jnp.float32)
    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()
    lowered = grow.lower(binsT, g, g, member, fmeta, fmask, key)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    print(f"grower trace/lower: {t1-t0:.1f}s   compile: {t2-t1:.1f}s")


main()
